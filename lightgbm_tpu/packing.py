"""Sub-byte bin-matrix packing: the nibble (4-bit) + crumb (2-bit)
storage layouts.

``max_bin <= 16`` means every bin index of a feature group fits in 4
bits, so the HBM-resident ``(N, G)`` uint8 bin matrix wastes half its
bytes — and the bandwidth-bound histogram kernels read twice the HBM
they need (the LiteMORT compact-binning lever, PAPERS.md arxiv
2001.09419, on top of the GPU-histogram bandwidth analysis, arxiv
1706.08359).  ``max_bin <= 4`` tightens that to a CRUMB: four bin
indices per byte, a 4x read-stream cut.  This module is the ONE home
for the packed layout every layer shares: host-side construction
(dataset.py), the binary/shard caches (dataset_io.py,
sharded/cache.py), the quality profile's bincounts
(quality/profile.py), and the static layout parameters the device
kernels unpack by (ops/histogram.py, ops/partition.py,
ops/predict.py).

Layout — **three sections: crumb, nibble, byte**:

* groups are ordered NARROWEST-FIRST at construction
  (``Dataset._build_groups``): the first ``C`` groups each have
  ``num_bin <= 4`` (crumb groups), groups ``C..P`` have
  ``num_bin <= 16`` (nibble groups), the remaining ``G - P`` are
  wide;
* storage byte ``j < ceil(C/4)`` carries groups ``4j .. 4j+3`` in
  its four crumbs (group ``4j + k`` at bit ``2k``);
* nibble bytes follow: byte ``ceil(C/4) + j`` carries group
  ``C + 2j`` in its LOW nibble and ``C + 2j + 1`` in its HIGH
  nibble (the interleave keeps bundle-adjacent groups inside one
  byte);
* wide groups follow one byte each.

So storage column arithmetic is pure and static — ``byte_of(g)`` /
``shift_of(g)`` below — which is what lets the Pallas kernels unpack
crumbs and nibbles in-register with static shifts instead of carrying
an indirection table.  The full section geometry travels through the
device kernels as ONE static int, the **pack spec**
(``pack_spec(P, C) = P | C << 16``): every kernel's existing
``packed_groups`` static argument carries it unchanged, and a
crumb-free spec is numerically equal to the legacy plain-``P`` value
so every pre-crumb lowering (and its compiled-cache key) is
bit-preserved.

Modes (``Config.bin_packing``):

* ``8bit`` (default): no packed section — the legacy one-byte-per-
  group matrix, bit-compatible with every existing cache;
* ``4bit``: requires ``max_bin <= 16`` (config-level hard error).  A
  single feature too wide for a nibble is a loud construction error
  naming the group; a wide multi-feature EFB bundle splits out into
  the byte-wide section with a warning ("EFB-aware group re-packing"
  — the bundle keeps its 8-bit-identical membership and moves to the
  wide section, because re-forming bundles at nibble width was
  measured to break byte-exact tree parity: a different bundling
  reconstructs default-bin mass through a different FixHistogram
  subtraction order, f32-ulp different from direct accumulation).
  Never emits a crumb section — a 4bit matrix stays byte-for-byte
  what r18 shipped;
* ``2bit``: requires ``max_bin <= 4`` (config-level hard error), same
  strictness shape as 4bit one tier down: a single feature too wide
  for a crumb is a hard error, a too-wide EFB bundle warns and falls
  back to the nibble (or byte) section;
* ``auto``: adaptive precision — crumb-narrow groups pack four per
  byte, nibble-narrow groups two per byte, wide groups stay
  byte-wide (the three-section layout).  Mixed-width datasets get
  exactly the savings their narrow features earn.

Trees are byte-identical across modes: packing changes the STORAGE of
bin indices, never their values, bundling is identical in every mode,
and the grower/partition/split layers stay bin-index-native (pinned
by tests/test_compact_bins.py on the interpret seam).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .utils.log import Log

#: bins-per-group bound for a nibble-packed group
NIBBLE_MAX_BIN = 16

#: bins-per-group bound for a crumb-packed (2-bit) group
CRUMB_MAX_BIN = 4

_MODES = ("auto", "8bit", "4bit", "2bit")


def resolve_bin_packing(config) -> str:
    """Normalize ``Config.bin_packing`` to one of
    ``auto|8bit|4bit|2bit`` (``None`` config — e.g. legacy cache
    restore — resolves 8bit)."""
    if config is None:
        return "8bit"
    spec = str(config.bin_packing).lower() if hasattr(config,
                                                      "bin_packing") \
        else "8bit"
    if spec not in _MODES:
        Log.warning(f"unknown bin_packing={spec!r}; using '8bit'")
        return "8bit"
    return spec


# ---------------------------------------------------------------------------
# the static pack spec: both section counts in one int.  A crumb-free
# spec equals the plain packed-group count, so every legacy call site
# (and every compiled-function cache key) is numerically unchanged.
# ---------------------------------------------------------------------------
def pack_spec(packed_groups: int, crumb_groups: int = 0) -> int:
    """Encode (P total sub-byte groups, C crumb groups) as one static
    int.  ``crumb_groups == 0`` round-trips to plain ``packed_groups``."""
    return int(packed_groups) | (int(crumb_groups) << 16)


def spec_packed(spec: int) -> int:
    """P: total sub-byte (crumb + nibble) group count of a spec."""
    return int(spec) & 0xFFFF


def spec_crumb(spec: int) -> int:
    """C: crumb (2-bit) group count of a spec (0 for legacy specs)."""
    return int(spec) >> 16


def packed_bytes(spec: int) -> int:
    """Storage bytes of the packed section: ``ceil(C/4)`` crumb bytes
    + ``ceil((P-C)/2)`` nibble bytes.  Accepts a plain group count
    (crumb-free spec) and then matches the legacy two-per-byte math."""
    P, C = spec_packed(spec), spec_crumb(spec)
    return (C + 3) // 4 + (P - C + 1) // 2


def storage_cols(num_groups: int, spec: int) -> int:
    """Total storage byte columns for ``num_groups`` logical groups of
    which the first ``spec_packed(spec)`` are sub-byte packed."""
    return packed_bytes(spec) + (num_groups - spec_packed(spec))


def logical_groups(cols: int, spec: int) -> int:
    """Inverse of :func:`storage_cols` — logical G from storage width."""
    return cols - packed_bytes(spec) + spec_packed(spec)


class BinLayout:
    """Resolved packing layout of one dataset's bin matrix.

    A dataset whose matrix has NO packed section carries
    ``bin_layout = None`` instead (the storage is then the plain
    logical ``(N, G)`` matrix and every consumer takes its legacy
    path untouched)."""

    __slots__ = ("mode", "num_groups", "packed_groups", "crumb_groups")

    def __init__(self, mode: str, num_groups: int, packed_groups: int,
                 crumb_groups: int = 0):
        if not (0 < packed_groups <= num_groups):
            raise ValueError(
                f"BinLayout needs 0 < packed_groups ({packed_groups}) "
                f"<= num_groups ({num_groups}); use bin_layout=None "
                "for an unpacked matrix")
        if not (0 <= crumb_groups <= packed_groups):
            raise ValueError(
                f"BinLayout needs 0 <= crumb_groups ({crumb_groups}) "
                f"<= packed_groups ({packed_groups})")
        self.mode = mode
        self.num_groups = int(num_groups)
        self.packed_groups = int(packed_groups)
        self.crumb_groups = int(crumb_groups)

    # ------------------------------------------------------------------
    @property
    def device_spec(self) -> int:
        """The static pack spec the device kernels carry (equals the
        plain ``packed_groups`` count when the layout has no crumbs)."""
        return pack_spec(self.packed_groups, self.crumb_groups)

    @property
    def crumb_bytes(self) -> int:
        return (self.crumb_groups + 3) // 4

    @property
    def packed_bytes(self) -> int:
        return packed_bytes(self.device_spec)

    @property
    def cols(self) -> int:
        return storage_cols(self.num_groups, self.device_spec)

    def byte_of(self, g: int) -> int:
        if g < self.crumb_groups:
            return g // 4
        if g < self.packed_groups:
            return self.crumb_bytes + (g - self.crumb_groups) // 2
        return self.packed_bytes + (g - self.packed_groups)

    def shift_of(self, g: int) -> int:
        if g < self.crumb_groups:
            return 2 * (g % 4)
        if g < self.packed_groups:
            return 4 * ((g - self.crumb_groups) % 2)
        return 0

    def width_mask(self, g: int) -> int:
        if g < self.crumb_groups:
            return 0x3
        return 0xF if g < self.packed_groups else 0xFF

    def __repr__(self):
        return (f"BinLayout({self.mode}, groups={self.num_groups}, "
                f"packed={self.packed_groups}, "
                f"crumb={self.crumb_groups}, cols={self.cols})")

    # ------------------------------------------------------------------
    def to_state(self) -> dict:
        """Cache-header form (binary cache v3/v4 / shard manifest)."""
        state = {"mode": self.mode, "num_groups": int(self.num_groups),
                 "packed_groups": int(self.packed_groups)}
        if self.crumb_groups:
            # only crumb-carrying layouts grow the key: a crumb-free
            # state dict stays byte-identical to what r18 caches hold
            # (shard manifests compare layout states by dict equality)
            state["crumb_groups"] = int(self.crumb_groups)
        return state

    @classmethod
    def from_state(cls, state: Optional[dict]) -> Optional["BinLayout"]:
        if not state or not int(state.get("packed_groups", 0)):
            return None
        return cls(str(state.get("mode", "auto")),
                   int(state["num_groups"]), int(state["packed_groups"]),
                   int(state.get("crumb_groups", 0)))

    # ------------------------------------------------------------------
    # host-side pack / unpack (vectorized numpy; the native
    # ``ltpu_pack_nibbles`` kernel takes the nibble-only pack when
    # available — it predates crumbs, so a crumb section forces numpy)
    # ------------------------------------------------------------------
    def pack_rows(self, logical: np.ndarray, out: Optional[np.ndarray]
                  = None, lib=None) -> np.ndarray:
        """(n, G) logical uint8 -> (n, cols) storage.  ``out`` writes in
        place (the construction pipeline packs chunk scratch straight
        into the resident storage matrix)."""
        logical = np.ascontiguousarray(logical, dtype=np.uint8)
        n = logical.shape[0]
        if logical.shape[1] != self.num_groups:
            raise ValueError(f"pack_rows expects {self.num_groups} "
                             f"group columns, got {logical.shape[1]}")
        if out is None:
            out = np.empty((n, self.cols), dtype=np.uint8)
        P, C = self.packed_groups, self.crumb_groups
        Cb, Pb = self.crumb_bytes, self.packed_bytes
        if (C == 0 and lib is not None and n
                and _native_pack(lib, logical, P, out)):
            return out
        # crumb section: group 4j+k lands at bit 2k of byte j.  The
        # plane-0 assignment zeroes the upper bits (crumb values are
        # <= 3), so the OR planes need no pre-clear.
        if C:
            out[:, :Cb] = logical[:, 0:C:4]
            for k in (1, 2, 3):
                plane = logical[:, k:C:4]
                if plane.shape[1]:
                    out[:, :plane.shape[1]] |= plane << np.uint8(2 * k)
        lo = logical[:, C:P:2]
        hi = logical[:, C + 1:P:2]
        out[:, Cb:Cb + lo.shape[1]] = lo
        out[:, Cb:Cb + hi.shape[1]] |= hi << np.uint8(4)
        if hi.shape[1] < lo.shape[1]:   # odd nibble count: top nibble
            out[:, Pb - 1] &= np.uint8(0x0F)  # of the last byte stays 0
        out[:, Pb:] = logical[:, P:]
        return out

    def unpack_rows(self, storage: np.ndarray) -> np.ndarray:
        """(n, cols) storage -> (n, G) logical uint8 (a fresh array)."""
        storage = np.asarray(storage, dtype=np.uint8)
        if storage.shape[1] != self.cols:
            raise ValueError(f"unpack_rows expects {self.cols} storage "
                             f"columns, got {storage.shape[1]}")
        n = storage.shape[0]
        P, C = self.packed_groups, self.crumb_groups
        Cb, Pb = self.crumb_bytes, self.packed_bytes
        logical = np.empty((n, self.num_groups), dtype=np.uint8)
        if C:
            ck = storage[:, :Cb]
            for k in range(4):
                cnt = (C - k + 3) // 4
                if cnt > 0:
                    logical[:, k:C:4] = \
                        ((ck >> np.uint8(2 * k)) & np.uint8(0x03))[:, :cnt]
        pk = storage[:, Cb:Pb]
        logical[:, C:P:2] = (pk & np.uint8(0x0F))[:, :(P - C + 1) // 2]
        logical[:, C + 1:P:2] = (pk >> np.uint8(4))[:, :(P - C) // 2]
        logical[:, P:] = storage[:, Pb:]
        return logical

    def unpack_group(self, storage: np.ndarray, g: int) -> np.ndarray:
        """One logical group column's bin values, (n,) uint8."""
        b, sh = self.byte_of(g), self.shift_of(g)
        col = np.asarray(storage[:, b], dtype=np.uint8)
        if g < self.packed_groups:
            return (col >> np.uint8(sh)) & np.uint8(self.width_mask(g))
        return col

    def write_group(self, storage: np.ndarray, g: int,
                    values: np.ndarray, rows=None) -> None:
        """Read-modify-write one group's bin values into its crumb /
        nibble (or byte) — the sparse/CSR push write.  Caller must keep
        each storage BYTE single-writer (up to four packed groups share
        one)."""
        b, sh = self.byte_of(g), self.shift_of(g)
        vals = np.asarray(values, dtype=np.uint8)
        if g >= self.packed_groups:
            if rows is None:
                storage[:, b] = vals
            else:
                storage[rows, b] = vals
            return
        keep = np.uint8(0xFF ^ (self.width_mask(g) << sh))
        if rows is None:
            storage[:, b] = (storage[:, b] & keep) | (vals << np.uint8(sh))
        else:
            cur = storage[rows, b]
            storage[rows, b] = (cur & keep) | (vals << np.uint8(sh))

    def fill_group(self, storage: np.ndarray, g: int, value: int) -> None:
        """Fill one group's crumb/nibble/byte across every row (prefill
        of implicit-zero bins for the streaming CSR push protocol) —
        scalar broadcast, no N-element temp."""
        b, sh = self.byte_of(g), self.shift_of(g)
        v = np.uint8(value)
        if g >= self.packed_groups:
            storage[:, b] = v
            return
        keep = np.uint8(0xFF ^ (self.width_mask(g) << sh))
        storage[:, b] &= keep
        storage[:, b] |= np.uint8(v << sh)


def _native_pack(lib, logical: np.ndarray, packed_groups: int,
                 out: np.ndarray) -> bool:
    """Native nibble pack (``ltpu_pack_nibbles``); False -> numpy path
    (stale prebuilt libltpu.so without the entry point).  Nibble-only:
    callers must not reach here with a crumb section."""
    import ctypes
    fn = getattr(lib, "ltpu_pack_nibbles", None)
    if fn is None or not getattr(fn, "argtypes", None):
        return False
    if not (logical.flags.c_contiguous and out.flags.c_contiguous):
        return False
    n, g = logical.shape
    fn(logical.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
       n, g, packed_groups,
       out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
       out.shape[1])
    return True


# ---------------------------------------------------------------------------
# layout construction (called from Dataset._build_groups once bundles
# and per-group bin counts are known)
# ---------------------------------------------------------------------------
def build_layout(mode: str, group_num_bin: Sequence[int],
                 group_features: Optional[List[List[int]]] = None,
                 feature_names: Optional[Sequence[str]] = None
                 ) -> Optional[BinLayout]:
    """Resolve the layout for a group list ALREADY ordered
    narrowest-first.  ``mode`` is the resolved ``bin_packing``; returns
    None when nothing packs (8bit mode, or auto with no narrow group).

    ``4bit`` strictness: a wide SINGLE-FEATURE group is a hard error
    naming the group and its feature (it means max_bin > 16 reached
    construction — a silently-wide "4-bit" matrix would defeat the
    capacity math the caller asked for).  A wide multi-feature EFB
    bundle only warns: it keeps its 8-bit-identical membership and
    stores byte-wide, preserving byte-exact tree parity (see the
    module docstring).  ``2bit`` applies the same shape one tier down
    against :data:`CRUMB_MAX_BIN`, with too-wide EFB bundles falling
    back to the nibble (or byte) section.

    Only ``auto`` and ``2bit`` emit a crumb section — ``4bit``
    matrices stay byte-for-byte what r18 shipped."""
    G = len(group_num_bin)
    if mode == "8bit" or G == 0:
        return None

    def _label(g: int) -> str:
        feats = group_features[g] if group_features else []
        labels = [feature_names[f] if feature_names
                  and f < len(feature_names) else f"feature {f}"
                  for f in feats]
        names = (f" (features: {', '.join(map(str, labels))})"
                 if labels else "")
        return (f"group {g} ({group_num_bin[g]} bins){names}")

    def _split_wide(lo: int, bound: int):
        """(single-feature, multi-feature) groups in ``lo..G`` whose
        bin count exceeds ``bound`` — EVERY wide group is inspected,
        not just the widest: a wide single-feature group is a hard
        error even when an even wider EFB bundle exists beside it."""
        wide = [g for g in range(lo, G) if group_num_bin[g] > bound]
        single = [g for g in wide if not group_features
                  or len(group_features[g]) == 1]
        return single, [g for g in wide if g not in single]

    C = 0
    if mode in ("auto", "2bit"):
        while C < G and group_num_bin[C] <= CRUMB_MAX_BIN:
            C += 1
    P = C
    while P < G and group_num_bin[P] <= NIBBLE_MAX_BIN:
        P += 1
    if mode == "2bit" and C < G:
        wide_single, wide_multi = _split_wide(C, CRUMB_MAX_BIN)
        if wide_multi:
            Log.warning(
                "bin_packing=2bit: EFB bundle(s) wider than the "
                f"{CRUMB_MAX_BIN} bins a crumb holds — "
                + "; ".join(_label(g) for g in wide_multi)
                + " — each bundle keeps its layout and stores nibble- "
                "or byte-wide (three-section matrix) so trees stay "
                "byte-identical to the 8-bit path; disable "
                "enable_bundle for a fully crumb-packed matrix")
        if wide_single:
            # a categorical feature can exceed max_bin even when
            # max_bin <= 4 (its bin count follows the fitted category
            # table), so "lower max_bin" is not always the way out
            Log.fatal(
                "bin_packing=2bit: feature group(s) too wide for the "
                f"{CRUMB_MAX_BIN} bins a crumb holds — "
                + "; ".join(_label(g) for g in wide_single)
                + " — lower max_bin (<= 4; a categorical feature "
                "needs <= 3 distinct categories) or use "
                "bin_packing=auto to keep wide groups nibble- or "
                "byte-wide")
    if mode in ("4bit", "2bit") and P < G:
        wide_single, wide_multi = _split_wide(P, NIBBLE_MAX_BIN)
        if wide_multi:
            Log.warning(
                f"bin_packing={mode}: EFB bundle(s) wider than the "
                f"{NIBBLE_MAX_BIN} bins a nibble holds — "
                + "; ".join(_label(g) for g in wide_multi)
                + " — each bundle keeps its layout and stores "
                "byte-wide (two-section matrix) so trees stay "
                "byte-identical to the 8-bit path; disable "
                "enable_bundle for a fully packed matrix")
        if wide_single:
            # a categorical feature can exceed max_bin even when
            # max_bin <= 16 (its bin count follows the fitted category
            # table), so "lower max_bin" is not always the way out
            Log.fatal(
                f"bin_packing={mode}: feature group(s) too wide for "
                f"the {NIBBLE_MAX_BIN} bins a nibble holds — "
                + "; ".join(_label(g) for g in wide_single)
                + " — lower max_bin (<= 16; a categorical feature "
                "needs <= 15 distinct categories) or use "
                "bin_packing=auto to keep wide groups byte-wide")
    if P == 0:
        return None
    return BinLayout(mode, G, P, C)
