"""Flat C-API-compatible function surface.

The reference exposes its core through ~90 flat C functions
(reference: include/LightGBM/c_api.h, src/c_api.cpp) that the Python,
R and Java bindings call through ctypes/.Call/JNI.  This framework
inverts the stack — the core is a Python/JAX program and the native
code sits BELOW it (lightgbm_tpu/native) — so the C API's role is
played by this module: the same function names, handle discipline and
0/-1 + ``LGBM_GetLastError`` error convention (reference
c_api.h:765-788 API_BEGIN/END), implemented over the Python core.
Non-Python hosts embed it via CPython (the reference's R binding is
likewise a thin shim over its C API, R-package/src/lightgbm_R.cpp).

Handles are opaque integers from a process-local registry, mirroring
the reference's pointer handles.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from .basic import Dataset
from .booster import Booster
from .config import Config
from .utils.log import Log

_lock = threading.Lock()
_handles: Dict[int, Any] = {}
_next_handle = [1]
_last_error = [""]


def _register(obj) -> int:
    with _lock:
        h = _next_handle[0]
        _next_handle[0] += 1
        _handles[h] = obj
        return h


def _get(handle: int):
    obj = _handles.get(int(handle))
    if obj is None:
        raise KeyError(f"invalid handle {handle}")
    return obj


def _api(fn):
    """API_BEGIN/API_END analog: catch everything, stash the message,
    return -1 (reference c_api.h:771-788)."""
    def wrapper(*args, **kwargs):
        try:
            return fn(*args, **kwargs)
        except Exception as e:           # noqa: BLE001 — C boundary
            _last_error[0] = f"{type(e).__name__}: {e}"
            return -1
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


def _session(bst):
    """gbdt of an active training session; clean error otherwise
    (file-loaded model, or free_dataset ended the session)."""
    if bst.gbdt is None:
        raise RuntimeError("booster has no training session "
                           "(file-loaded model or datasets were freed)")
    return bst.gbdt


def LGBM_GetLastError() -> str:
    """reference c_api.h:46-50."""
    return _last_error[0]


def _parse_params(parameters: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for tok in (parameters or "").replace("\n", " ").split():
        if "=" in tok:
            k, v = tok.split("=", 1)
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# Dataset
# ---------------------------------------------------------------------------
@_api
def LGBM_DatasetCreateFromMat(data, parameters: str, reference=None,
                              out=None) -> int:
    """reference c_api.h:128-147 (row-major float matrix).  ``out`` is
    a one-element list receiving the handle (the C out-pointer)."""
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(np.asarray(data, dtype=np.float64), reference=ref,
                 free_raw_data=False,
                 params=params)
    out[0] = _register(ds)
    return 0


@_api
def LGBM_DatasetCreateFromCSR(indptr, indices, data, num_col: int,
                              parameters: str, reference=None,
                              out=None) -> int:
    """reference c_api.h:147-180 (CSR rows).  Stays sparse end-to-end:
    the Dataset bins CSC columns directly, never densifying the whole
    matrix."""
    from scipy import sparse as sp
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    mat = sp.csr_matrix(
        (np.asarray(data, dtype=np.float64),
         np.asarray(indices, dtype=np.int32),
         np.asarray(indptr, dtype=np.int64)),
        shape=(len(indptr) - 1, int(num_col)))
    ds = Dataset(mat, reference=ref, params=params,
                 free_raw_data=False)
    out[0] = _register(ds)
    return 0


@_api
def LGBM_DatasetCreateFromCSC(col_ptr, indices, data, num_row: int,
                              parameters: str, reference=None,
                              out=None) -> int:
    """reference c_api.h:183-216 (CSC columns)."""
    from scipy import sparse as sp
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    mat = sp.csc_matrix(
        (np.asarray(data, dtype=np.float64),
         np.asarray(indices, dtype=np.int32),
         np.asarray(col_ptr, dtype=np.int64)),
        shape=(int(num_row), len(col_ptr) - 1))
    ds = Dataset(mat, reference=ref, params=params,
                 free_raw_data=False)
    out[0] = _register(ds)
    return 0


@_api
def LGBM_DatasetCreateFromSampledColumn(sample_data, sample_indices,
                                        num_col: int, num_per_col,
                                        num_sample_row: int,
                                        num_total_row: int,
                                        parameters: str, out=None) -> int:
    """reference c_api.h:68-97: fit mappers from per-column samples and
    await PushRows chunks.  ``sample_data``/``sample_indices`` are
    per-column lists (values, row indices within the sample)."""
    from .dataset import Dataset as CoreDataset
    from .config import Config
    params = _parse_params(parameters)
    cfg = Config.from_params(params)
    vals = [np.asarray(sample_data[j], dtype=np.float64)[:num_per_col[j]]
            for j in range(num_col)]
    rows = [np.asarray(sample_indices[j], dtype=np.int64)[:num_per_col[j]]
            for j in range(num_col)]
    core = CoreDataset.from_sampled_columns(
        vals, rows, int(num_sample_row), int(num_total_row), config=cfg)
    out[0] = _register(_PushableDataset(core))
    return 0


class _PushableDataset:
    """Wrapper so Booster creation accepts a pushed core dataset (the
    lazy-Dataset protocol expects .construct()/set_field)."""

    def __init__(self, core):
        self._core = core

    def construct(self, config=None):
        return self._core

    def set_field(self, name, data):
        self._core.metadata.set_field(name, data)
        return self

    def get_field(self, name):
        return self._core.metadata.get_field(name)

    def num_data(self):
        return self._core.num_data

    def num_feature(self):
        return self._core.num_total_features


@_api
def LGBM_DatasetPushRows(handle, data, num_row: int, num_col: int,
                         start_row: int) -> int:
    """reference c_api.h:100-120."""
    ds = _get(handle)
    chunk = np.asarray(data, dtype=np.float64).reshape(num_row, num_col)
    ds._core.push_rows(chunk, int(start_row))
    if ds._core._pushed_rows >= ds._core.num_data:
        ds._core.finish_load()
    return 0


@_api
def LGBM_DatasetPushRowsByCSR(handle, indptr, indices, data,
                              num_col: int, start_row: int) -> int:
    """reference c_api.h:122-145."""
    ds = _get(handle)
    ds._core.push_rows_csr(indptr, indices, data, int(start_row))
    if ds._core._pushed_rows >= ds._core.num_data:
        ds._core.finish_load()
    return 0


@_api
def LGBM_DatasetCreateFromFile(filename: str, parameters: str,
                               reference=None, out=None) -> int:
    """reference c_api.h:53-66.  Constructed eagerly: the reference's
    c_api parses and bins the file at create (c_api.cpp
    DatasetLoader::LoadFromFile), so C callers may query num_data /
    num_feature immediately."""
    params = _parse_params(parameters)
    ref = _get(reference) if reference else None
    ds = Dataset(str(filename), reference=ref, params=params,
                 free_raw_data=False)
    ds.construct()
    out[0] = _register(ds)
    return 0


@_api
def LGBM_DatasetSetField(handle, field_name: str, field_data) -> int:
    """reference c_api.h:223-238."""
    _get(handle).set_field(field_name, np.asarray(field_data))
    return 0


@_api
def LGBM_DatasetGetField(handle, field_name: str, out=None) -> int:
    """reference c_api.h:240-256."""
    out[0] = _get(handle).get_field(field_name)
    return 0


@_api
def LGBM_DatasetGetNumData(handle, out=None) -> int:
    out[0] = _get(handle).num_data()
    return 0


@_api
def LGBM_DatasetGetNumFeature(handle, out=None) -> int:
    out[0] = _get(handle).num_feature()
    return 0


@_api
def LGBM_DatasetSaveBinary(handle, filename: str) -> int:
    """reference c_api.h:204-211."""
    _get(handle).save_binary(str(filename))
    return 0


@_api
def LGBM_DatasetFree(handle) -> int:
    with _lock:
        _handles.pop(int(handle), None)
    return 0


# ---------------------------------------------------------------------------
# Booster
# ---------------------------------------------------------------------------
@_api
def LGBM_BoosterCreate(train_data, parameters: str, out=None) -> int:
    """reference c_api.h:316-325."""
    cfg = Config.from_params(_parse_params(parameters))
    ds = _get(train_data)
    core = ds.construct(cfg) if hasattr(ds, "construct") else ds
    bst = Booster(config=cfg, train_set=core)
    out[0] = _register(bst)
    return 0


@_api
def LGBM_BoosterCreateFromModelfile(filename: str, out_num_iterations=None,
                                    out=None) -> int:
    """reference c_api.h:327-337."""
    bst = Booster(model_file=str(filename))
    if out_num_iterations is not None:
        out_num_iterations[0] = bst.current_iteration
    out[0] = _register(bst)
    return 0


@_api
def LGBM_BoosterLoadModelFromString(model_str: str, out_num_iterations=None,
                                    out=None) -> int:
    bst = Booster(model_str=model_str)
    if out_num_iterations is not None:
        out_num_iterations[0] = bst.current_iteration
    out[0] = _register(bst)
    return 0


@_api
def LGBM_BoosterFree(handle) -> int:
    with _lock:
        _handles.pop(int(handle), None)
    return 0


@_api
def LGBM_BoosterAddValidData(handle, valid_data) -> int:
    """reference c_api.h:348-355."""
    bst = _get(handle)
    vs = _get(valid_data)
    core = vs.construct(bst.config) if hasattr(vs, "construct") else vs
    _session(bst).add_valid(core, f"valid_{len(bst.gbdt.valid_sets)}")
    return 0


@_api
def LGBM_BoosterGetNumClasses(handle, out=None) -> int:
    out[0] = _get(handle).num_class
    return 0


@_api
def LGBM_BoosterUpdateOneIter(handle, is_finished=None) -> int:
    """reference c_api.h:401-408."""
    fin = _get(handle).update()
    if is_finished is not None:
        is_finished[0] = 1 if fin else 0
    return 0


@_api
def LGBM_BoosterUpdateOneIterCustom(handle, grad, hess,
                                    is_finished=None) -> int:
    """reference c_api.h:410-422 (custom objective gradients)."""
    fin = _get(handle).update(fobj=lambda *_: (np.asarray(grad),
                                               np.asarray(hess)))
    if is_finished is not None:
        is_finished[0] = 1 if fin else 0
    return 0


@_api
def LGBM_BoosterRollbackOneIter(handle) -> int:
    _get(handle).rollback_one_iter()
    return 0


@_api
def LGBM_BoosterGetCurrentIteration(handle, out=None) -> int:
    out[0] = _get(handle).current_iteration
    return 0


@_api
def LGBM_BoosterGetEval(handle, data_idx: int, out=None) -> int:
    """reference c_api.h:458-472: metric values for one dataset
    (0 = training, i = i-th validation set)."""
    bst = _get(handle)
    g = _session(bst)
    if data_idx == 0 and not g.train_metrics:
        g.add_train_metrics()
    results = g.eval_metrics()
    names = ["training"] + g.valid_names
    want = names[data_idx] if data_idx < len(names) else None
    out[0] = [v for (dname, _m, v, _b) in results if dname == want]
    return 0


@_api
def LGBM_BoosterPredictForMat(handle, data, predict_type: int = 0,
                              num_iteration: int = -1, out=None) -> int:
    """reference c_api.h:610-635.  predict_type: 0 normal, 1 raw score,
    2 leaf index, 3 contrib (SHAP)."""
    bst = _get(handle)
    out[0] = bst.predict(np.asarray(data, dtype=np.float64),
                         num_iteration=num_iteration,
                         raw_score=(predict_type == 1),
                         pred_leaf=(predict_type == 2),
                         pred_contrib=(predict_type == 3))
    return 0


@_api
def LGBM_BoosterPredictForCSR(handle, indptr, indices, data, num_col: int,
                              predict_type: int = 0,
                              num_iteration: int = -1, out=None) -> int:
    """reference c_api.h:574-607: CSR prediction (row-chunked densify
    inside Booster.predict — never the whole matrix)."""
    from scipy import sparse as sp
    bst = _get(handle)
    mat = sp.csr_matrix(
        (np.asarray(data, dtype=np.float64),
         np.asarray(indices, dtype=np.int32),
         np.asarray(indptr, dtype=np.int64)),
        shape=(len(indptr) - 1, int(num_col)))
    out[0] = bst.predict(mat, num_iteration=num_iteration,
                         raw_score=(predict_type == 1),
                         pred_leaf=(predict_type == 2),
                         pred_contrib=(predict_type == 3))
    return 0


@_api
def LGBM_BoosterSaveModel(handle, num_iteration: int, filename: str) -> int:
    """reference c_api.h:674-683."""
    _get(handle).save_model(str(filename), num_iteration=num_iteration)
    return 0


@_api
def LGBM_BoosterSaveModelToString(handle, num_iteration: int = -1,
                                  out=None) -> int:
    out[0] = _get(handle).model_to_string(num_iteration=num_iteration)
    return 0


@_api
def LGBM_BoosterDumpModel(handle, num_iteration: int = -1, out=None) -> int:
    """JSON dump (reference c_api.h:694-704)."""
    out[0] = _get(handle).dump_model(num_iteration=num_iteration)
    return 0


@_api
def LGBM_BoosterFeatureImportance(handle, num_iteration: int = -1,
                                  importance_type: int = 0,
                                  out=None) -> int:
    """reference c_api.h:717-728; 0 = split counts, 1 = total gain."""
    out[0] = _get(handle).feature_importance(
        importance_type="split" if importance_type == 0 else "gain",
        num_iteration=num_iteration)
    return 0


@_api
def LGBM_BoosterGetEvalCounts(handle, out=None) -> int:
    """reference c_api.h:430-437: number of metrics per dataset (so C
    callers can size the LGBM_BoosterGetEval result buffer)."""
    bst = _get(handle)
    g = _session(bst)
    if not g.train_metrics:
        g.add_train_metrics()
    out[0] = sum(len(m.names()) for m in g.train_metrics)
    return 0


@_api
def LGBM_BoosterGetEvalNames(handle, out=None) -> int:
    """reference c_api.h:439-446."""
    bst = _get(handle)
    g = _session(bst)
    if not g.train_metrics:
        g.add_train_metrics()
    names: List[str] = []
    for m in g.train_metrics:
        names.extend(m.names())
    out[0] = names
    return 0


# ---------------------------------------------------------------------------
# Network (distributed seam — reference c_api.h:749-762)
# ---------------------------------------------------------------------------
@_api
def LGBM_NetworkInit(machines: str, local_listen_port: int,
                     listen_time_out: int, num_machines: int) -> int:
    """The socket rendezvous has no TPU analog: multi-host setup goes
    through jax.distributed.initialize + the mesh (parallel/mesh.py).
    Kept for call-compatibility; warns and succeeds."""
    if num_machines > 1:
        Log.warning("LGBM_NetworkInit: use jax.distributed.initialize "
                    "+ mesh_shape instead; socket rendezvous is not "
                    "part of the TPU backend")
    return 0


@_api
def LGBM_NetworkFree() -> int:
    return 0


@_api
def LGBM_NetworkInitWithFunctions(num_machines: int, rank: int,
                                  reduce_scatter_ext_fun=None,
                                  allgather_ext_fun=None) -> int:
    """The reference's external-collective injection seam
    (c_api.h:760-762).  Here collectives are compiled into the XLA
    program by GSPMD, so host callables CANNOT be routed into jitted
    training — the installed backend only serves host-side simulation
    (parallel/collectives.py HostCollectives API).  Warns loudly so an
    embedder expecting the reference's transport injection knows to use
    jax.distributed.initialize + mesh_shape instead."""
    from .parallel import collectives
    if num_machines > 1:
        Log.warning(
            "LGBM_NetworkInitWithFunctions: injected collectives are "
            "NOT used by jitted training on TPU (XLA emits its own over "
            "ICI/DCN); they are only reachable through the host-side "
            "simulation API. Use jax.distributed.initialize + "
            "mesh_shape for real multi-host training.")
    collectives.install_external(num_machines, rank,
                                 reduce_scatter_ext_fun,
                                 allgather_ext_fun)
    return 0


# ---------------------------------------------------------------------------
# getter tail (reference c_api.h:316-739) — the long tail third-party
# bindings end up needing
# ---------------------------------------------------------------------------
@_api
def LGBM_DatasetGetSubset(handle, used_row_indices, num_used_row_indices,
                          parameters: str, out=None) -> int:
    """reference c_api.h:195-210 — bagging-style row subset sharing the
    parent's bin mappers."""
    ds = _get(handle)
    idx = np.asarray(used_row_indices,
                     dtype=np.int64)[:int(num_used_row_indices)]
    sub = ds.subset(idx, params=_parse_params(parameters) or None)
    out[0] = _register(sub)
    return 0


@_api
def LGBM_DatasetSetFeatureNames(handle, feature_names,
                                num_feature_names: int) -> int:
    """reference c_api.h:212-218."""
    ds = _get(handle)
    names = [str(feature_names[i]) for i in range(int(num_feature_names))]
    ds.feature_name = names
    core = getattr(ds, "_core", None)
    if core is not None and not callable(getattr(core, "construct", None)):
        core.feature_names = names
    return 0


@_api
def LGBM_DatasetGetFeatureNames(handle, out_strs=None, out_len=None
                                ) -> int:
    """reference c_api.h:220-230 (out_strs: list receiving the
    names)."""
    ds = _get(handle)
    names = None
    core = getattr(ds, "_core", None)
    if core is not None:
        names = getattr(core, "feature_names", None)
    if names is None:
        names = getattr(ds, "feature_name", None)
    if names in (None, "auto"):
        names = []
    out_strs[:] = list(names)
    if out_len is not None:
        out_len[0] = len(names)
    return 0


@_api
def LGBM_BoosterMerge(handle, other_handle) -> int:
    """reference c_api.h:330-338 — append the other booster's trees."""
    bst = _get(handle)
    other = _get(other_handle)
    bst._sync_models()
    other._sync_models()
    import copy as _copy
    # deep copies: merged trees must not alias the source booster's
    # mutable Tree objects (SetLeafValue on one would corrupt the other)
    bst.models.extend(_copy.deepcopy(t) for t in other.models)
    if bst.gbdt is not None:
        # keep the per-model scale bookkeeping aligned so later
        # flushes can reconcile (the foreign trees are final: scale 1)
        for _ in other.models:
            bst.gbdt._tree_scale.append(1.0)
            bst.gbdt._applied_scale.append(1.0)
    bst._raw_stack_cache = None
    bst._device_stale = True   # in-session stacks no longer match
    return 0


@_api
def LGBM_BoosterNumberOfTotalModel(handle, out_models=None) -> int:
    """reference c_api.h:376-383."""
    out_models[0] = _get(handle).num_trees()
    return 0


@_api
def LGBM_BoosterGetNumPredict(handle, data_idx: int,
                              out_len=None) -> int:
    """reference c_api.h:520-530 — prediction count for train (0) or
    valid set data_idx-1."""
    bst = _get(handle)
    g = _session(bst)
    if data_idx == 0:
        n = g.num_data
    else:
        n = g.valid_sets[data_idx - 1].num_data
    out_len[0] = n * max(bst.num_tree_per_iteration, 1)
    return 0


@_api
def LGBM_BoosterGetPredict(handle, data_idx: int, out_len=None,
                           out_result=None) -> int:
    """reference c_api.h:532-548 / gbdt.cpp:691-728 GetPredictAt:
    converted (sigmoid/softmax) scores of the training set (0) or
    validation set data_idx-1, class-major."""
    bst = _get(handle)
    g = _session(bst)
    if data_idx == 0:
        raw = np.asarray(g.scores[:, :g.num_data], dtype=np.float64)
    else:
        vs = g.valid_sets[data_idx - 1]
        raw = np.asarray(vs.scores[:, :vs.num_data], dtype=np.float64)
    k = max(bst.num_tree_per_iteration, 1)
    conv = raw.T  # (n, k)
    if not bst.average_output:
        conv = bst._convert_output(conv)
    flat = np.asarray(conv).T.reshape(-1)  # class-major like reference
    n = flat.shape[0]
    if out_result is not None:
        out_result[:n] = flat
    if out_len is not None:
        out_len[0] = n
    return 0


@_api
def LGBM_BoosterGetLeafValue(handle, tree_idx: int, leaf_idx: int,
                             out_val=None) -> int:
    """reference c_api.h:433-443."""
    bst = _get(handle)
    bst._sync_models()
    out_val[0] = float(bst.models[int(tree_idx)].leaf_value[int(leaf_idx)])
    return 0


@_api
def LGBM_BoosterSetLeafValue(handle, tree_idx: int, leaf_idx: int,
                             val: float) -> int:
    """reference c_api.h:445-456 — host-tree mutation invalidates the
    device predict caches (same staleness rule as refit)."""
    bst = _get(handle)
    bst._sync_models()
    bst.models[int(tree_idx)].leaf_value[int(leaf_idx)] = float(val)
    bst._device_stale = True
    bst._raw_stack_cache = None
    return 0


@_api
def LGBM_BoosterResetParameter(handle, parameters: str) -> int:
    """reference c_api.h:395-403 — currently learning_rate (the
    parameter the reference's reset path exercises in tests) plus any
    plain config scalars."""
    _get(handle).reset_parameter(_parse_params(parameters))
    return 0


@_api
def LGBM_BoosterPredictForFile(handle, data_filename: str,
                               data_has_header: int, predict_type: int,
                               num_iteration: int, parameter: str,
                               result_filename: str) -> int:
    """reference c_api.h:495-518 — batch file prediction written as
    one row per line (tab-separated for multi-output)."""
    bst = _get(handle)
    from .config import Config as _Config
    from .data_loader import load_file
    cfg = _Config.from_params(dict(_parse_params(parameter),
                                   has_header=bool(data_has_header)))
    X, _, _ = load_file(str(data_filename), cfg)
    pred = bst.predict(
        X, num_iteration=int(num_iteration),
        raw_score=predict_type == 1, pred_leaf=predict_type == 2,
        pred_contrib=predict_type == 3)
    out = np.atleast_2d(np.asarray(pred))
    if out.shape[0] == 1 and X.shape[0] != 1:
        out = out.T
    with open(str(result_filename), "w") as f:
        for row in (out if out.ndim > 1 else out[:, None]):
            f.write("\t".join(f"{v:g}" for v in np.atleast_1d(row))
                    + "\n")
    return 0


# ---------------------------------------------------------------------------
# round-4 tail: the 7 symbols the r3 audit found missing
# ---------------------------------------------------------------------------
def LGBM_SetLastError(msg: str) -> int:
    """reference c_api.h:768 — let embedders (custom objectives calling
    back into the host) set the error slot themselves."""
    _last_error[0] = str(msg)
    return 0


@_api
def LGBM_DatasetCreateByReference(reference, num_total_row: int,
                                  out=None) -> int:
    """reference c_api.h: create an empty dataset aligned to an
    existing one's bin mappers, awaiting PushRows chunks — the
    streaming path used when workers bin against a coordinator's
    mappers."""
    from .dataset import Dataset as CoreDataset
    ref_obj = _get(reference)
    ref_core = ref_obj.construct() if hasattr(ref_obj, "construct") \
        else ref_obj
    core = CoreDataset.from_reference_for_push(ref_core,
                                               int(num_total_row))
    out[0] = _register(_PushableDataset(core))
    return 0


@_api
def LGBM_BoosterResetTrainingData(handle, train_data) -> int:
    """reference c_api.h:352-360: swap the training dataset of an
    existing booster (continued training on refreshed data)."""
    bst = _get(handle)
    ds = _get(train_data)
    core = ds.construct(bst.config) if hasattr(ds, "construct") else ds
    bst.reset_training_data(core)
    return 0


@_api
def LGBM_BoosterGetNumFeature(handle, out=None) -> int:
    """reference c_api.h:443-450 (LGBM_BoosterGetNumFeature)."""
    out[0] = _get(handle).num_feature()
    return 0


@_api
def LGBM_BoosterGetFeatureNames(handle, out_strs=None,
                                out_len=None) -> int:
    """reference c_api.h:430-441: feature names of the booster's
    model (post-training they come from the model, not the dataset)."""
    names = list(_get(handle).feature_name())
    if out_len is not None:
        out_len[0] = len(names)
    if out_strs is not None:
        out_strs[0] = names
    return 0


@_api
def LGBM_BoosterCalcNumPredict(handle, num_row: int, predict_type: int,
                               num_iteration: int = -1,
                               out_len=None) -> int:
    """reference c_api.h:520-535: result-buffer size for a prediction
    call — rows x per-row outputs (classes, leaves, or contribs)."""
    bst = _get(handle)
    ncls = bst.num_tree_per_iteration
    cur = bst.current_iteration
    # reference semantics: num_iteration <= 0 means all iterations
    n_iter = cur if num_iteration <= 0 else min(int(num_iteration), cur)
    if predict_type == 2:                      # leaf indices
        per_row = ncls * n_iter
    elif predict_type == 3:                    # SHAP contribs
        per_row = ncls * (bst.num_feature() + 1)
    else:                                      # raw / normal
        per_row = ncls
    out_len[0] = int(num_row) * per_row
    return 0


@_api
def LGBM_BoosterPredictForCSC(handle, col_ptr, indices, data,
                              num_row: int, predict_type: int = 0,
                              num_iteration: int = -1, out=None) -> int:
    """reference c_api.h:626-659: CSC prediction — the transposed
    sibling of the CSR path (converted column-major -> row-major
    sparse, then the same chunked sparse predict)."""
    from scipy import sparse as sp
    bst = _get(handle)
    ncol = len(col_ptr) - 1
    mat = sp.csc_matrix(
        (np.asarray(data, dtype=np.float64),
         np.asarray(indices, dtype=np.int32),
         np.asarray(col_ptr, dtype=np.int64)),
        shape=(int(num_row), ncol)).tocsr()
    out[0] = bst.predict(mat, num_iteration=num_iteration,
                         raw_score=(predict_type == 1),
                         pred_leaf=(predict_type == 2),
                         pred_contrib=(predict_type == 3))
    return 0
