"""Training callbacks (reference: python-package/lightgbm/callback.py:49-215).

Each callback receives a CallbackEnv namedtuple (model, params,
iteration, end_iteration, evaluation_result_list) after (or before)
every iteration.
"""
from __future__ import annotations

import collections
from typing import Callable, List

from .engine import EarlyStopException
from .utils.log import Log


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    def _callback(env):
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            msg = "\t".join(f"{d}'s {m}: {v:g}"
                            for d, m, v, _ in env.evaluation_result_list)
            Log.info(f"[{env.iteration + 1}]\t{msg}")
    _callback.order = 10
    return _callback


def record_evaluation(eval_result: dict) -> Callable:
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dict")
    eval_result.clear()

    def _callback(env):
        for dname, mname, value, _ in env.evaluation_result_list or []:
            eval_result.setdefault(dname, collections.OrderedDict()) \
                .setdefault(mname, []).append(value)
    _callback.order = 20
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Reset parameters on schedule; supports learning_rate as list or
    callable (reference callback.py:105-147)."""
    def _callback(env):
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration:
                    raise ValueError(
                        f"Length of list {key} has to be {env.end_iteration}")
                new_params[key] = value[env.iteration]
            elif callable(value):
                new_params[key] = value(env.iteration)
            else:
                raise ValueError(
                    "Only list and callable values are supported as a "
                    "parameter of reset_parameter")
        if "learning_rate" in new_params and env.model is not None:
            env.model.gbdt.shrinkage_rate = new_params["learning_rate"]
        if new_params:
            env.params.update(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def telemetry_snapshot(dest: dict) -> Callable:
    """Expose the runtime telemetry registry to user code per
    iteration, ``record_evaluation``-style: after each iteration a
    ``lightgbm_tpu.telemetry.TELEMETRY.snapshot()`` dict (counters,
    gauges, retrace map, derived per-tree host/device split) is
    appended to ``dest["snapshots"]`` with the matching 1-based
    iteration in ``dest["iterations"]``.

    Needs telemetry enabled (``telemetry=counters`` or higher) to
    carry data, and — like every per-iteration callback — opts the run
    out of multi-iteration fused dispatch chunks, so counters advance
    once per iteration (docs/OBSERVABILITY.md)."""
    if not isinstance(dest, dict):
        raise TypeError("dest should be a dict")
    dest.clear()

    def _callback(env):
        from .telemetry import TELEMETRY
        dest.setdefault("iterations", []).append(env.iteration + 1)
        dest.setdefault("snapshots", []).append(TELEMETRY.snapshot())
    _callback.order = 25
    return _callback


def early_stopping(stopping_rounds: int, first_metric_only: bool = False,
                   verbose: bool = True) -> Callable:
    """Early-stopping callback (reference callback.py:148-215)."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List = []
    cmp_op: List[Callable] = []

    def _init(env):
        if not env.evaluation_result_list:
            raise ValueError(
                "For early stopping, at least one dataset and eval metric "
                "is required for evaluation")
        if verbose:
            Log.info(f"Training until validation scores don't improve for "
                     f"{stopping_rounds} rounds.")
        for _, _, _, bigger in env.evaluation_result_list:
            best_iter.append(0)
            if bigger:
                best_score.append(float("-inf"))
                cmp_op.append(lambda a, b: a > b)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda a, b: a < b)
            best_score_list.append(None)

    def _callback(env):
        if not best_score:
            _init(env)
        for i, (dname, mname, value, _) in \
                enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](value, best_score[i]):
                best_score[i] = value
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            if dname == "training":
                continue
            if env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    Log.info(f"Early stopping, best iteration is:"
                             f"[{best_iter[i] + 1}]")
                raise EarlyStopException(best_iter[i], best_score_list[i])
            if first_metric_only:
                break
    _callback.order = 30
    return _callback
