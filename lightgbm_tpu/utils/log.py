"""Leveled logging (reference: include/LightGBM/utils/log.h).

``Log.fatal`` raises instead of aborting, matching the reference's
``Log::Fatal`` -> std::runtime_error contract (log.h:83-95).
"""
from __future__ import annotations

import sys
import time


class LightGBMError(RuntimeError):
    """Raised by Log.fatal (reference: Log::Fatal throws std::runtime_error)."""


_SINK = None


def set_sink(fn) -> None:
    """Install an observer for emitted log lines (``fn(tag, msg)``) —
    the crash flight recorder subscribes here so the last-N warnings
    ride its ring buffer.  One sink; None uninstalls."""
    global _SINK
    _SINK = fn


class Log:
    # verbosity: <0 fatal only, =0 warning+, =1 info+, >1 debug+
    level: int = 1

    @classmethod
    def set_level(cls, level: int) -> None:
        cls.level = level

    @classmethod
    def _emit(cls, tag: str, msg: str) -> None:
        sys.stderr.write(f"[LightGBM-TPU] [{tag}] {msg}\n")
        sys.stderr.flush()
        if _SINK is not None:
            try:
                _SINK(tag, msg)
            except Exception:
                pass

    @classmethod
    def debug(cls, msg: str) -> None:
        if cls.level > 1:
            cls._emit("Debug", msg)

    @classmethod
    def info(cls, msg: str) -> None:
        if cls.level >= 1:
            cls._emit("Info", msg)

    @classmethod
    def warning(cls, msg: str) -> None:
        if cls.level >= 0:
            cls._emit("Warning", msg)

    @classmethod
    def fatal(cls, msg: str) -> None:
        cls._emit("Fatal", msg)
        raise LightGBMError(msg)


class PhaseTimer:
    """Per-phase accumulated wall-clock timing, the analog of the
    reference's TIMETAG chrono counters (gbdt.cpp:21-29,
    serial_tree_learner.cpp:13-20)."""

    def __init__(self):
        self.acc: dict[str, float] = {}
        self._start: dict[str, float] = {}

    def start(self, phase: str) -> None:
        self._start[phase] = time.perf_counter()

    def stop(self, phase: str) -> None:
        t0 = self._start.pop(phase, None)
        if t0 is not None:
            dt = time.perf_counter() - t0
            self.acc[phase] = self.acc.get(phase, 0.0) + dt
            # mirror each phase into the telemetry counters so the
            # TIMETAG accounting rides the same export as everything
            # else (lazy import: telemetry imports this module)
            from ..telemetry import TELEMETRY
            TELEMETRY.add(f"phase_{phase}_ms", dt * 1e3)

    def report(self) -> str:
        return ", ".join(f"{k}={v:.3f}s" for k, v in sorted(self.acc.items()))
