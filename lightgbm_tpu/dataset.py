"""Binned dataset: the TPU-resident training matrix.

TPU-native re-design of the reference's Dataset/FeatureGroup/Metadata
(reference: include/LightGBM/dataset.h:282-609, feature_group.h:18-230,
src/io/dataset.cpp, src/io/metadata.cpp).  Key representation change:
instead of per-group Bin objects (dense/sparse/4-bit) in row order plus
leaf-ordered sparse copies, the whole training set is ONE packed
``(num_data, num_groups)`` uint8 matrix that lives in HBM, sharded over
the mesh row axis for data-parallel training.  Exclusive-feature-bundle
groups keep the reference's bin-offset scheme (offset 0 = shared default
slot, feature_group.h:34-51/128-136) so EFB plugs in without kernel
changes; the per-feature view is recovered on device by a precomputed
``(F, max_bin)`` gather map plus the FixHistogram default-bin
reconstruction (dataset.cpp:776-795).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, MISSING_NAN,
                      MISSING_NONE, MISSING_ZERO, BinMapper,
                      find_bin_mappers, resolve_construct_threads)
from .config import Config
from .packing import (CRUMB_MAX_BIN, NIBBLE_MAX_BIN, BinLayout,
                      build_layout, resolve_bin_packing)
from .utils.log import Log


class Metadata:
    """Labels, weights, query boundaries, init scores
    (reference dataset.h:36-248, src/io/metadata.cpp)."""

    def __init__(self, num_data: int):
        self.num_data = num_data
        self.label = np.zeros(num_data, dtype=np.float32)
        self.weight: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None  # (num_queries+1,)
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        label = np.asarray(label, dtype=np.float32).reshape(-1)
        if len(label) != self.num_data:
            Log.fatal(f"Length of label ({len(label)}) != num_data ({self.num_data})")
        self.label = label

    def set_weight(self, weight: Optional[Sequence[float]]) -> None:
        if weight is None:
            self.weight = None
            return
        weight = np.asarray(weight, dtype=np.float32).reshape(-1)
        if len(weight) != self.num_data:
            Log.fatal(f"Length of weight ({len(weight)}) != num_data ({self.num_data})")
        self.weight = weight

    def set_group(self, group: Optional[Sequence[int]]) -> None:
        """``group`` is per-query sizes (python API convention); converted
        to cumulative boundaries (reference metadata.cpp query_boundaries_)."""
        if group is None:
            self.query_boundaries = None
            return
        group = np.asarray(group, dtype=np.int64).reshape(-1)
        bounds = np.concatenate([[0], np.cumsum(group)])
        if bounds[-1] != self.num_data:
            Log.fatal(f"Sum of query counts ({bounds[-1]}) != num_data ({self.num_data})")
        self.query_boundaries = bounds.astype(np.int32)

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        arr = np.asarray(init_score, dtype=np.float64).reshape(-1)
        if len(arr) % self.num_data != 0:
            Log.fatal("Initial score size doesn't match data size")
        self.init_score = arr

    @property
    def num_queries(self) -> int:
        if self.query_boundaries is None:
            return 0
        return len(self.query_boundaries) - 1

    def get_field(self, name: str):
        if name == "label":
            return self.label
        if name == "weight":
            return self.weight
        if name == "init_score":
            return self.init_score
        if name == "group":
            if self.query_boundaries is None:
                return None
            return np.diff(self.query_boundaries)
        Log.fatal(f"Unknown field {name}")

    def set_field(self, name: str, data) -> None:
        if name == "label":
            self.set_label(data)
        elif name == "weight":
            self.set_weight(data)
        elif name == "init_score":
            self.set_init_score(data)
        elif name in ("group", "query"):
            self.set_group(data)
        else:
            Log.fatal(f"Unknown field {name}")


class FeatureView:
    """Per-feature device-facing metadata: where the feature's bins live
    inside its group column and how missing values are encoded."""

    __slots__ = ("feature_idx", "group", "sub", "offset", "num_bin",
                 "default_bin", "missing_type", "is_categorical", "mapper",
                 "collapsed_default")

    def __init__(self, feature_idx: int, group: int, sub: int, offset: int,
                 mapper: BinMapper, collapsed_default: bool):
        self.feature_idx = feature_idx
        self.group = group
        self.sub = sub
        self.offset = offset          # group-bin index of this feature's bin
        self.num_bin = mapper.num_bin
        self.default_bin = mapper.default_bin
        self.missing_type = mapper.missing_type
        self.is_categorical = mapper.bin_type == BIN_CATEGORICAL
        self.mapper = mapper
        # True when the feature shares the group's bin-0 default slot
        # (multi-feature bundles, feature_group.h:128-136)
        self.collapsed_default = collapsed_default


class Dataset:
    """The binned training matrix + metadata (host side).

    ``group_bins`` is the packed (num_data, num_groups) uint8 matrix; the
    device training path uploads it once per training run (the analog of
    GPUTreeLearner::AllocateGPUMemory's one-time upload,
    gpu_tree_learner.cpp:234-556).
    """

    def __init__(self):
        self.num_data = 0
        self.num_total_features = 0
        self.mappers: List[BinMapper] = []
        self.used_features: List[int] = []       # real idx of non-trivial features
        self.features: List[FeatureView] = []    # one per used feature
        # STORAGE bin matrix: (N, G) uint8 when bin_layout is None;
        # nibble-packed (N, bin_layout.cols) otherwise (packing.py —
        # the first packed_groups groups ride two per byte)
        self.group_bins: Optional[np.ndarray] = None
        self.bin_layout: Optional[BinLayout] = None
        self.group_num_bin: List[int] = []
        self.group_is_multi: List[bool] = []
        self.metadata: Metadata = Metadata(0)
        self.feature_names: List[str] = []
        self.max_bin = 255
        self.config: Optional[Config] = None
        self.monotone_constraints: Optional[np.ndarray] = None
        self._raw_data: Optional[np.ndarray] = None
        self._categorical_features: List[int] = []
        self._bundles: List[List[int]] = []

    # ------------------------------------------------------------------
    @property
    def num_features(self) -> int:
        return len(self.features)

    @property
    def num_groups(self) -> int:
        return len(self.group_num_bin)

    @property
    def label(self) -> np.ndarray:
        return self.metadata.label

    # reference-compatible accessors: custom fobj/feval callbacks are
    # handed this core object and expect the python package's
    # Dataset.get_label()/get_weight()/get_group() surface
    def get_field(self, name: str):
        return self.metadata.get_field(name)

    def get_label(self):
        return self.get_field("label")

    def get_weight(self):
        return self.get_field("weight")

    def get_group(self):
        return self.get_field("group")

    def get_init_score(self):
        return self.get_field("init_score")

    # ------------------------------------------------------------------
    @classmethod
    def from_matrix(cls, data: np.ndarray, label=None, weight=None,
                    group=None, init_score=None,
                    config: Optional[Config] = None,
                    categorical_features: Optional[Sequence[int]] = None,
                    feature_names: Optional[Sequence[str]] = None,
                    reference: Optional["Dataset"] = None) -> "Dataset":
        """Build from an in-memory float matrix or a scipy sparse
        matrix — the analog of LGBM_DatasetCreateFromMat / FromCSR/CSC
        -> CostructFromSampleData (reference c_api.cpp:424+,
        dataset_loader.cpp:488-610; sparse classes
        src/io/sparse_bin.hpp:68-456).

        Sparse input is NEVER densified whole: sampling, EFB conflict
        counting and bin-matrix construction all walk the CSC columns,
        so host memory is bounded by nnz + the packed (N, G) uint8
        output (the per-bundle-densify design — the uint8 matrix IS the
        HBM-resident training representation)."""
        config = config or Config()
        sparse = hasattr(data, "tocsc") and hasattr(data, "nnz")
        if sparse:
            data = data.tocsc()
            data.sort_indices()
        else:
            data = np.asarray(data, dtype=np.float64)
            if data.ndim != 2:
                raise ValueError("data must be 2-dimensional")
        num_data, num_features = data.shape

        self = cls()
        self.config = config
        self.num_data = num_data
        self.num_total_features = num_features
        self.max_bin = config.max_bin
        self.feature_names = list(feature_names) if feature_names else [
            f"Column_{i}" for i in range(num_features)]

        if reference is not None:
            # validation sets share the training set's bin mappers
            # (reference basic.py reference-alignment / dataset.h CopyFeatureMapperFrom)
            if reference.num_total_features != num_features:
                Log.fatal("Validation data has different number of features "
                          f"({num_features} vs {reference.num_total_features})")
            self.mappers = reference.mappers
            self.used_features = list(reference.used_features)
            self.max_bin = reference.max_bin
            self._build_groups(reference=reference)
        else:
            cat_set = set(categorical_features or [])
            sampler = (_sample_feature_values_sparse if sparse
                       else _sample_feature_values)
            sample_vals, total_cnt, sample_rows = sampler(
                data, config.bin_construct_sample_cnt, config.data_random_seed)
            self.mappers = self._fit_mappers(sample_vals, total_cnt,
                                             config, cat_set)
            self.used_features = [i for i, m in enumerate(self.mappers)
                                  if not m.is_trivial]
            if not self.used_features:
                Log.warning("There are no meaningful features; "
                            "all features are constant or filtered")
            self._build_groups(reference=None, sample_nonzero=sample_rows,
                               sample_cnt=total_cnt)

        if sparse:
            self._bin_data_sparse(data)
        else:
            self._bin_data(data)
        self._raw_data = data
        self._categorical_features = list(categorical_features or [])
        self.metadata = Metadata(num_data)
        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weight(weight)
        self.metadata.set_group(group)
        self.metadata.set_init_score(init_score)
        self._resolve_monotone(config)
        return self

    # ------------------------------------------------------------------
    @classmethod
    def from_sampled_columns(cls, sample_vals: List[np.ndarray],
                             sample_rows: List[np.ndarray],
                             total_sample: int, num_data: int,
                             config: Optional[Config] = None,
                             categorical_features=None,
                             feature_names=None) -> "Dataset":
        """Streaming construction, step 1: fit bin mappers from sampled
        per-column values, allocate the packed (N, G) uint8 matrix, and
        return a dataset awaiting ``push_rows`` chunks + ``finish_load``
        — the two-round / LGBM_DatasetCreateFromSampledColumn +
        PushRows protocol (reference c_api.h:68-145,
        dataset_loader.cpp:180-265).  The float matrix never exists:
        peak host memory is samples + one chunk + the uint8 matrix.

        Args:
          sample_vals: per-feature sampled non-zero (or NaN) values.
          sample_rows: per-feature row indices of those values within
            the sample (feeds EFB conflict counting).
          total_sample: number of sampled rows (zeros implicit).
          num_data: full row count being pushed.
        """
        config = config or Config()
        self = cls()
        self.config = config
        self.num_data = num_data
        self.num_total_features = len(sample_vals)
        self.max_bin = config.max_bin
        self.feature_names = list(feature_names) if feature_names else [
            f"Column_{i}" for i in range(len(sample_vals))]
        cat_set = set(categorical_features or [])
        self.mappers = self._fit_mappers(sample_vals, total_sample,
                                         config, cat_set)
        self.used_features = [i for i, m in enumerate(self.mappers)
                              if not m.is_trivial]
        self._build_groups(reference=None, sample_nonzero=sample_rows,
                           sample_cnt=total_sample)
        self._init_push_storage(list(categorical_features or []))
        return self

    @classmethod
    def from_reference_for_push(cls, ref: "Dataset",
                                num_data: int) -> "Dataset":
        """Streaming construction aligned to an existing dataset's bin
        mappers (reference LGBM_DatasetCreateByReference, c_api.h —
        the distributed/streaming analog of validation-set alignment):
        allocates the packed matrix for ``num_data`` rows and awaits
        ``push_rows`` chunks + ``finish_load``."""
        self = cls()
        self.config = ref.config
        self.num_data = int(num_data)
        self.num_total_features = ref.num_total_features
        self.max_bin = ref.max_bin
        self.feature_names = list(ref.feature_names)
        self.mappers = ref.mappers
        self.used_features = list(ref.used_features)
        self._build_groups(reference=ref)
        self._init_push_storage(list(
            getattr(ref, "_categorical_features", [])))
        return self

    def _init_push_storage(self, categorical_features) -> None:
        """Shared streaming-construction tail (from_sampled_columns /
        from_reference_for_push): allocate the packed matrix, prefill
        implicit-zero bins so sparse (CSR) pushes only write stored
        entries, and arm the pushed-row counter."""
        self.group_bins = np.zeros(
            (self.num_data, self._storage_cols()), dtype=np.uint8)
        for f in self.features:
            if not f.collapsed_default:
                zb = int(np.asarray(
                    self.mappers[f.feature_idx].value_to_bin(
                        np.zeros(1)))[0])
                if zb != 0:
                    if self.bin_layout is not None:
                        self.bin_layout.fill_group(self.group_bins,
                                                   f.group, zb)
                    else:
                        self.group_bins[:, f.group] = zb
        self.metadata = Metadata(self.num_data)
        self._categorical_features = categorical_features
        self._resolve_monotone(self.config)
        self._pushed_rows = 0

    def push_rows(self, chunk: np.ndarray, row_start: int) -> None:
        """Streaming construction, step 2: bin one dense float chunk
        (reference LGBM_DatasetPushRows, c_api.h:100-120)."""
        chunk = np.asarray(chunk, dtype=np.float64)
        if chunk.ndim == 1:
            chunk = chunk[None, :]
        self._bin_rows_dense(chunk, row_start)
        # actual pushed-row COUNT (not a high-water mark): chunks may
        # arrive in any order (reference allows thread-partitioned
        # arbitrary start_row), so only the sum of chunk sizes can tell
        # when every row has arrived
        self._pushed_rows = getattr(self, "_pushed_rows", 0) \
            + chunk.shape[0]

    def push_rows_csr(self, indptr, indices, values,
                      row_start: int) -> None:
        """Streaming CSR chunk push (reference LGBM_DatasetPushRowsByCSR,
        c_api.h:122-145): only stored entries are written; implicit
        zeros were prefilled at creation."""
        indptr = np.asarray(indptr, dtype=np.int64)
        indices = np.asarray(indices, dtype=np.int32)
        values = np.asarray(values, dtype=np.float64)
        nrows = len(indptr) - 1
        row_of = np.repeat(np.arange(nrows, dtype=np.int64),
                           np.diff(indptr)) + row_start
        order = np.argsort(indices, kind="stable")
        cols_s, rows_s, vals_s = indices[order], row_of[order], values[order]
        bounds = np.searchsorted(cols_s, np.arange(
            self.num_total_features + 1))
        for f in self.features:
            j = f.feature_idx
            lo, hi = bounds[j], bounds[j + 1]
            if lo == hi:
                continue
            m = self.mappers[j]
            col = m.value_to_bin(vals_s[lo:hi])
            rr = rows_s[lo:hi]
            if not f.collapsed_default:
                self._write_group_rows(f.group, rr,
                                       col.astype(np.uint8))
            else:
                gb = col + f.offset
                if m.default_bin == 0:
                    gb -= 1
                keep = col != m.default_bin
                self._write_group_rows(f.group, rr[keep],
                                       gb[keep].astype(np.uint8))
        self._pushed_rows = getattr(self, "_pushed_rows", 0) + nrows

    def _write_group_rows(self, group: int, rows, vals) -> None:
        """Scattered per-group bin write, storage-layout aware (nibble
        read-modify-write when the group is packed)."""
        if self.bin_layout is None:
            self.group_bins[rows, group] = vals
        else:
            self.bin_layout.write_group(self.group_bins, group, vals,
                                        rows=rows)

    def _storage_cols(self) -> int:
        """Byte columns of the storage bin matrix."""
        return (self.bin_layout.cols if self.bin_layout is not None
                else self.num_groups)

    def logical_group_bins(self) -> Optional[np.ndarray]:
        """The logical (N, G) group-bin view — unpacks a nibble-packed
        storage matrix (fresh array), passes the legacy matrix through.
        Parity checks and host-side per-group readers only; the device
        path streams the STORAGE matrix and unpacks in-register."""
        if self.group_bins is None or self.bin_layout is None:
            return self.group_bins
        return self.bin_layout.unpack_rows(np.asarray(self.group_bins))

    def finish_load(self) -> "Dataset":
        """End of streaming pushes (reference FinishLoad)."""
        pushed = getattr(self, "_pushed_rows", self.num_data)
        if pushed < self.num_data:
            Log.warning(f"finish_load: only {pushed} of {self.num_data} "
                        "rows were pushed")
        return self

    # ------------------------------------------------------------------
    def _fit_mappers(self, sample_vals: List[np.ndarray],
                     total_sample_cnt: int, config: Config,
                     cat_set: set) -> List[BinMapper]:
        """The ONE bin-mapper fit path — in-RAM (`from_matrix`) and
        two-round streaming (`from_sampled_columns`) construction both
        route through here, so the threaded fit cannot diverge between
        them.  Per-feature fits fan across ``construct_threads`` host
        threads (numpy sort/searchsorted release the GIL); results are
        byte-identical at every thread count."""
        from .telemetry import TELEMETRY
        threads = resolve_construct_threads(config)
        with TELEMETRY.span("fit_mappers", features=len(sample_vals),
                            threads=threads):
            return find_bin_mappers(
                sample_vals, total_sample_cnt, config.max_bin,
                config.min_data_in_bin, config.min_data_in_leaf, cat_set,
                config.use_missing, config.zero_as_missing,
                num_threads=threads)

    # ------------------------------------------------------------------
    def _build_groups(self, reference: Optional["Dataset"],
                      sample_nonzero: Optional[List[np.ndarray]] = None,
                      sample_cnt: int = 0) -> None:
        """Assign features to groups.  With EFB disabled (or until the
        bundler finds conflicts-free bundles) every used feature is its
        own single-feature group with identity bin mapping.
        Multi-feature bundles follow the reference offset scheme
        (feature_group.h:34-51): group bin 0 is the shared default slot,
        each feature occupies [offset, offset+num_bin-1) with its
        default bin collapsed into slot 0."""
        from .telemetry import TELEMETRY
        with TELEMETRY.span("pack"):
            self._build_groups_impl(reference, sample_nonzero, sample_cnt)

    def _build_groups_impl(self, reference: Optional["Dataset"],
                           sample_nonzero: Optional[List[np.ndarray]],
                           sample_cnt: int) -> None:
        if reference is not None:
            self.features = reference.features
            self.group_num_bin = reference.group_num_bin
            self.group_is_multi = reference.group_is_multi
            self._bundles = reference._bundles
            # aligned datasets share the training set's storage layout
            # (group order AND nibble packing) — a packed train matrix
            # with an unpacked validation matrix would split every
            # device code path in two
            self.bin_layout = getattr(reference, "bin_layout", None)
            return
        bundles = _find_bundles(self, sample_nonzero, sample_cnt)
        pack_mode = resolve_bin_packing(self.config)
        if pack_mode != "8bit" and bundles:
            # narrowest-first group order (packing.py layout): groups
            # whose bin count fits a crumb come first (auto/2bit — the
            # three-section layout), then nibble-narrow groups, wide
            # groups follow.  4bit keeps the two-section sort so its
            # matrices stay byte-for-byte what r18 caches hold.  Stable
            # within each section (by first feature index, the legacy
            # order), so the reorder is deterministic; trees are
            # invariant to group numbering — histograms expand to
            # per-FEATURE space before the split finder ever sees them
            if pack_mode in ("auto", "2bit"):
                bundles.sort(key=lambda b: (
                    0 if _bundle_num_bin(self, b) <= CRUMB_MAX_BIN
                    else (1 if _bundle_num_bin(self, b) <= NIBBLE_MAX_BIN
                          else 2),
                    b[0]))
            else:
                bundles.sort(key=lambda b: (
                    0 if _bundle_num_bin(self, b) <= NIBBLE_MAX_BIN
                    else 1,
                    b[0]))
        self._bundles = bundles
        self.features = [None] * 0
        feats: List[FeatureView] = []
        self.group_num_bin = []
        self.group_is_multi = []
        for gidx, bundle in enumerate(bundles):
            if len(bundle) == 1:
                fidx = bundle[0]
                m = self.mappers[fidx]
                feats.append(FeatureView(fidx, gidx, 0, 0, m,
                                         collapsed_default=False))
                self.group_num_bin.append(m.num_bin)
                self.group_is_multi.append(False)
            else:
                total = 1  # bin 0 = shared default slot
                for sub, fidx in enumerate(bundle):
                    m = self.mappers[fidx]
                    offset = total
                    nb = m.num_bin
                    if m.default_bin == 0:
                        nb -= 1
                    feats.append(FeatureView(fidx, gidx, sub, offset, m,
                                             collapsed_default=True))
                    total += nb
                self.group_num_bin.append(total)
                self.group_is_multi.append(True)
        # order features by real index for stable downstream numbering
        feats.sort(key=lambda f: f.feature_idx)
        self.features = feats
        self.bin_layout = build_layout(
            pack_mode, self.group_num_bin,
            group_features=bundles,
            feature_names=self.feature_names)

    # ------------------------------------------------------------------
    def _bin_data(self, data: np.ndarray) -> None:
        self.group_bins = np.zeros(
            (self.num_data, self._storage_cols()), dtype=np.uint8)
        self._bin_rows_dense(data, 0)

    def _bin_rows_dense(self, data: np.ndarray, row_start: int) -> None:
        """Bin a dense float chunk into group_bins[row_start:...] —
        shared by whole-matrix construction and the PushRows streaming
        path (reference Dataset::PushOneRow via FeatureGroup::PushData,
        feature_group.h:128-136).  Native fast paths now cover ALL
        three feature classes — numerical (``ltpu_bin_dense[_mt]``),
        categorical lookup (``ltpu_bin_cat``) and EFB bundle
        offset/default-collapse writes (``ltpu_bin_bundle``) — with the
        per-feature Python mapper as the fallback for any feature the
        library can't take.

        Nibble-packed datasets bin through a bounded LOGICAL scratch
        chunk and pack it straight into the storage matrix
        (``ltpu_pack_nibbles`` / the numpy fallback): the full-width
        8-bit matrix never exists — peak extra memory is one scratch
        chunk, regardless of N."""
        from .telemetry import TELEMETRY
        out = self.group_bins[row_start:row_start + data.shape[0]]
        with TELEMETRY.span("bin", rows=int(data.shape[0])):
            if self.bin_layout is None:
                self._bin_rows_dense_into(data, out)
                return
            lay = self.bin_layout
            lib = self._native_lib()
            step = max(1, int(getattr(self.config,
                                      "streaming_chunk_rows", 65536)
                              or 65536))
            for i in range(0, data.shape[0], step):
                chunk = np.asarray(data[i:i + step])
                scratch = np.zeros((chunk.shape[0], self.num_groups),
                                   dtype=np.uint8)
                self._bin_rows_dense_into(chunk, scratch)
                lay.pack_rows(scratch, out=out[i:i + chunk.shape[0]],
                              lib=lib)

    def _bin_rows_dense_into(self, data: np.ndarray, out) -> None:
        native_feats = [f for f in self.features
                        if not f.is_categorical and not f.collapsed_default]
        rest = [f for f in self.features if f not in native_feats]
        lib = self._native_lib()
        xc = None
        if lib is not None and data.shape[0]:
            xc = np.ascontiguousarray(data, dtype=np.float64)
        if native_feats and xc is not None \
                and self._try_native_bin_dense(xc, out, native_feats, lib):
            pass
        else:
            rest = self.features
        for f in rest:
            if xc is not None \
                    and self._try_native_bin_rest(xc, out, f, lib):
                continue
            col = self.mappers[f.feature_idx].value_to_bin(
                data[:, f.feature_idx])
            if not f.collapsed_default:
                out[:, f.group] = col.astype(np.uint8)
            else:
                # bundle write: non-default values land at offset (+ the
                # default-at-0 slot removal), defaults stay at group bin 0.
                # (reference feature_group.h:128-136)
                gb = col + f.offset
                if f.mapper.default_bin == 0:
                    gb -= 1
                is_default = col == f.mapper.default_bin
                keep = ~is_default
                out[keep, f.group] = gb[keep].astype(np.uint8)

    # ------------------------------------------------------------------
    def _native_lib(self):
        """libltpu handle, or None when ``native_binning=false`` or the
        library is unavailable (build failure, missing g++ — the Python
        mapper path then serves every feature)."""
        cfg = self.config
        if cfg is not None and not getattr(cfg, "native_binning", True):
            return None
        from .native import get_lib
        return get_lib()

    def _try_native_bin_dense(self, xc: np.ndarray, out, feats,
                              lib) -> bool:
        """Fast path: numerical value->bin through the native library.

        Host numpy searchsorted runs ~20M values/s (it dominated the
        10.5M-row HIGGS prep, round-3 verdict weak #4); the compiled
        compare-count loop in native/src/bin_dense.cpp is BIT-IDENTICAL
        (same float64 'left'-side search as the reference's ValueToBin,
        bin.h:450-486) and ~10x faster, and ``ltpu_bin_dense_mt`` fans
        the row blocks over ``construct_threads`` host threads.
        ``feats`` is the numerical non-bundled subset of features this
        call handles.  Disable with ``native_binning=false``.  The old
        4096-row cutoff is gone: streaming chunks of any size take the
        native path now.

        (An accelerator-side compare-count formulation was measured and
        rejected for this environment: the remote-attach tunnel moves
        ~25 MB/s, so uploading the raw float matrix costs more than
        all of host binning.)
        """
        import ctypes
        if self.group_bins is None or xc.shape[0] == 0:
            return False
        fn = getattr(lib, "ltpu_bin_dense", None)
        if fn is None or not getattr(fn, "argtypes", None):
            return False                       # stale prebuilt lib
        n, f_total = xc.shape
        nfu = len(feats)
        bounds_parts = []
        off = [0]
        use_nan = np.zeros(nfu, np.uint8)
        nan_bin = np.zeros(nfu, np.int64)
        fidx = np.zeros(nfu, np.int64)
        for j, f in enumerate(feats):
            m = self.mappers[f.feature_idx]
            n_search = m.num_bin - (1 if m.missing_type == MISSING_NAN
                                    else 0)
            bounds_parts.append(np.asarray(
                m.bin_upper_bound[:n_search - 1], np.float64))
            off.append(off[-1] + len(bounds_parts[-1]))
            use_nan[j] = 1 if m.missing_type == MISSING_NAN else 0
            nan_bin[j] = m.num_bin - 1
            fidx[j] = f.feature_idx
        bounds_flat = (np.concatenate(bounds_parts) if off[-1]
                       else np.zeros(1, np.float64))
        boff = np.asarray(off, np.int64)
        res = np.empty((nfu, n), np.uint8)

        def p(a, t):
            return a.ctypes.data_as(ctypes.POINTER(t))

        fn_mt = getattr(lib, "ltpu_bin_dense_mt", None)
        threads = resolve_construct_threads(self.config)
        if fn_mt is not None:
            # threaded over disjoint row ranges — byte-identical to the
            # serial walk at every thread count (no accumulation)
            fn_mt(p(xc, ctypes.c_double), n, f_total,
                  p(fidx, ctypes.c_long), nfu,
                  p(bounds_flat, ctypes.c_double), p(boff, ctypes.c_long),
                  p(use_nan, ctypes.c_ubyte), p(nan_bin, ctypes.c_long),
                  p(res, ctypes.c_ubyte), threads)
        else:
            fn(p(xc, ctypes.c_double), n, f_total, p(fidx, ctypes.c_long),
               nfu, p(bounds_flat, ctypes.c_double), p(boff, ctypes.c_long),
               p(use_nan, ctypes.c_ubyte), p(nan_bin, ctypes.c_long),
               p(res, ctypes.c_ubyte))
        scatter = getattr(lib, "ltpu_scatter_cols", None)
        cols = np.asarray([f.group for f in feats], np.int64)
        if scatter is not None and getattr(scatter, "argtypes", None) \
                and out.flags.c_contiguous \
                and out.dtype == np.uint8 and out.shape[0] == n:
            # out.shape[0] == n guards the raw-pointer write: a clamped
            # group_bins slice (out-of-range push_rows row_start) must
            # fall through to the numpy path, which raises a broadcast
            # error instead of writing past the buffer
            # blocked-transpose write: numpy's strided per-column
            # assignment dominated wide-matrix prep (see bin_dense.cpp)
            scatter(p(res, ctypes.c_ubyte), nfu, n,
                    p(cols, ctypes.c_long), p(out, ctypes.c_ubyte),
                    out.shape[1])
        else:
            for j, f in enumerate(feats):
                out[:, f.group] = res[j]
        return True

    def _try_native_bin_rest(self, xc: np.ndarray, out, f, lib) -> bool:
        """Native value->bin for the features ``ltpu_bin_dense`` does
        not cover: categorical lookup (``ltpu_bin_cat``) and EFB bundle
        offset/default-collapse writes (``ltpu_bin_bundle``) — until
        round 11 these were the remaining per-feature Python loops in
        dense construction.  Returns False (leaving the Python
        fallback to run) when the library lacks the entry points or
        the output slice can't take a raw strided write."""
        import ctypes
        n = xc.shape[0]
        if n == 0:
            return True
        if not (out.flags.c_contiguous and out.dtype == np.uint8
                and out.shape[0] == n):
            # same clamped-slice guard as the scatter path above
            return False
        m = self.mappers[f.feature_idx]
        stride = out.shape[1]
        out_col = ctypes.cast(out.ctypes.data + f.group,
                              ctypes.POINTER(ctypes.c_ubyte))

        def p(a, t):
            return a.ctypes.data_as(ctypes.POINTER(t))

        if f.is_categorical:
            fn_cat = getattr(lib, "ltpu_bin_cat", None)
            if fn_cat is None or not m.categorical_2_bin:
                return False
            if getattr(m, "_cat_lut", None) is None:
                m._build_cat_cache()
            lut = np.ascontiguousarray(m._cat_lut, dtype=np.int32)
            if not f.collapsed_default:
                fn_cat(p(xc, ctypes.c_double), n, xc.shape[1],
                       f.feature_idx, p(lut, ctypes.c_int32), len(lut),
                       m.num_bin - 1, out_col, stride)
                return True
            fn_bundle = getattr(lib, "ltpu_bin_bundle", None)
            if fn_bundle is None:
                return False
            tmp = np.empty(n, np.uint8)
            fn_cat(p(xc, ctypes.c_double), n, xc.shape[1],
                   f.feature_idx, p(lut, ctypes.c_int32), len(lut),
                   m.num_bin - 1, p(tmp, ctypes.c_ubyte), 1)
            fn_bundle(p(tmp, ctypes.c_ubyte), n, f.offset,
                      m.default_bin, out_col, stride)
            return True
        # numerical feature inside a multi-feature bundle: bin through
        # the shared dense kernel into a scratch row, then apply the
        # bundle write
        fn = getattr(lib, "ltpu_bin_dense", None)
        fn_bundle = getattr(lib, "ltpu_bin_bundle", None)
        if fn is None or fn_bundle is None \
                or not getattr(fn, "argtypes", None):
            return False
        n_search = m.num_bin - (1 if m.missing_type == MISSING_NAN else 0)
        bounds = np.ascontiguousarray(
            m.bin_upper_bound[:n_search - 1], np.float64)
        if not len(bounds):
            bounds = np.zeros(1, np.float64)
            boff = np.asarray([0, 0], np.int64)
        else:
            boff = np.asarray([0, len(bounds)], np.int64)
        use_nan = np.asarray(
            [1 if m.missing_type == MISSING_NAN else 0], np.uint8)
        nan_bin = np.asarray([m.num_bin - 1], np.int64)
        fidx = np.asarray([f.feature_idx], np.int64)
        tmp = np.empty(n, np.uint8)
        fn(p(xc, ctypes.c_double), n, xc.shape[1],
           p(fidx, ctypes.c_long), 1, p(bounds, ctypes.c_double),
           p(boff, ctypes.c_long), p(use_nan, ctypes.c_ubyte),
           p(nan_bin, ctypes.c_long), p(tmp, ctypes.c_ubyte))
        fn_bundle(p(tmp, ctypes.c_ubyte), n, f.offset, m.default_bin,
                  out_col, stride)
        return True

    # ------------------------------------------------------------------
    def _bin_data_sparse(self, csc) -> None:
        """Bin a CSC matrix column-by-column into the packed (N, G)
        uint8 matrix: implicit zeros land in each feature's zero bin
        (== its default bin, the GreedyFindBin contract) without ever
        materializing a dense float column (reference sparse path:
        src/io/sparse_bin.hpp Push / feature_group.h:128-136).  The
        per-column loop fans over ``construct_threads`` host threads,
        one task per GROUP (bundled features share a group column, so
        group granularity keeps every output column single-writer);
        numpy's searchsorted releases the GIL, and the result is
        byte-identical at every thread count."""
        from .telemetry import TELEMETRY
        N = self.num_data
        lay = self.bin_layout
        out = np.zeros((N, self._storage_cols()), dtype=np.uint8)
        indptr, indices, values = csc.indptr, csc.indices, csc.data

        def bin_feature(f) -> None:
            m = self.mappers[f.feature_idx]
            j = f.feature_idx
            rows = indices[indptr[j]:indptr[j + 1]]
            vals = values[indptr[j]:indptr[j + 1]]
            col = m.value_to_bin(vals.astype(np.float64))
            zero_bin = int(np.asarray(
                m.value_to_bin(np.zeros(1)))[0])
            if not f.collapsed_default:
                if zero_bin != 0:
                    if lay is not None:
                        lay.fill_group(out, f.group, zero_bin)
                    else:
                        out[:, f.group] = zero_bin
                cb = col.astype(np.uint8)
                if lay is not None:
                    lay.write_group(out, f.group, cb, rows=rows)
                else:
                    out[rows, f.group] = cb
            else:
                gb = col + f.offset
                if m.default_bin == 0:
                    gb -= 1
                keep = col != m.default_bin
                gbk = gb[keep].astype(np.uint8)
                if lay is not None:
                    lay.write_group(out, f.group, gbk, rows=rows[keep])
                else:
                    out[rows[keep], f.group] = gbk

        # task key = STORAGE byte column, not logical group: two
        # nibble-packed groups share a byte, and the read-modify-write
        # nibble updates need every byte single-writer under threading
        by_group: Dict[int, list] = {}
        for f in self.features:
            key = lay.byte_of(f.group) if lay is not None else f.group
            by_group.setdefault(key, []).append(f)

        def bin_group(feats) -> None:
            for f in feats:
                bin_feature(f)

        threads = resolve_construct_threads(self.config)
        with TELEMETRY.span("bin", rows=int(N)):
            if threads > 1 and len(by_group) > 1:
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(
                        max_workers=min(threads, len(by_group))) as ex:
                    # consume the iterator so a worker exception
                    # propagates instead of vanishing
                    list(ex.map(bin_group, by_group.values()))
            else:
                for feats in by_group.values():
                    bin_group(feats)
        self.group_bins = out

    # ------------------------------------------------------------------
    def _resolve_monotone(self, config: Config) -> None:
        mc = config.monotone_constraints
        if mc:
            arr = np.zeros(len(self.features), dtype=np.int8)
            for j, f in enumerate(self.features):
                if f.feature_idx < len(mc):
                    arr[j] = mc[f.feature_idx]
            self.monotone_constraints = arr
        else:
            self.monotone_constraints = None

    # ------------------------------------------------------------------
    def feature_bin_maps(self) -> Tuple[np.ndarray, np.ndarray]:
        """Device gather map from group histograms to per-feature
        histograms.

        Returns ``(bin_map, needs_fix)`` where ``bin_map[f, b]`` is the
        flattened (group, group_bin) index holding feature ``f``'s bin
        ``b`` (or -1 when the bin's count must be reconstructed from leaf
        totals — the FixHistogram path, dataset.cpp:776-795), and
        ``needs_fix[f]`` is that reconstructed bin's index (or -1)."""
        F = self.num_features
        B = self.max_feature_bin
        bin_map = np.full((F, B), -1, dtype=np.int32)
        fix_bin = np.full(F, -1, dtype=np.int32)
        for j, f in enumerate(self.features):
            for b in range(f.num_bin):
                if not f.collapsed_default:
                    bin_map[j, b] = f.group * self.max_group_bin + b
                else:
                    if b == f.mapper.default_bin:
                        fix_bin[j] = b
                        continue
                    gb = b + f.offset - (1 if f.mapper.default_bin == 0 else 0)
                    bin_map[j, b] = f.group * self.max_group_bin + gb
        return bin_map, fix_bin

    @property
    def max_group_bin(self) -> int:
        return max(self.group_num_bin) if self.group_num_bin else 1

    @property
    def max_feature_bin(self) -> int:
        return max((f.num_bin for f in self.features), default=1)

    # ------------------------------------------------------------------
    def feature_meta_arrays(self) -> Dict[str, np.ndarray]:
        """Per-used-feature metadata arrays shipped to the device split
        finder."""
        F = self.num_features
        num_bin = np.array([f.num_bin for f in self.features], dtype=np.int32)
        default_bin = np.array([f.default_bin for f in self.features],
                               dtype=np.int32)
        missing_type = np.array([f.missing_type for f in self.features],
                                dtype=np.int32)
        is_cat = np.array([f.is_categorical for f in self.features],
                          dtype=bool)
        mono = (self.monotone_constraints if self.monotone_constraints
                is not None else np.zeros(F, dtype=np.int8))
        return dict(num_bin=num_bin, default_bin=default_bin,
                    missing_type=missing_type, is_categorical=is_cat,
                    monotone=mono.astype(np.int32))

    # ------------------------------------------------------------------
    def real_feature_index(self, inner_idx: int) -> int:
        return self.features[inner_idx].feature_idx

    def inner_feature_index(self, real_idx: int) -> int:
        for j, f in enumerate(self.features):
            if f.feature_idx == real_idx:
                return j
        return -1

    def feature_infos(self) -> List[str]:
        return [m.feature_info_str() for m in self.mappers]


# ---------------------------------------------------------------------------
def _bundle_num_bin(ds: "Dataset", bundle: List[int]) -> int:
    """A bundle's group bin count — the same arithmetic the
    `_build_groups_impl` packing loop applies (shared default slot +
    per-feature widths minus the default-at-0 removals)."""
    if len(bundle) == 1:
        return ds.mappers[bundle[0]].num_bin
    total = 1
    for fidx in bundle:
        m = ds.mappers[fidx]
        total += m.num_bin - (1 if m.default_bin == 0 else 0)
    return total


def _sample_feature_values(data: np.ndarray, sample_cnt: int, seed: int
                           ) -> Tuple[List[np.ndarray], int,
                                      List[np.ndarray]]:
    """Row-sample then collect per-feature non-zero (and NaN) values for
    bin finding (reference dataset_loader.cpp:649-754 sampling +
    bin.cpp:207 contract: zeros are implicit).  Also returns per-feature
    non-zero row indices within the sample, feeding the EFB bundler."""
    num_data = data.shape[0]
    if num_data > sample_cnt:
        rng = np.random.RandomState(seed)
        idx = rng.choice(num_data, size=sample_cnt, replace=False)
        idx.sort()
        sample = data[idx]
    else:
        sample = data
    from .data_loader import split_sample_columns
    out, rows = split_sample_columns(sample)
    return out, sample.shape[0], rows


def _sample_feature_values_sparse(csc, sample_cnt: int, seed: int
                                  ) -> Tuple[List[np.ndarray], int,
                                             List[np.ndarray]]:
    """Sparse analog of :func:`_sample_feature_values`: row-sample the
    CSC matrix (via a CSR slice) and collect each column's stored
    values/rows — zeros stay implicit, exactly the reference sampling
    contract (dataset_loader.cpp:649-754 + bin.cpp:207)."""
    num_data = csc.shape[0]
    if num_data > sample_cnt:
        rng = np.random.RandomState(seed)
        idx = rng.choice(num_data, size=sample_cnt, replace=False)
        idx.sort()
        sample = csc.tocsr()[idx].tocsc()
        sample.sort_indices()
    else:
        sample = csc
    total = sample.shape[0]
    indptr, indices, values = sample.indptr, sample.indices, sample.data
    out = []
    rows = []
    for j in range(sample.shape[1]):
        v = values[indptr[j]:indptr[j + 1]].astype(np.float64)
        r = indices[indptr[j]:indptr[j + 1]]
        keep = np.isnan(v) | (np.abs(v) > 1e-35)
        out.append(v[keep])
        rows.append(r[keep].astype(np.int64))
    return out, total, rows


def _find_bundles(ds: Dataset, sample_nonzero: Optional[List[np.ndarray]]
                  = None, sample_cnt: int = 0) -> List[List[int]]:
    """Exclusive feature bundling (reference dataset.cpp:66-210
    FindGroups/FastFeatureBundling): greedily pack mutually-exclusive
    sparse features into shared bin columns, tolerating
    ``max_conflict_rate`` collisions, with the 256-bins-per-group cap
    the GPU learner imposes (dataset.cpp:76,90-91) — which is exactly
    the uint8 packed-column constraint here.

    ``sample_nonzero``: per-feature sorted row indices (within the
    sample) where the feature is non-default.  When absent (e.g.
    reloaded binary cache) falls back to single-feature groups.
    """
    cfg = ds.config
    if (sample_nonzero is None or cfg is None or not cfg.enable_bundle
            or not cfg.is_enable_bundle):
        return [[fidx] for fidx in ds.used_features]

    # NOTE on packing: bundling is IDENTICAL across every bin_packing
    # mode.  Capping bundles at a nibble's 16 bins was tried and
    # rejected — a different bundling reconstructs default-bin mass
    # through a different FixHistogram subtraction order, which breaks
    # the byte-identical-trees bar by f32 ulps.  Wide bundles instead
    # split OUT of the packed section into byte-wide storage columns
    # (packing.py two-section layout), preserving exact parity.
    max_group_bins = 256
    max_conflict = int(cfg.max_conflict_rate * max(sample_cnt, 1))
    # order by non-zero count descending (densest placed first,
    # mirroring the reference's sorted-by-count greedy pass)
    order = sorted(ds.used_features,
                   key=lambda f: -len(sample_nonzero[f]))
    bundles: List[List[int]] = []
    bundle_rows: List[np.ndarray] = []
    bundle_bins: List[int] = []
    bundle_conflicts: List[int] = []
    for fidx in order:
        m = ds.mappers[fidx]
        nb = m.num_bin - (1 if m.default_bin == 0 else 0)
        rows = sample_nonzero[fidx]
        placed = False
        # a feature covering most rows can't bundle with anything
        if len(rows) * 2 < sample_cnt:
            for bi in range(len(bundles)):
                if bundle_bins[bi] + nb > max_group_bins:
                    continue
                conflicts = np.intersect1d(bundle_rows[bi], rows,
                                           assume_unique=True).size
                if bundle_conflicts[bi] + conflicts <= max_conflict:
                    bundles[bi].append(fidx)
                    bundle_rows[bi] = np.union1d(bundle_rows[bi], rows)
                    bundle_bins[bi] += nb
                    bundle_conflicts[bi] += conflicts
                    placed = True
                    break
        if not placed:
            bundles.append([fidx])
            bundle_rows.append(rows)
            bundle_bins.append(nb + 1)  # + shared default slot
            bundle_conflicts.append(0)
    # stable order: by first (lowest) feature index
    for b in bundles:
        b.sort()
    bundles.sort(key=lambda b: b[0])
    return bundles
