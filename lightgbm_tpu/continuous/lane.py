"""The continuous-training lane: a train→evaluate→publish daemon
closing the loop between checkpoints (r12), streaming construction
(r11) and the serving registry (r14).

One :class:`ContinuousLane` supervises one served model name.  Each
*cycle* walks a four-phase state machine::

    ingest -> train -> eval -> publish
      |        |         |        |
      v        v         v        v
    load new  continue/  gate vs  hot-publish into the registry
    slices,   refit a    current  (warm-before-cutover), or
    drift     candidate  model    quarantine the candidate

Crash safety is ledger-based: every phase COMMITS its outputs to
``ledger.json`` (atomic tmp+fsync+rename, the r12 writer) before the
next phase starts, and every phase's work is a deterministic function
of the ledger + the slice files still sitting in the ingest directory.
A SIGKILL at ANY instant therefore resumes by re-entering the recorded
phase and replaying it — same slices, same tail holdout split, same
training (mid-cycle checkpoints via ``continuous_checkpoint_freq``
make the replay cheap; without them the cycle re-trains from its
start) — and publishes a byte-identical model
(``tests/test_continuous.py`` pins this with real SIGKILLs through the
``continuous.cycle`` fault seam).

Publish is gated: the candidate and the currently accepted model are
both scored on the cycle's held-out eval rows, and the candidate may
not regress the gated metric past
``continuous_publish_max_regression``.  Rejected candidates are
QUARANTINED (recorded in the ledger with the metrics that damned
them; the next cycle continues from the last good model).  After a
publish, the serving side can feed live quality back through
``report_live_metric`` (or ``POST /continuous`` with
``{"action": "live_metric", "value": ...}``); a live regression past
the same bound auto-rolls the registry back to the previous version
and quarantines the published candidate.

Control + observability ride the SAME listener as ``/metrics`` and
``/predict/<model>``: ``GET /continuous`` returns the lane status,
``POST /continuous`` takes ``pause`` / ``resume`` / ``force_cycle`` /
``live_metric`` actions.  Spans (``continuous_cycle`` + one per
phase), counters (cycles, rows, publishes, rejects, rollbacks, drift)
and the ``continuous_cycle_ms`` histogram are in the
docs/OBSERVABILITY.md glossary; a cycle failure dumps the crash
flight recorder naming the phase it died in.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..config import Config
from ..reliability import checkpoint as _ckpt
from ..reliability.faults import FAULTS
from ..telemetry import TELEMETRY
from ..utils.log import Log
from . import ingest as _ingest

LEDGER_NAME = "ledger.json"
LEDGER_SCHEMA = 1
BASE_MODEL = "model_base.txt"
PHASES = ("ingest", "train", "eval", "publish")

# objectives whose eval gate the lane can score without side metadata
# (ranking needs query boundaries per slice — not carried by slices
# yet, so the lane refuses at construction instead of gating on a
# meaningless metric)
_UNSUPPORTED_OBJECTIVES = ("lambdarank",)


class ContinuousLane:
    """Supervised train→evaluate→publish loop for one served model.

    Args:
      config: the daemon :class:`~lightgbm_tpu.config.Config`
        (``continuous_*`` knobs; ``continuous_ingest_dir`` required).
      registry: the serving :class:`ModelRegistry` accepted candidates
        hot-publish into (rollback flips its pointer back).
      name: served model name (the ``/predict/<name>`` route).
      base_model: Booster, model-file path, or None — the model the
        first cycle continues/refits from; None falls back to
        ``config.input_model``.
      base_data / base_label: in-memory base training matrix, or None
        to load ``config.data``.  The base dataset's bin mappers are
        FROZEN: every ingested slice bins into this bin space.
      train_params: the parameter dict each continue-cycle trains
        under (objective, num_leaves, ... — the daemon's CLI params in
        ``task=serve``).  Must be identical across restarts: the cycle
        replay guarantee fingerprints training on it.
    """

    def __init__(self, config: Config, registry=None,
                 name: str = "model", base_model=None,
                 base_data=None, base_label=None,
                 train_params: Optional[Dict[str, Any]] = None,
                 clock=None):
        self.config = config
        self.registry = registry
        self.name = name
        # injectable wall clock (tests drive the scheduled-cycle timer
        # without sleeping); the ledger stores absolute times from it
        self._clock = clock or time.time
        self.train_params = dict(train_params or {})
        self._base_model_arg = base_model
        self._base_data = base_data
        self._base_label = base_label
        self._base_core = None
        self._metric_cfg = Config.from_params(self.train_params) \
            if self.train_params else Config()
        if self._metric_cfg.objective in _UNSUPPORTED_OBJECTIVES:
            raise ValueError(
                f"continuous lane: objective "
                f"{self._metric_cfg.objective!r} is not supported yet "
                "(the eval gate needs per-slice query metadata)")
        self.ingest_dir = config.continuous_ingest_dir
        if not self.ingest_dir:
            raise ValueError("continuous lane needs "
                             "continuous_ingest_dir")
        self.state_dir = config.continuous_state_dir or \
            os.path.join(self.ingest_dir, ".continuous")
        os.makedirs(self.state_dir, exist_ok=True)
        self._cycle_lock = threading.RLock()
        # small mutation lock so status()/control reads never block
        # behind a training phase holding the cycle lock
        self._ledger_lock = threading.Lock()
        # serializes publish-state transitions ONLY (the publish
        # phase and rollbacks): a live-metric rollback must be able
        # to pull a bad model while a training phase holds the cycle
        # lock for minutes
        self._publish_lock = threading.RLock()
        self._ledger = self._load_ledger()
        # accumulated training slices (train portions only), rebuilt
        # deterministically from the ledger on restart
        self._acc: List[Tuple[np.ndarray, np.ndarray]] = []
        self._acc_names: List[str] = []
        self._paused = False
        self._stop = threading.Event()
        self._force = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._routes_mounted = False
        self.last_cycle: Optional[dict] = None
        # per-phase deadline watch (docs/RELIABILITY.md, deadline
        # watchdog): each cycle phase re-arms a one-shot monitor
        # token; a phase stalled past watchdog_continuous_s dumps
        # all-thread stacks + counts a stall (observability — the
        # phase is not interrupted).  0 (default) = unwatched
        self._watchdog_s = float(getattr(
            config, "watchdog_continuous_s", 0.0) or 0.0)
        self._watch_token = None

    # -- paths / ledger ------------------------------------------------
    def _p(self, *parts: str) -> str:
        return os.path.join(self.state_dir, *parts)

    def _load_ledger(self) -> dict:
        path = self._p(LEDGER_NAME)
        if os.path.exists(path):
            with open(path) as f:
                led = json.load(f)
            if led.get("schema") != LEDGER_SCHEMA:
                raise ValueError(
                    f"continuous ledger {path} has schema "
                    f"{led.get('schema')} (this build reads "
                    f"{LEDGER_SCHEMA})")
            return led
        return {
            "schema": LEDGER_SCHEMA,
            "cycle": 1,
            "phase": "idle",
            "cycle_slices": [],      # in-flight cycle's slice names
            "cycle_decision": None,  # committed eval-gate outcome
            "processed": [],         # [{name, cycle, rows}] in order
            "last_good": BASE_MODEL,
            "published": [],         # publish stack, current last
            "quarantined": [],
        }

    def _commit(self, **updates) -> None:
        """Atomically persist the ledger (the phase-commit point: the
        crash-replay contract is 'everything before the last commit
        is durable, everything after replays')."""
        self._commit_mutate(lambda led: led.update(updates))

    def _commit_mutate(self, fn) -> None:
        """Read-modify-write commit: ``fn(ledger)`` runs UNDER the
        ledger lock, so increments (the serving-drift tally) cannot
        lose updates to a concurrent phase commit.  The durable write
        happens INSIDE the lock too — serialize-then-write-outside
        would let two racing commits rename in the wrong order and
        leave the OLDER serialization as the on-disk ledger a crash
        replays from."""
        with self._ledger_lock:
            fn(self._ledger)
            text = json.dumps(self._ledger, indent=1, sort_keys=True)
            _ckpt.atomic_write_text(self._p(LEDGER_NAME), text)

    # -- base dataset / model ------------------------------------------
    def _base(self):
        """Construct (once) the base dataset whose bin mappers every
        ingested slice binds to."""
        if self._base_core is not None:
            return self._base_core
        from ..basic import Dataset
        cfg = self._metric_cfg
        if self._base_data is not None:
            ds = Dataset(self._base_data, label=self._base_label,
                         params=self.train_params, free_raw_data=False)
        elif self.config.data:
            ds = Dataset(self.config.data, params=self.train_params,
                         free_raw_data=False)
        else:
            raise ValueError(
                "continuous lane needs a base dataset: pass "
                "base_data= or set data=<file>")
        self._base_core = ds.construct(cfg)
        if self.config.continuous_mode == "continue":
            if self._base_core._raw_data is None:
                raise ValueError(
                    "continuous_mode=continue needs the base "
                    "dataset's raw rows to seed continued-training "
                    "scores — two-round streaming bases cannot "
                    "continue-train (use continuous_mode=refit, or a "
                    "non-streaming base)")
            md = self._base_core.metadata
            if md.weight is not None or md.init_score is not None:
                # refusing loudly beats silently training every cycle
                # unweighted: append_construct carries labels only, so
                # a weighted base would produce systematically
                # different candidates than the operator configured
                raise ValueError(
                    "continuous lane: the base dataset carries row "
                    "weights/init_score, which the append-construct "
                    "cycle datasets do not propagate yet — continue "
                    "cycles would train unweighted. Drop the weights "
                    "or use continuous_mode=refit.")
        return self._base_core

    def _base_model_path(self) -> str:
        """Materialize the base model into the state dir exactly once
        (byte-stable across restarts: never rewritten when present)."""
        path = self._p(BASE_MODEL)
        if os.path.exists(path):
            return path
        src = self._base_model_arg
        if src is None and self.config.input_model:
            src = self.config.input_model
        if src is None:
            raise ValueError(
                "continuous lane needs a base model: pass base_model= "
                "or set input_model=<file>")
        if isinstance(src, str):
            with open(src) as f:
                text = f.read()
        else:
            text = src.model_to_string()
        _ckpt.atomic_write_text(path, text)
        return path

    def _booster(self, model_name: str):
        from ..booster import Booster
        return Booster(config=self._metric_cfg,
                       model_file=self._p(model_name))

    # -- accumulated slice state ---------------------------------------
    def _restore_accumulated(self) -> None:
        """Re-load every processed slice's TRAIN rows in ledger order
        (deterministic: name order within each cycle was the discovery
        order the ledger recorded)."""
        want = [rec["name"] for rec in self._ledger["processed"]]
        if self._acc_names == want:
            return
        self._acc = []
        self._acc_names = []
        for rec in self._ledger["processed"]:
            X, y = _ingest.load_slice(
                os.path.join(self.ingest_dir, rec["name"]),
                self._metric_cfg)
            Xt, yt, _Xe, _ye = _ingest.holdout_split(
                X, y, self.config.continuous_eval_holdout)
            self._acc.append((Xt, yt))
            self._acc_names.append(rec["name"])

    # -- cycle phases ---------------------------------------------------
    def _phase(self, phase: str, cycle: int) -> None:
        """Enter a cycle phase: the ``continuous.cycle`` fault seam
        fires BEFORE the phase's side effects (kill/OOM injection
        lands between commits, where recovery must replay), and the
        deadline watchdog re-arms for the new phase (the previous
        phase's token is cancelled — it completed by reaching here)."""
        from ..reliability.watchdog import WATCHDOG
        WATCHDOG.cancel(self._watch_token)
        self._watch_token = WATCHDOG.watch(
            f"continuous_{phase}", self._watchdog_s,
            seam="continuous.cycle")
        FAULTS.fault_point("continuous.cycle")
        TELEMETRY.gauge("continuous_phase", f"{phase}@{cycle}")

    def _load_cycle_slices(self, names,
                           count_drift: bool = False) -> List[dict]:
        """(Re)load the cycle's slices, cut the deterministic
        train/eval tail split and compute per-slice drift.  Drift
        counters/warnings only fire on the FIRST (ingest-phase) pass
        — a crash-resume reload recomputes silently."""
        base = self._base()
        out = []
        for name in names:
            X, y = _ingest.load_slice(
                os.path.join(self.ingest_dir, name), self._metric_cfg)
            Xt, yt, Xe, ye = _ingest.holdout_split(
                X, y, self.config.continuous_eval_holdout)
            out.append({"name": name, "X": X, "y": y,
                        "Xt": Xt, "yt": yt, "Xe": Xe, "ye": ye,
                        "drift": _ingest.drift_check(
                            base, X, name, count=count_drift)})
        return out

    def _drift_refit_updates(self, drifted_slices: int,
                             led: dict) -> dict:
        """Ingest-commit updates for the drift-triggered base refit
        (``continuous_drift_refit_threshold``): the cumulative
        drifted-slice tally lives in the LEDGER (so a crash-replayed
        cycle decides the same mode), and once it crosses the
        threshold the cycle's committed mode flips to ``refit`` —
        leaf values refreshed through the model's REAL-VALUED
        thresholds, immune to the frozen mappers' edge-bin clamping —
        then the tally resets.  Threshold 0 (default) keeps the
        r15 warn-and-count-only behavior.  The tally is read from
        ``led`` (the commit-locked ledger view) so serving-drift
        reports (:meth:`report_serving_drift`) landing during ingest
        are folded in, never overwritten (``led`` is required — an
        unlocked ``self._ledger`` read here would reintroduce the
        lost-update race the locked commit exists to fix)."""
        thr = int(getattr(self.config,
                          "continuous_drift_refit_threshold", 0) or 0)
        tally = int(led.get("drift_slices", 0)) \
            + int(drifted_slices)
        mode = self.config.continuous_mode
        if thr > 0 and tally >= thr:
            mode = "refit"
            tally = 0
            if TELEMETRY.on:
                TELEMETRY.add("continuous_drift_refits", 1)
            TELEMETRY.journal.emit(
                "drift_refit", seam="continuous.cycle",
                lane=self.name, threshold=thr)
            Log.warning(
                f"continuous lane {self.name!r}: drifted-slice tally "
                f"reached continuous_drift_refit_threshold={thr} — "
                "this cycle REFITS leaf values on the fresh labels "
                "(real-valued thresholds, no frozen-mapper clamping) "
                "instead of continue-training, then the tally resets "
                "(docs/CONTINUOUS_TRAINING.md, drift semantics)")
        return {"drift_slices": tally, "cycle_mode": mode}

    def report_serving_drift(self, model: str = "",
                             worst_feature: Optional[int] = None,
                             psi: Optional[float] = None,
                             **detail) -> int:
        """SERVING-side drift report (the quality monitors'
        drift→refit hook, docs/MODEL_MONITORING.md): live traffic
        drifting past ``quality_drift_refit_threshold`` increments the
        SAME ledger-committed drift tally ingest drift feeds, so
        ``continuous_drift_refit_threshold`` can flip a future cycle
        to refit on what the model actually serves — not only on what
        the ingest directory happens to receive.  Atomic
        read-modify-write under the ledger lock (never blocks behind
        a training phase; the cycle lock is not taken).  Returns the
        new tally."""
        out = {}

        def bump(led):
            led["drift_slices"] = int(led.get("drift_slices", 0)) + 1
            led["serving_drift_reports"] = int(
                led.get("serving_drift_reports", 0)) + 1
            out["tally"] = led["drift_slices"]
        self._commit_mutate(bump)
        if TELEMETRY.on:
            TELEMETRY.add("continuous_serving_drift_reports", 1)
        thr = int(getattr(self.config,
                          "continuous_drift_refit_threshold", 0) or 0)
        Log.warning(
            f"continuous lane {self.name!r}: SERVING drift report"
            + (f" from model {model!r}" if model else "")
            + (f" (feature f{worst_feature}, PSI {psi:g})"
               if psi is not None else "")
            + f" — ledger drift tally now {out['tally']}"
            + (f" of refit threshold {thr}" if thr > 0
               else " (continuous_drift_refit_threshold=0: counted, "
                    "no refit trigger)"))
        return out["tally"]

    def _cycle_train_params(self, cycle: int) -> Dict[str, Any]:
        p = dict(self.train_params)
        p["num_iterations"] = self.config.continuous_iterations
        freq = self.config.continuous_checkpoint_freq
        if freq > 0:
            p["checkpoint_freq"] = freq
            p["checkpoint_path"] = self._p(f"ckpt_cycle_{cycle}")
            p["resume"] = "auto"
        else:
            # no mid-cycle checkpoints: a killed cycle replays from
            # its start (still byte-identical, just recomputed)
            p["checkpoint_freq"] = -1
            p["resume"] = "off"
        return p

    def _train_candidate(self, cycle: int, slices: List[dict]) -> str:
        """Train (or refit) this cycle's candidate and atomically
        persist it as ``model_cycle_<cycle>.txt``.  Deterministic
        given the ledger: replaying after a kill produces the same
        bytes (mid-cycle checkpoints only shortcut the replay)."""
        span = TELEMETRY.start_span("continuous_train", cycle=cycle)
        try:
            init_path = self._p(self._ledger["last_good"])
            # the MODE is a ledger fact committed at ingest (the
            # drift-refit trigger may override the configured mode for
            # this one cycle) — reading config here would let a crash
            # replay train a different candidate than the first pass
            mode = self._ledger.get("cycle_mode") \
                or self.config.continuous_mode
            if mode == "refit":
                Xs = [s["Xt"] for s in slices if len(s["Xt"])]
                ys = [s["yt"] for s in slices if len(s["yt"])]
                if not Xs:
                    raise ValueError(
                        "continuous refit cycle has no train rows")
                from ..booster import Booster
                cand = Booster(config=self._metric_cfg,
                               model_file=init_path)
                cand.refit(np.concatenate(Xs, axis=0),
                           np.concatenate(ys, axis=0),
                           dict(self.train_params))
            else:
                from ..engine import train as _train
                base = self._base()
                self._restore_accumulated()
                new = [(s["Xt"], s["yt"]) for s in slices
                       if len(s["Xt"])]
                parts = self._acc + new
                core = _ingest.append_construct(
                    base, [x for x, _ in parts],
                    [y for _, y in parts],
                    base_raw=base._raw_data)
                cand = _train(self._cycle_train_params(cycle), core,
                              init_model=init_path,
                              verbose_eval=False)
            path = self._p(f"model_cycle_{cycle}.txt")
            text = cand.model_to_string()
            _ckpt.atomic_write_text(path, text)
            prof = getattr(cand, "quality_profile", None)
            if prof is not None:
                # quality=on rode the cycle's train params: persist
                # the candidate's reference profile beside its model
                # file so the hot-publish arms fresh drift monitors
                # for the new version (refit cycles carry none — the
                # refit path has no constructed cycle dataset to
                # profile; docs/MODEL_MONITORING.md)
                from ..quality import model_fingerprint, profile_path
                if model_fingerprint(text) == prof.fingerprint:
                    prof.save(profile_path(path))
            return os.path.basename(path)
        finally:
            TELEMETRY.end_span(span)

    # -- eval gate ------------------------------------------------------
    def _metric(self, booster, X: np.ndarray, y: np.ndarray
                ) -> Tuple[float, bool, str]:
        """Score ``booster`` on (X, y) with the gated metric: the
        configured metric (or the objective's default), evaluated on
        converted predictions — (value, bigger_is_better, name)."""
        import jax.numpy as jnp

        from ..dataset import Metadata
        from ..metrics import create_metrics
        pred = np.asarray(booster.predict(X))
        if pred.ndim == 2 and pred.shape[1] > 1:
            # multiclass probabilities: score logloss directly (the
            # Metric classes expect raw scores to softmax themselves)
            li = np.clip(y.astype(np.int64), 0, pred.shape[1] - 1)
            pt = np.clip(pred[np.arange(len(y)), li], 1e-15, None)
            return float(np.mean(-np.log(pt))), False, "multi_logloss"
        metrics = create_metrics(self._metric_cfg)
        m = next((mm for mm in metrics
                  if not mm.name.startswith(("multi_", "ndcg", "map"))),
                 None)
        if m is None:
            from ..metrics import L2Metric
            m = L2Metric(self._metric_cfg)
        if m.name in ("cross_entropy_lambda", "kldiv"):
            # these two metrics apply the output link THEMSELVES
            # (score -> hhat / sigmoid): feeding converted predictions
            # would double-transform; hand them raw scores like the
            # training-time eval does
            pred = np.asarray(booster.predict(X, raw_score=True))
        meta = Metadata(len(y))
        meta.set_label(y)
        m.init(meta, len(y))
        val = m.eval(jnp.asarray(pred.reshape(-1),
                                 dtype=jnp.float32))[0]
        return float(val), bool(m.bigger_is_better), m.name

    def _gate(self, cycle: int, cand_name: str,
              slices: List[dict]) -> dict:
        """Score candidate vs the current (last good) model on the
        cycle's held-out rows and commit the publish/quarantine
        decision."""
        span = TELEMETRY.start_span("continuous_eval", cycle=cycle)
        try:
            Xe = [s["Xe"] for s in slices if len(s["Xe"])]
            ye = [s["ye"] for s in slices if len(s["ye"])]
            decision = {"cycle": cycle, "candidate": cand_name,
                        "publish_unix": time.time()}
            if not Xe or self.config.continuous_eval_holdout <= 0:
                # no held-out rows: the gate cannot measure, publish
                decision.update(accept=True, metric=None,
                                candidate_metric=None,
                                current_metric=None)
                return decision
            X = np.concatenate(Xe, axis=0)
            y = np.concatenate(ye, axis=0)
            cand_v, bigger, mname = self._metric(
                self._booster(cand_name), X, y)
            cur_v, _, _ = self._metric(
                self._booster(self._ledger["last_good"]), X, y)
            regression = (cur_v - cand_v) if bigger else (cand_v - cur_v)
            accept = regression <= \
                self.config.continuous_publish_max_regression
            decision.update(
                accept=bool(accept), metric=mname,
                bigger_is_better=bigger,
                candidate_metric=cand_v, current_metric=cur_v,
                regression=round(float(regression), 12),
                eval_rows=int(len(y)))
            TELEMETRY.gauge("continuous_last_eval_metric", cand_v)
            return decision
        finally:
            TELEMETRY.end_span(span)

    # -- publish / quarantine / rollback --------------------------------
    def _publish(self, cycle: int, decision: dict,
                 slices_meta: List[dict]) -> dict:
        """Act on the committed gate decision: hot-publish the
        accepted candidate (warm-before-cutover, zero failed
        responses — the r14 registry guarantee) or quarantine it;
        then retire the cycle in the ledger."""
        span = TELEMETRY.start_span("continuous_publish", cycle=cycle)
        tm = TELEMETRY
        with self._publish_lock:
            return self._publish_locked(cycle, decision, slices_meta,
                                        span, tm)

    def _publish_locked(self, cycle, decision, slices_meta, span, tm):
        try:
            cand = decision["candidate"]
            processed = self._ledger["processed"] + slices_meta
            if decision["accept"]:
                version = None
                if self.registry is not None:
                    entry = self.registry.publish(
                        self.name, self._p(cand),
                        published_unix=decision["publish_unix"],
                        eval_metric=decision.get("candidate_metric"),
                        source="continuous")
                    version = entry.version
                published = self._ledger["published"] + [{
                    "cycle": cycle, "model": cand, "version": version,
                    "metric": decision.get("candidate_metric"),
                    "metric_name": decision.get("metric"),
                    "bigger_is_better": decision.get(
                        "bigger_is_better", False),
                    "unix": decision["publish_unix"],
                }]
                self._commit(phase="idle", cycle=cycle + 1,
                             cycle_slices=[], cycle_decision=None,
                             processed=processed, published=published,
                             last_good=cand)
                if tm.on:
                    tm.add("continuous_publishes", 1)
                Log.info(
                    f"continuous lane {self.name!r}: cycle {cycle} "
                    f"published {cand}"
                    + (f" as v{version}" if version else "")
                    + (f" ({decision['metric']}="
                       f"{decision['candidate_metric']:g} vs current "
                       f"{decision['current_metric']:g})"
                       if decision.get("metric") else ""))
            else:
                quarantined = self._ledger["quarantined"] + [{
                    "cycle": cycle, "model": cand,
                    "reason": "eval gate",
                    "metric": decision.get("metric"),
                    "candidate_metric": decision.get(
                        "candidate_metric"),
                    "current_metric": decision.get("current_metric"),
                    "regression": decision.get("regression"),
                }]
                self._commit(phase="idle", cycle=cycle + 1,
                             cycle_slices=[], cycle_decision=None,
                             processed=processed,
                             quarantined=quarantined)
                if tm.on:
                    tm.add("continuous_publish_rejects", 1)
                    tm.add("continuous_quarantined", 1)
                tm.journal.emit(
                    "quarantine", seam="continuous.cycle",
                    lane=self.name, cycle=cycle, model=cand,
                    reason="eval gate")
                Log.warning(
                    f"continuous lane {self.name!r}: cycle {cycle} "
                    f"candidate {cand} QUARANTINED by the eval gate "
                    f"({decision.get('metric')}: candidate "
                    f"{decision.get('candidate_metric')} vs current "
                    f"{decision.get('current_metric')}, regression "
                    f"{decision.get('regression')} > "
                    f"{self.config.continuous_publish_max_regression:g}"
                    "); continuing from the last good model")
            return decision
        finally:
            TELEMETRY.end_span(span)

    def report_live_metric(self, value: float) -> bool:
        """Serving-side live-quality hook: compare ``value`` against
        the eval metric the current version published at; a
        regression past ``continuous_publish_max_regression``
        auto-rolls the registry back and quarantines the published
        candidate.  Returns True when a rollback fired.

        Serialized against the PUBLISH phase only (not the whole
        cycle): pulling a bad model must not wait minutes behind an
        in-flight training phase.  A cycle mid-train keeps building
        its candidate from the pre-rollback model — the eval gate
        re-reads ``last_good`` and judges it against the restored
        one."""
        with self._publish_lock:
            published = self._ledger["published"]
            if not published:
                return False
            cur = published[-1]
            if cur.get("metric") is None:
                return False
            bigger = bool(cur.get("bigger_is_better", False))
            regression = (cur["metric"] - value) if bigger \
                else (value - cur["metric"])
            if regression <= \
                    self.config.continuous_publish_max_regression:
                return False
            self._rollback(reason="live metric regression",
                           live_metric=float(value),
                           regression=float(regression))
            return True

    def _rollback(self, reason: str, **detail) -> None:
        """Registry pointer flip back + ledger retirement of the bad
        publish (the rolled-back candidate joins the quarantine)."""
        tm = TELEMETRY
        published = list(self._ledger["published"])
        bad = published.pop()
        prev_model = published[-1]["model"] if published else BASE_MODEL
        if self.registry is not None:
            try:
                self.registry.rollback(self.name)
            except (KeyError, ValueError):
                # nothing earlier resident in THIS process (daemon
                # restarted since): re-publish the previous good model
                self.registry.publish(
                    self.name, self._p(prev_model),
                    published_unix=time.time(),
                    eval_metric=(published[-1]["metric"]
                                 if published else None),
                    source="continuous")
        quarantined = self._ledger["quarantined"] + [{
            "cycle": bad["cycle"], "model": bad["model"],
            "reason": reason, **detail,
        }]
        self._commit(published=published, quarantined=quarantined,
                     last_good=prev_model)
        if tm.on:
            tm.add("continuous_rollbacks", 1)
            tm.add("continuous_quarantined", 1)
        tm.journal.emit(
            "rollback", seam="continuous.cycle",
            lane=self.name, model=bad["model"], cause=reason)
        tm.flight.dump("continuous_rollback", seam="continuous.cycle",
                       model=bad["model"], cause=reason, **detail)
        Log.warning(
            f"continuous lane {self.name!r}: ROLLED BACK "
            f"{bad['model']} ({reason}"
            + (f", live {detail.get('live_metric')}"
               if "live_metric" in detail else "")
            + f"); serving {prev_model} again — candidate quarantined")

    # -- scheduled (cron-style) cycles ----------------------------------
    def _cycle_interval(self) -> float:
        return float(getattr(self.config,
                             "continuous_cycle_interval_s", 0.0) or 0.0)

    def scheduled_due(self) -> bool:
        """Whether the ledger-committed next-due time has passed (the
        cron-style timer beside the directory watcher;
        ``continuous_cycle_interval_s``)."""
        iv = self._cycle_interval()
        if iv <= 0:
            return False
        with self._ledger_lock:
            due = self._ledger.get("next_cycle_unix")
        return due is not None and self._clock() >= float(due)

    def run_scheduled_cycle(self) -> Optional[dict]:
        """Run one scheduled cycle when due (no-op otherwise) and
        commit the next due time to the ledger — committed in a
        ``finally`` so a failing cycle keeps its poll-driven ledger
        replay instead of hot-looping the schedule; a restarted
        daemon reads the committed due time and keeps the cadence
        instead of firing immediately.  A scheduled fire behaves like
        ``force_cycle``: a continue-mode cycle trains even with no
        new slices."""
        if not self.scheduled_due():
            return None
        Log.info(f"continuous lane {self.name!r}: scheduled cycle due "
                 f"(continuous_cycle_interval_s="
                 f"{self._cycle_interval():g})")
        if TELEMETRY.on:
            TELEMETRY.add("continuous_scheduled_cycles", 1)
        try:
            return self.run_cycle(force=True)
        finally:
            self._commit(next_cycle_unix=round(
                self._clock() + self._cycle_interval(), 6))

    # -- the cycle driver -----------------------------------------------
    def run_cycle(self, force: bool = False) -> Optional[dict]:
        """Run (or crash-resume) ONE cycle synchronously; returns the
        cycle's decision record, or None when there was nothing to do.
        The worker thread calls this on every poll tick; tests drive
        it directly for determinism."""
        with self._cycle_lock:
            t0 = time.perf_counter()
            led = self._ledger
            cycle = int(led["cycle"])
            resuming = led["phase"] != "idle"
            cycle_span = TELEMETRY.start_span("continuous_cycle",
                                              cycle=cycle)
            try:
                if resuming:
                    names = list(led["cycle_slices"])
                    Log.warning(
                        f"continuous lane {self.name!r}: resuming "
                        f"cycle {cycle} from phase "
                        f"{led['phase']!r} ({len(names)} slice(s) "
                        "from the ledger)")
                else:
                    done = {rec["name"] for rec in led["processed"]}
                    names = _ingest.discover_slices(self.ingest_dir,
                                                    done)
                    if not names and not (
                            force
                            and self.config.continuous_mode
                            == "continue"):
                        return None
                decision = self._run_phases(cycle, names,
                                            led["phase"])
                decision["resumed"] = resuming
                self.last_cycle = decision
                tm = TELEMETRY
                if tm.on:
                    tm.add("continuous_cycles", 1)
                    tm.gauge("continuous_cycle", cycle)
                    tm.observe("continuous_cycle_ms",
                               (time.perf_counter() - t0) * 1e3)
                return decision
            except BaseException as e:
                # the flight dump names the phase the cycle died in —
                # for 'kill' actions this is the only trace left
                TELEMETRY.flight.dump(
                    "continuous_cycle_failed", seam="continuous.cycle",
                    phase=self._ledger["phase"], cycle=cycle,
                    error=repr(e)[:300])
                if TELEMETRY.on:
                    TELEMETRY.add("continuous_cycle_failures", 1)
                raise
            finally:
                from ..reliability.watchdog import WATCHDOG
                WATCHDOG.cancel(self._watch_token)
                self._watch_token = None
                TELEMETRY.end_span(cycle_span)

    def _run_phases(self, cycle: int, names: List[str],
                    start_phase: str) -> dict:
        """Walk the phase machine from ``start_phase`` (``idle`` =
        fresh cycle).  Each phase re-derives its inputs from the
        ledger, does its work, and commits before the next starts."""
        start = PHASES.index(start_phase) if start_phase in PHASES \
            else 0
        slices = None
        decision = self._ledger.get("cycle_decision")
        # ingest: load + drift-check the slices, commit the cycle
        if start <= PHASES.index("ingest"):
            self._phase("ingest", cycle)
            span = TELEMETRY.start_span("continuous_ingest",
                                        cycle=cycle,
                                        slices=len(names))
            try:
                slices = self._load_cycle_slices(names,
                                                 count_drift=True)
                if TELEMETRY.on and slices:
                    TELEMETRY.add(
                        "continuous_rows_ingested",
                        int(sum(len(s["X"]) for s in slices)))
            finally:
                TELEMETRY.end_span(span)
            n_drifted = sum(1 for s in slices if s.get("drift"))
            self._commit_mutate(lambda led: led.update(
                phase="train", cycle_slices=names,
                **self._drift_refit_updates(n_drifted, led)))
        if slices is None:
            slices = self._load_cycle_slices(names)
        # train: produce the candidate model file
        if start <= PHASES.index("train"):
            self._phase("train", cycle)
            cand = self._train_candidate(cycle, slices)
            self._commit(phase="eval")
        else:
            cand = f"model_cycle_{cycle}.txt"
        # eval: gate the candidate, commit the decision
        if start <= PHASES.index("eval") or decision is None:
            self._phase("eval", cycle)
            decision = self._gate(cycle, cand, slices)
            self._commit(phase="publish", cycle_decision=decision)
        # publish: act on the committed decision, retire the cycle
        self._phase("publish", cycle)
        slices_meta = [{"name": s["name"], "cycle": cycle,
                        "rows": int(len(s["X"]))} for s in slices]
        decision = dict(decision)
        decision["drift"] = {s["name"]: s["drift"] for s in slices
                             if s.get("drift")}
        self._publish(cycle, decision, slices_meta)
        # fold the cycle's train rows into the in-memory accumulator
        for s in slices:
            self._acc.append((s["Xt"], s["yt"]))
            self._acc_names.append(s["name"])
        return decision

    # -- worker thread + control surface --------------------------------
    def start(self, mount_routes: bool = True) -> "ContinuousLane":
        """Publish the base model if the registry has nothing under
        ``name`` yet, mount ``/continuous`` on the shared listener,
        and start the poll worker."""
        self._base_model_path()
        if self.registry is not None:
            try:
                self.registry.get(self.name)
            except KeyError:
                self.registry.publish(self.name,
                                      self._p(BASE_MODEL),
                                      published_unix=time.time(),
                                      source="manual")
            # close the drift→refit loop for LIVE traffic: serving
            # quality monitors read this hook at fire time, so drift
            # past quality_drift_refit_threshold lands in the same
            # ledger tally ingest drift feeds
            self.registry.on_quality_drift = self.report_serving_drift
        if self._cycle_interval() > 0 \
                and self._ledger.get("next_cycle_unix") is None:
            # first arm of the cron-style timer: commit the due time
            # so a restart keeps the cadence (an already-committed due
            # time is left alone — including one now in the past,
            # which fires on the first poll)
            self._commit(next_cycle_unix=round(
                self._clock() + self._cycle_interval(), 6))
        if mount_routes:
            TELEMETRY.register_http_route("/continuous",
                                          self._http_route)
            self._routes_mounted = True
        if self._thread is not None and not self._thread.is_alive():
            self._thread = None        # a previous worker finished
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"ltpu-continuous-{self.name}")
            self._thread.start()
        return self

    def stop(self, timeout_s: float = 60.0) -> None:
        if self.registry is not None and getattr(
                self.registry, "on_quality_drift", None) \
                == self.report_serving_drift:
            # == not `is`: each attribute access creates a FRESH
            # bound-method object, so `is` would never match and the
            # hook would leak past stop()
            # symmetric teardown of what start() installed: a stopped
            # (possibly decommissioned) lane must not keep receiving
            # serving-drift reports into its ledger
            self.registry.on_quality_drift = None
        self._stop.set()
        self._force.set()
        t = self._thread
        if t is not None:
            t.join(timeout_s)
            if t.is_alive():
                # a long training phase is still draining: keep the
                # handle so a premature start() cannot spawn a SECOND
                # worker over the same ledger
                Log.warning(
                    f"continuous lane {self.name!r}: worker still "
                    f"finishing its cycle after {timeout_s:g}s; it "
                    "will exit at the next poll check")
            else:
                self._thread = None
        if self._routes_mounted:
            TELEMETRY.unregister_http_route("/continuous")
            self._routes_mounted = False

    def _run(self) -> None:
        while not self._stop.is_set():
            if self._paused:
                # don't consume a pending force_cycle while paused —
                # it fires on resume (cheap pause-flag poll)
                self._stop.wait(min(self.config.continuous_poll_s,
                                    0.5))
                continue
            forced = self._force.is_set()
            self._force.clear()
            try:
                if not forced and self.scheduled_due():
                    self.run_scheduled_cycle()
                else:
                    self.run_cycle(force=forced)
                    if forced and self.scheduled_due():
                        # the forced cycle already trained over
                        # everything the due scheduled cycle would —
                        # re-arm the timer instead of immediately
                        # training a duplicate cycle next poll
                        self._commit(next_cycle_unix=round(
                            self._clock() + self._cycle_interval(),
                            6))
            except Exception as e:
                # the cycle already dumped the flight recorder;
                # the lane survives and retries next poll (the
                # ledger replays the failed cycle)
                Log.warning(
                    f"continuous lane {self.name!r}: cycle "
                    f"failed ({type(e).__name__}: {e}); will "
                    "retry from the ledger next poll")
            self._force.wait(self.config.continuous_poll_s)

    def pause(self) -> None:
        self._paused = True

    def resume(self) -> None:
        self._paused = False

    def force_cycle(self) -> None:
        """Skip the poll wait (worker runs a cycle immediately, and a
        continue-mode cycle runs even with no new slices)."""
        self._force.set()

    def status(self) -> dict:
        with self._ledger_lock:
            led = self._ledger
            pub = led["published"][-1] if led["published"] else None
            return {
                "name": self.name,
                "mode": self.config.continuous_mode,
                "state": "paused" if self._paused else (
                    "running" if self._thread is not None else
                    "stopped"),
                "cycle": led["cycle"],
                "phase": led["phase"],
                "ingest_dir": self.ingest_dir,
                "slices_processed": len(led["processed"]),
                "published": pub,
                "publishes": len(led["published"]),
                "quarantined": led["quarantined"],
                "last_good": led["last_good"],
                "last_cycle": self.last_cycle,
                "drift_slices": int(led.get("drift_slices", 0)),
                "drift_refit_threshold": int(getattr(
                    self.config, "continuous_drift_refit_threshold",
                    0) or 0),
                "serving_drift_reports": int(led.get(
                    "serving_drift_reports", 0)),
                "cycle_interval_s": self._cycle_interval(),
                "next_cycle_unix": led.get("next_cycle_unix"),
            }

    def _http_route(self, method, path, body, headers):
        """``GET /continuous`` status; ``POST /continuous`` control
        (``{"action": "pause"|"resume"|"force_cycle"|"live_metric",
        "value": ...}``)."""
        if method == "GET":
            return (200, "application/json",
                    json.dumps(self.status()).encode(), None)
        if method != "POST":
            return (405, "application/json",
                    json.dumps({"error": "GET for status, POST "
                                "{'action': ...} for control"}
                               ).encode(), {"Allow": "GET, POST"})
        try:
            req = json.loads(body.decode("utf-8")) if body else {}
            action = req.get("action", "")
        except (ValueError, UnicodeDecodeError) as e:
            return (400, "application/json",
                    json.dumps({"error": str(e)[:200]}).encode(),
                    None)
        if action == "pause":
            self.pause()
        elif action == "resume":
            self.resume()
        elif action == "force_cycle":
            self.force_cycle()
        elif action == "live_metric":
            try:
                value = float(req["value"])
            except (KeyError, TypeError, ValueError):
                return (400, "application/json",
                        json.dumps({"error": "live_metric needs a "
                                    "numeric 'value'"}).encode(),
                        None)
            rolled = self.report_live_metric(value)
            return (200, "application/json",
                    json.dumps({"action": action,
                                "rolled_back": rolled,
                                **self.status()}).encode(), None)
        else:
            return (400, "application/json",
                    json.dumps(
                        {"error": f"unknown action {action!r}",
                         "actions": ["pause", "resume",
                                     "force_cycle", "live_metric"]}
                    ).encode(), None)
        return (200, "application/json",
                json.dumps({"action": action,
                            **self.status()}).encode(), None)
