"""Continuous-training service: the train→evaluate→publish daemon
closing the loop between checkpoints (r12), streaming construction
(r11) and the serving registry (r14) — docs/CONTINUOUS_TRAINING.md.

Two modules:

- :mod:`.ingest` — slice discovery (directory poll / MANIFEST order),
  append-construction against the base dataset's FROZEN bin mappers
  (the r11 ``from_reference_for_push`` streaming protocol), and the
  drift detector for values the frozen mappers cannot resolve.
- :mod:`.lane` — the four-phase cycle state machine
  (ingest→train→eval→publish) with a crash-safe ledger, the eval
  gate + quarantine, post-publish live-metric rollback, the
  ``/continuous`` control surface and the ``continuous.cycle`` fault
  seam.

CLI: ``python -m lightgbm_tpu task=serve input_model=model.txt
data=base.csv continuous_ingest_dir=incoming/`` serves AND keeps
training.
"""
from .ingest import (append_construct, discover_slices, drift_check,
                     holdout_split, load_slice)
from .lane import ContinuousLane

__all__ = ["ContinuousLane", "append_construct", "discover_slices",
           "drift_check", "holdout_split", "load_slice"]
