"""Ingest side of the continuous-training lane: slice discovery,
drift detection, and append-construction against frozen bin mappers.

A *slice* is one data file dropped into ``continuous_ingest_dir`` —
same text formats as ``data`` (csv/tsv/libsvm, label column resolved
the same way).  Discovery is deterministic: slices process in sorted
name order, or in the order listed by an optional ``MANIFEST`` file in
the directory (one relative path per line, ``#`` comments allowed) —
determinism is what makes a SIGKILLed cycle replay byte-identical
from the ledger.

Appended slices are binned through the r11 streaming-construction
protocol (``Dataset.from_reference_for_push`` + ``push_rows``) against
the BASE dataset's FROZEN bin mappers: base rows are never re-binned
(their packed bins are copied), new rows bin into the base bin space,
and trees trained on the result stay in the same threshold space as
every previously published model.

Freezing the mappers is also what makes drift *observable*: a new
value past a numerical mapper's fitted ``[min_val, max_val]`` range,
or a category the mapper never saw, clamps into an edge/overflow bin
— silently degrading resolution.  ``drift_check`` counts exactly
those values per feature, warns loudly once per slice, and feeds the
``continuous_drift_values`` / ``continuous_drift_slices`` counters
(docs/CONTINUOUS_TRAINING.md, drift semantics).
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..binning import BIN_CATEGORICAL, BIN_NUMERICAL
from ..config import Config
from ..telemetry import TELEMETRY
from ..utils.log import Log

MANIFEST_NAME = "MANIFEST"

# names the watcher never treats as slices: the manifest itself,
# hidden files, partial writes, binary dataset caches and the lane's
# own state directory
_SKIP_SUFFIXES = (".tmp", ".part", ".bin", ".swp")


def discover_slices(ingest_dir: str,
                    processed: Sequence[str] = ()) -> List[str]:
    """New slice file names in ``ingest_dir`` in DETERMINISTIC order:
    the ``MANIFEST`` order when one exists (files it lists that are
    not on disk yet are simply not ready), else sorted names.  Names
    in ``processed`` (the ledger) are skipped."""
    if not os.path.isdir(ingest_dir):
        return []
    done = set(processed)
    manifest = os.path.join(ingest_dir, MANIFEST_NAME)
    if os.path.exists(manifest):
        names = []
        with open(manifest) as f:
            for ln in f:
                ln = ln.split("#", 1)[0].strip()
                if ln:
                    names.append(ln)
    else:
        names = sorted(os.listdir(ingest_dir))
    out = []
    for name in names:
        if name in done or name == MANIFEST_NAME \
                or name.startswith(".") \
                or name.endswith(_SKIP_SUFFIXES):
            continue
        path = os.path.join(ingest_dir, name)
        if os.path.isfile(path):
            out.append(name)
    return out


def load_slice(path: str, config: Config
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Parse one slice file into (X float64 matrix, label).  Slices
    must carry labels — the lane trains and gates on them."""
    from ..data_loader import load_file
    X, label, _extras = load_file(path, config)
    if label is None:
        raise ValueError(
            f"continuous ingest: slice {path} carries no label column "
            "(the lane trains on fresh labels; set label_column)")
    return np.ascontiguousarray(np.asarray(X, dtype=np.float64)), \
        np.asarray(label, dtype=np.float64)


def drift_check(base_core, X: np.ndarray, slice_name: str = "",
                count: bool = True) -> Dict[int, int]:
    """Count values of ``X`` that fall OUTSIDE the base dataset's
    fitted bin ranges: numerical values past ``[min_val, max_val]``
    (finite only — NaN is a modeled missing value, not drift) and
    unseen categories.  Returns {real feature index: count}, warns
    loudly and bumps the drift counters when anything drifted.
    ``count=False`` computes silently — the crash-resume reload path
    must not double-count a slice's drift."""
    per_feature: Dict[int, int] = {}
    for f in base_core.features:
        j = f.feature_idx
        m = base_core.mappers[j]
        col = X[:, j]
        if m.bin_type == BIN_NUMERICAL:
            finite = np.isfinite(col)
            n = int(np.count_nonzero(
                finite & ((col < m.min_val) | (col > m.max_val))))
        elif m.bin_type == BIN_CATEGORICAL:
            with np.errstate(invalid="ignore"):
                iv = col.astype(np.int64)
            valid = ~np.isnan(col)
            known = np.zeros(len(col), dtype=bool)
            if m.categorical_2_bin:
                keys = np.fromiter(m.categorical_2_bin.keys(),
                                   dtype=np.int64)
                known[valid] = np.isin(iv[valid], keys)
            n = int(np.count_nonzero(valid & ~known))
        else:  # pragma: no cover - no third bin type exists
            continue
        if n:
            per_feature[j] = n
    if per_feature and count:
        total = sum(per_feature.values())
        tm = TELEMETRY
        if tm.on:
            tm.add("continuous_drift_values", total)
            tm.add("continuous_drift_slices", 1)
        worst = sorted(per_feature.items(), key=lambda kv: -kv[1])[:5]
        Log.warning(
            "continuous ingest: DATA DRIFT in slice "
            f"{slice_name or '<array>'} — {total} value(s) across "
            f"{len(per_feature)} feature(s) fall outside the base "
            "dataset's fitted bin ranges and will clamp into edge "
            "bins (worst: "
            + ", ".join(f"feature {j}: {c}" for j, c in worst)
            + "). The frozen mappers cannot resolve these values; "
              "consider retraining the base dataset "
              "(docs/CONTINUOUS_TRAINING.md, drift semantics)")
    return per_feature


def append_construct(base_core, slices: Sequence[np.ndarray],
                     labels: Sequence[np.ndarray],
                     base_raw: Optional[np.ndarray] = None):
    """Build the cycle's training dataset: base rows + every slice,
    binned in the base's FROZEN bin space.

    The base's packed bins are COPIED (never re-binned — byte-for-byte
    the construction the base model trained on); each slice pushes
    through the r11 streaming protocol row chunk by row chunk.  When
    ``base_raw`` is given (continue-mode needs raw rows to seed
    continued-training scores), the returned core carries the stacked
    raw matrix in ``_raw_data``.

    Labels: base labels + per-slice labels, concatenated in push
    order."""
    from ..dataset import Dataset as CoreDataset
    base_n = int(base_core.num_data)
    new_n = int(sum(x.shape[0] for x in slices))
    core = CoreDataset.from_reference_for_push(
        base_core, base_n + new_n)
    core.group_bins[:base_n] = base_core.group_bins
    core._pushed_rows = base_n
    off = base_n
    for x in slices:
        core.push_rows(x, off)
        off += int(x.shape[0])
    core.finish_load()
    base_label = base_core.metadata.label
    core.metadata.set_label(np.concatenate(
        [np.asarray(base_label, dtype=np.float64)]
        + [np.asarray(y, dtype=np.float64) for y in labels]))
    core.pandas_categorical = getattr(
        base_core, "pandas_categorical", None)
    if base_raw is not None:
        core._raw_data = np.ascontiguousarray(np.concatenate(
            [np.asarray(base_raw, dtype=np.float64)] + list(slices),
            axis=0))
    return core


def holdout_split(X: np.ndarray, y: np.ndarray, holdout: float
                  ) -> Tuple[np.ndarray, np.ndarray,
                             np.ndarray, np.ndarray]:
    """Deterministic tail split of one slice into (train rows, train
    labels, eval rows, eval labels): the LAST ``ceil(n * holdout)``
    rows are held out for the eval gate.  No RNG — a crash-replayed
    cycle must cut the exact same rows.  A 1-row slice always keeps
    its row in training (an empty train set can't boost)."""
    n = int(X.shape[0])
    k = int(np.ceil(n * float(holdout))) if holdout > 0 else 0
    k = min(k, n - 1) if n > 1 else 0
    cut = n - k
    return X[:cut], y[:cut], X[cut:], y[cut:]
