"""Micro-batching scheduler: coalesce concurrent predict requests
into the serving predictor's power-of-two buckets.

The shape-bucketed predictor (booster.py `_ServingPredictor`, r8) was
built so micro-batch traffic reuses ONE compiled program per bucket —
but until now it only ever saw one caller's batch at a time, so N
concurrent single-row requests still cost N dispatches of a
16-row bucket each.  This module is the missing aggregation layer
(the Booster-paper batching argument, arXiv 2011.02022): a bounded
request queue whose dispatcher thread holds the oldest request open
for at most ``serve_batch_deadline_ms``, merges every request that
arrived in the window into one concatenated matrix (capped at
``serve_max_batch_rows``), dispatches ONCE, and slices the result
back per request.  Per-row scores are independent of batch
composition in every predict path (host walk and device level
descent alike), so coalesced results are byte-identical to a direct
``Booster.predict`` of the same rows — pinned by
``tests/test_serving.py``.

Admission control lives at ``submit``: a full queue
(``serve_queue_depth``) or a projected queue wait beyond
``serve_shed_deadline_ms`` (batches ahead x the EWMA dispatch wall)
raises :class:`ShedLoad`, which the HTTP frontend turns into
503 + Retry-After.  Shedding at the door keeps the latency of
admitted requests bounded instead of letting every client time out
together.

Determinism seams (no sleeps in tests): the clock is injectable
(``clock=``), the dispatcher thread is optional (``start=False``),
and ``drain_pending()`` runs the coalescing loop inline — the
deadline/coalescing semantics are tested against a fake clock, the
threaded path against real concurrent load.

Telemetry (docs/OBSERVABILITY.md): ``serve_requests`` /
``serve_dispatches`` / ``serve_rows`` / ``serve_coalesced_requests``
/ ``serve_shed_requests`` / ``serve_errors`` counters, a
``serve_dispatch`` span per coalesced dispatch, and the
``serve_queue_wait_ms`` / ``serve_batch_fill`` / ``serve_batch_rows``
histograms the capacity-planning guide (docs/SERVING.md) reads.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, List, Optional

import numpy as np

from ..telemetry import (TELEMETRY, BATCH_BOUNDS, RATIO_BOUNDS,
                         clear_trace, current_trace, new_span_id,
                         set_trace)


class ShedLoad(Exception):
    """Admission-control rejection: the request was NOT queued.  The
    HTTP frontend maps this to 503 with ``Retry-After`` =
    ``retry_after_s`` (rounded up to a whole second)."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class BatcherClosed(RuntimeError):
    """Submit raced a hot swap: this batcher drained and closed while
    the caller held a reference.  The registry retries against the
    current entry — callers never see this as a failed response."""


class _Request:
    __slots__ = ("rows", "n", "t_enq", "done", "result", "error",
                 "tag", "trace")

    def __init__(self, rows: np.ndarray, t_enq: float, tag=None,
                 trace=None):
        self.rows = rows
        self.n = int(rows.shape[0])
        self.t_enq = t_enq
        self.done = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        # co-batching identity: which member model this request
        # belongs to (None on a single-model batcher)
        self.tag = tag
        # causal trace context (trace_id, span_id) snapshotted from
        # the submitting thread — the coalesced dispatch records a
        # fan-in link back to each member's span
        self.trace = trace


class MicroBatcher:
    """Bounded request queue + coalescing dispatcher over one predict
    callable (one instance per served model version — the queue IS
    the version's in-flight work, which is what hot-swap drains)."""

    def __init__(self, predict_fn: Callable[[np.ndarray], np.ndarray],
                 config=None, clock: Optional[Callable[[], float]] = None,
                 start: bool = True, name: str = "",
                 observer: Optional[Callable] = None, pool=None):
        self.predict = predict_fn
        self.name = name
        # read-only post-dispatch hook fed (rows, results) of every
        # successful coalesced dispatch — the serving quality monitor
        # (lightgbm_tpu/quality/).  None (quality=off) costs one
        # attribute check; the hook runs after the batch's requests
        # are released, on whichever thread ran the dispatch (the
        # dispatcher inline, or a lane worker — with a lane pool the
        # monitor samples every lane's traffic; its own lock makes
        # cross-lane observation safe).  A hook crash is counted +
        # warned once, never surfaced to the request.
        self.observer = observer
        # lane pool (lightgbm_tpu/serving/lanes.py): when set, the
        # dispatcher thread only coalesces and routes — the batch
        # runs on a pool lane, so N models x N lanes dispatch
        # concurrently.  None keeps the r14 inline single stream.
        self.pool = pool
        self._jobs_out = 0
        self._observer_warned = False
        self.deadline_ms = float(getattr(
            config, "serve_batch_deadline_ms", 2.0))
        self.shed_ms = float(getattr(
            config, "serve_shed_deadline_ms", 100.0))
        self.queue_depth = max(1, int(getattr(
            config, "serve_queue_depth", 1024)))
        self.max_rows = max(1, int(getattr(
            config, "serve_max_batch_rows", 1024)))
        self.min_bucket = max(1, int(getattr(
            config, "predict_min_bucket_rows", 16)))
        # deadline on each coalesced dispatch (docs/RELIABILITY.md,
        # deadline watchdog): a dispatch wedged past it fails its
        # batch with a classified StallError (all-thread stacks
        # flight-dumped) instead of freezing the dispatcher thread —
        # and with it every queued request — forever.  0 = unbounded
        self.watchdog_s = float(getattr(
            config, "watchdog_serve_s", 0.0) or 0.0)
        # mirror the predictor's bucket policy for the fill metric:
        # with predict_bucket=off dispatches are exact-shaped, so the
        # fill denominator is the batch itself
        self.bucketed = str(getattr(config, "predict_bucket", "auto")
                            ).lower() not in ("off", "false", "0")
        self._clock = clock or time.perf_counter
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: Deque[_Request] = collections.deque()
        self._pending_rows = 0
        self._closed = False
        self._dispatch_ewma_ms = 0.0
        self._thread: Optional[threading.Thread] = None
        if start:
            self.start()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is None and not self._closed:
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"ltpu-batcher-{self.name or hex(id(self))}")
            self._thread.start()
        return self

    def close(self, drain: bool = True,
              timeout_s: float = 60.0) -> "MicroBatcher":
        """Stop accepting work; with ``drain`` (the default) every
        already-queued request is still dispatched and answered before
        the dispatcher exits — the hot-swap "old version drains
        in-flight work then releases" semantic."""
        with self._cond:
            self._closed = True
            if not drain:
                for r in self._pending:
                    r.error = BatcherClosed("batcher closed")
                    r.done.set()
                self._pending.clear()
                self._pending_rows = 0
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout_s)
        elif drain:
            self.drain_pending()
        if self.pool is not None:
            # batches already handed to lanes still belong to this
            # version: the hot-swap "old version drains" semantic
            # includes its in-flight lane work
            end = time.monotonic() + timeout_s
            with self._cond:
                while self._jobs_out > 0 and time.monotonic() < end:
                    self._cond.wait(0.1)
        return self

    @property
    def closed(self) -> bool:
        return self._closed

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- admission + submit --------------------------------------------
    def _projected_wait_ms(self) -> float:
        """Estimated queue wait for a NEW request (lock held): whole
        batches ahead of it x the EWMA coalesced-dispatch wall.  Zero
        until the first dispatch has been timed — admission never
        sheds on a cold estimator, only on real measured backlog."""
        if self._dispatch_ewma_ms <= 0.0 or not self._pending:
            return 0.0
        batches_ahead = -(-self._pending_rows // self.max_rows)
        wait = batches_ahead * self._dispatch_ewma_ms
        if self.pool is not None:
            # lanes drain batches concurrently: the projected wait a
            # NEW request sees divides by the healthy fleet width
            wait /= max(1, self.pool.healthy_count())
        return wait

    def submit(self, rows: np.ndarray,
               timeout_s: Optional[float] = None,
               tag=None) -> np.ndarray:
        """Queue ``rows`` (1D = one row) for the next coalesced
        dispatch; blocks until its slice of the batch result is ready.
        Raises :class:`ShedLoad` when admission control rejects, and
        re-raises the dispatch's exception on failure."""
        rows = np.asarray(rows, dtype=np.float64)
        if rows.ndim == 1:
            rows = rows[None, :]
        tm = TELEMETRY
        if rows.shape[0] == 0:
            return np.asarray(self.predict(rows))
        with self._cond:
            if self._closed:
                # NOT counted: the registry transparently retries a
                # swap-raced submit, and counting each attempt would
                # inflate serve_requests past serve_http_requests
                raise BatcherClosed("batcher closed")
            if tm.on:
                tm.add("serve_requests", 1)
            if len(self._pending) >= self.queue_depth:
                if tm.on:
                    tm.add("serve_shed_requests", 1)
                raise ShedLoad(
                    f"serving queue full ({self.queue_depth} requests "
                    "waiting)",
                    retry_after_s=max(self.shed_ms, 1000.0) / 1e3)
            wait = self._projected_wait_ms()
            if wait > self.shed_ms:
                if tm.on:
                    tm.add("serve_shed_requests", 1)
                raise ShedLoad(
                    f"projected queue wait {wait:.0f} ms exceeds "
                    f"serve_shed_deadline_ms={self.shed_ms:g}",
                    retry_after_s=wait / 1e3)
            req = _Request(rows, self._clock(), tag=tag,
                           trace=current_trace() if tm.spans_on
                           else None)
            self._pending.append(req)
            self._pending_rows += req.n
            self._cond.notify_all()
        if not req.done.wait(timeout_s):
            raise TimeoutError(
                f"serve request timed out after {timeout_s}s "
                "(dispatcher stalled?)")
        if req.error is not None:
            raise req.error
        return req.result

    # -- coalescing decisions (pure w.r.t. the injected clock) ---------
    def _ready(self, now: float) -> bool:
        """Whether the dispatcher should dispatch NOW: pending work
        and (closing, or a full batch, or the oldest request's
        coalescing deadline expired)."""
        if not self._pending:
            return False
        if self._closed or self._pending_rows >= self.max_rows:
            return True
        return (now - self._pending[0].t_enq) * 1e3 >= self.deadline_ms

    def _take_batch(self) -> List[_Request]:
        """Pop the longest request prefix within ``max_rows`` (lock
        held).  A single over-cap request dispatches alone — the
        predictor chunk-streams it internally.

        With a lane pool the prefix is additionally capped at a
        per-lane SHARE of the pending requests (ceil(pending /
        healthy lanes)): one greedy batch would swallow the whole
        backlog into a single lane and idle the rest of the fleet —
        splitting the window across lanes is where the N-lane
        throughput scaling comes from.  Per-row scores are
        independent of batch composition, so the split never changes
        results."""
        share = None
        if self.pool is not None and len(self._pending) > 1:
            lanes = max(1, self.pool.healthy_count())
            share = -(-len(self._pending) // lanes)
        batch: List[_Request] = []
        rows = 0
        while self._pending:
            r = self._pending[0]
            if batch and rows + r.n > self.max_rows:
                break
            if share is not None and len(batch) >= share:
                break
            batch.append(self._pending.popleft())
            rows += r.n
        self._pending_rows -= rows
        return batch

    # -- dispatcher ----------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._ready(self._clock()):
                    if self._closed and not self._pending:
                        return
                    if self._pending:
                        age_s = self._clock() - self._pending[0].t_enq
                        left = max(self.deadline_ms / 1e3 - age_s, 1e-4)
                        self._cond.wait(left)
                    else:
                        self._cond.wait()
                if self._closed and not self._pending:
                    return
                batch = self._take_batch()
            if self.pool is not None:
                self._dispatch_to_pool(batch)
            else:
                self._run_batch(batch)

    def _dispatch_to_pool(self, batch: List[_Request]) -> None:
        """Hand one coalesced batch to a pool lane.  The pool blocks
        while every healthy lane is full (backpressure into this
        queue, where admission control sheds); with no healthy lane
        left the batch fails loudly with the fleet-wide stall."""
        def job(lane, batch=batch):
            try:
                self._run_batch(batch, lane)
            finally:
                with self._cond:
                    self._jobs_out -= 1
                    self._cond.notify_all()

        def abort(err, batch=batch):
            self._fail_batch(batch, err)
            with self._cond:
                self._jobs_out -= 1
                self._cond.notify_all()

        with self._cond:
            self._jobs_out += 1
        try:
            self.pool.submit(job, abort)
        except Exception as e:
            # no healthy lane (fleet-wide stall) or pool shutdown:
            # fail the batch on the dispatcher thread, keep coalescing
            abort(e)

    def drain_pending(self) -> int:
        """Dispatch everything pending inline (deadline ignored) on
        the CALLING thread; returns the number of dispatches.  The
        deterministic seam for tests and for draining a never-started
        batcher."""
        dispatches = 0
        while True:
            with self._lock:
                if not self._pending:
                    return dispatches
                batch = self._take_batch()
            self._run_batch(batch)
            dispatches += 1

    def _bucket(self, m: int) -> int:
        """Nominal bucket the predictor's ladder rounds ``m`` rows up
        to — the fill-metric denominator (exact shape when bucketing
        is off; the predictor may additionally clamp to its chunk
        cap, which this metric deliberately ignores: fill measures
        batching quality against the ladder, not chunking)."""
        if not self.bucketed:
            return m
        from ..booster import round_up_bucket
        return round_up_bucket(m, self.min_bucket)

    def _fail_batch(self, batch: List[_Request],
                    e: BaseException) -> None:
        """Per-request failure propagation: the whole coalesced batch
        shares the dispatch, so it shares the error.  A watchdog
        StallError is additionally stall-classified (serve_stalls) —
        the frontend maps it to 503 + Retry-After rather than a
        generic 500."""
        from ..reliability.watchdog import StallError
        for r in batch:
            r.error = e
            r.done.set()
        tm = TELEMETRY
        if tm.on:
            tm.add("serve_errors", len(batch))
            if isinstance(e, StallError):
                tm.add("serve_stalls", 1)

    def _finish_request(self, r: _Request, out: np.ndarray,
                        s: int) -> None:
        """Assign one request its slice of the batch result (the
        co-batcher overrides this with the per-model segment
        finish)."""
        r.result = out[s:s + r.n]

    def _run_batch(self, batch: List[_Request], lane=None) -> None:
        tm = TELEMETRY
        now = self._clock()
        t0 = time.perf_counter()
        rows = sum(r.n for r in batch)
        # fan-in trace links (docs/OBSERVABILITY.md, Tracing): the
        # coalesced dispatch adopts the first traced member's trace
        # id, mints its own span id, and records the full member span
        # list — the merge tool draws one flow arrow per member into
        # this dispatch slice.  Installed as the active context for
        # the dispatch so a stall/fault underneath journals with it.
        attrs = {"requests": len(batch), "rows": rows}
        token = None
        if tm.spans_on:
            links = [r.trace[1] for r in batch if r.trace is not None]
            if links:
                attrs["trace"] = next(r.trace[0] for r in batch
                                      if r.trace is not None)
                attrs["span"] = new_span_id()
                attrs["links"] = links
                token = set_trace(attrs["trace"], attrs["span"])
            if lane is not None:
                attrs["lane"] = getattr(lane, "index", lane)
        try:
            x = batch[0].rows if len(batch) == 1 else np.concatenate(
                [r.rows for r in batch], axis=0)
            with tm.span("serve_dispatch", **attrs):
                if self.watchdog_s > 0:
                    from ..reliability.watchdog import run_with_deadline
                    out = np.asarray(run_with_deadline(
                        self.predict, self.watchdog_s,
                        "serve_dispatch", "predict.dispatch", x))
                else:
                    out = np.asarray(self.predict(x))
        except Exception as e:
            from ..reliability.watchdog import StallError
            if (lane is not None and self.pool is not None
                    and isinstance(e, StallError)):
                # the LANE is wedged, not the fleet: brown it out
                # (aborts its queued batches with the stall), route
                # around it from the next dispatch on
                self.pool.mark_stalled(lane, e)
            self._fail_batch(batch, e)
            return
        finally:
            if token is not None:
                clear_trace(token)
        dt_ms = (time.perf_counter() - t0) * 1e3
        with self._lock:
            self._dispatch_ewma_ms = dt_ms if not self._dispatch_ewma_ms \
                else 0.8 * self._dispatch_ewma_ms + 0.2 * dt_ms
        if lane is not None and self.pool is not None:
            self.pool.note_dispatch(lane, dt_ms)
        s = 0
        for r in batch:
            self._finish_request(r, out, s)
            s += r.n
            r.done.set()
        if self.observer is not None:
            # AFTER the waiting requests are released: the monitor's
            # host-side binning/PSI work (and a drift report's ledger
            # write) must never sit on the request critical path —
            # it runs on the thread that ran the dispatch (inline
            # dispatcher, or a lane worker: the monitor samples each
            # lane's traffic under its own lock)
            try:
                self.observer(x, out)
            except Exception as e:
                if tm.on:
                    tm.add("quality_observe_errors", 1)
                if not self._observer_warned:
                    self._observer_warned = True
                    from ..utils.log import Log
                    Log.warning(
                        f"serving quality observer crashed "
                        f"({type(e).__name__}: {e}); requests are "
                        "unaffected, monitoring may undercount")
        if tm.on:
            tm.add("serve_dispatches", 1)
            tm.add("serve_rows", rows)
            if len(batch) > 1:
                # requests that shared a dispatch with at least one
                # other — the amortization the micro-batcher exists for
                tm.add("serve_coalesced_requests", len(batch))
            tm.observe("serve_batch_fill", rows / self._bucket(rows),
                       bounds=RATIO_BOUNDS)
            tm.observe("serve_batch_rows", rows, bounds=BATCH_BOUNDS)
            for r in batch:
                tm.observe("serve_queue_wait_ms",
                           max(now - r.t_enq, 0.0) * 1e3)
