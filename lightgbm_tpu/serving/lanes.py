"""Device lane pool: N parallel dispatch streams under one registry.

The r14 micro-batcher ran every coalesced dispatch inline on its own
dispatcher thread — ONE stream between the listener and the hardware.
This module is the fleet layer (ROADMAP open item: replicate the
predictor across local devices): a :class:`LanePool` owns N worker
threads ("lanes"), each optionally pinned to a local accelerator
device via ``jax.default_device``, and every micro-batcher in the
registry hands its coalesced batches to the pool instead of running
them itself.  The per-device serving-predictor cache
(``Booster._serving_predictor`` keyed on the pinned device) gives
each lane its own resident ensemble stack, so lanes dispatch
concurrently instead of serializing on one device stream.

Routing is round-robin with work stealing: the candidate lane
advances per dispatch, but when the candidate's in-flight queue is
deeper than the shallowest healthy neighbor the batch is stolen to
that neighbor instead (``serve_steals`` counts them; per-lane
``serve_lane_depth.<i>`` gauges are what the steal decision reads).
Admission stays bounded: ``submit`` blocks while every healthy lane
already holds ``max_inflight`` batches, which backs the batcher
queue up and lets the r14 shed logic engage — the pool never grows
an unbounded second queue behind the first.

Reliability (docs/RELIABILITY.md): a dispatch that blows
``watchdog_serve_s`` stall-classifies its LANE, not the fleet — the
wedged lane is marked stalled (``serve_lane_stalls``), its queued
batches are failed loudly with the stall error (503 for exactly the
in-flight work on the wedged lane), and the router excludes it from
then on; survivors keep serving.  Only when EVERY lane is stalled
does ``submit`` itself raise, browning the whole service out loudly.
The stall is sticky by design — a wedged device stream does not
silently un-wedge, and ops sees the brownout on ``GET /models``.

On a single-device host (the CPU test seam) lanes are "simulated":
``serve_lanes=N`` builds N unpinned workers sharing the one device —
scheduling, stealing, stall isolation and parity behave identically,
which is what the lane-parity suite and the serve_bench scaling gate
run against.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, List, Optional, Tuple

from ..reliability.watchdog import StallError
from ..telemetry import TELEMETRY
from ..utils.log import Log


def resolve_lanes(config) -> Tuple[int, list]:
    """``serve_lanes=auto|N`` -> (lane count, per-lane device list).
    "auto" is one lane per local device on accelerator backends and 1
    on host backends; an explicit N forces N lanes, sharing devices
    round-robin when N exceeds the device count.  With only one
    distinct device the list is all-None (unpinned): pinning every
    lane to the same device would split the jit cache key for no
    parallelism, so simulated lanes share the default stream's
    compiled programs."""
    spec = str(getattr(config, "serve_lanes", "auto") or "auto")
    spec = spec.strip().lower()
    import jax
    accel = jax.default_backend() in ("tpu", "axon")
    local = list(jax.local_devices()) if accel else []
    if spec in ("auto", ""):
        n = max(1, len(local)) if accel else 1
    else:
        n = max(1, int(spec))
    if len(local) > 1:
        devices = [local[i % len(local)] for i in range(n)]
    else:
        devices = [None] * n
    return n, devices


class Lane:
    """One dispatch stream: a worker thread, its bounded in-flight
    queue, and its health/telemetry counters.  All mutable state is
    guarded by the owning pool's single lock."""

    __slots__ = ("index", "device", "jobs", "inflight", "dispatches",
                 "stalls", "stalled", "thread")

    def __init__(self, index: int, device):
        self.index = int(index)
        self.device = device
        # (job, abort) pairs: job(lane) runs on the worker under the
        # lane's device context; abort(error) fails the batch without
        # running it (stall drain)
        self.jobs: Deque[Tuple[Callable, Callable]] = collections.deque()
        self.inflight = False
        self.dispatches = 0
        self.stalls = 0
        self.stalled = False
        self.thread: Optional[threading.Thread] = None

    def depth(self) -> int:
        """Queued + running batches (pool lock held)."""
        return len(self.jobs) + (1 if self.inflight else 0)


class LanePool:
    """N lanes behind one submit door (one pool per registry, shared
    by every served model's batcher)."""

    def __init__(self, devices: list, name: str = "serve",
                 max_inflight: int = 2):
        if not devices:
            raise ValueError("LanePool needs at least one device slot")
        self.name = name
        # per-lane in-flight bound (queued + running): 2 mirrors the
        # predictor's double buffer — one batch computing, one staged.
        # Beyond that, submit blocks and the batcher queue (where the
        # r14 shed logic lives) absorbs the backlog
        self.max_inflight = max(1, int(max_inflight))
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._rr = -1
        self._lanes: List[Lane] = [Lane(i, d)
                                   for i, d in enumerate(devices)]
        for lane in self._lanes:
            t = threading.Thread(
                target=self._worker, args=(lane,), daemon=True,
                name=f"ltpu-lane-{name}-{lane.index}")
            lane.thread = t
            t.start()

    # -- introspection -------------------------------------------------
    @property
    def n_lanes(self) -> int:
        return len(self._lanes)

    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for lane in self._lanes if not lane.stalled)

    @property
    def warm_devices(self) -> tuple:
        """Distinct per-lane devices to warm before cutover (a single
        (None,) when lanes are unpinned/simulated — one warm covers
        the shared default stream)."""
        seen: dict = {}
        for lane in self._lanes:
            seen.setdefault(lane.device, None)
        return tuple(seen)

    def snapshot(self) -> List[dict]:
        """Per-lane state for ``GET /models``: copied under the pool
        lock (ints only) and released — a /models poll never parks
        dispatch routing behind response serialization."""
        with self._lock:
            return [{
                "lane": lane.index,
                "device": (str(lane.device)
                           if lane.device is not None else None),
                "queue_depth": lane.depth(),
                "dispatches": lane.dispatches,
                "stalls": lane.stalls,
                "stalled": lane.stalled,
            } for lane in self._lanes]

    # -- routing -------------------------------------------------------
    def submit(self, job: Callable, abort: Callable) -> Lane:
        """Enqueue one coalesced batch: ``job(lane)`` runs on the
        selected lane's worker, ``abort(error)`` is called instead if
        the lane stalls before the batch runs.  Blocks while every
        healthy lane is at ``max_inflight`` (backpressure into the
        batcher queue); raises :class:`StallError` when no healthy
        lane remains."""
        tm = TELEMETRY
        with self._cond:
            while True:
                if self._closed:
                    raise RuntimeError("lane pool closed")
                healthy = [ln for ln in self._lanes if not ln.stalled]
                if not healthy:
                    raise StallError(
                        f"serve_dispatch({self.name})",
                        "predict.dispatch", 0.0, 0.0)
                if any(ln.depth() < self.max_inflight
                       for ln in healthy):
                    break
                self._cond.wait(1.0)
            # round-robin candidate, stolen to the shallowest healthy
            # neighbor when the candidate's queue is deeper (the
            # per-lane depth gauges drive this decision)
            self._rr += 1
            cand = healthy[self._rr % len(healthy)]
            dmin = min(ln.depth() for ln in healthy)
            if cand.depth() > dmin:
                cand = min(healthy,
                           key=lambda ln: (ln.depth(), ln.index))
                if tm.on:
                    tm.add("serve_steals", 1)
            cand.jobs.append((job, abort))
            depth = cand.depth()
            self._cond.notify_all()
        if tm.on:
            tm.gauge(f"serve_lane_depth.{cand.index}", depth)
        return cand

    def note_dispatch(self, lane: Lane, dt_ms: float) -> None:
        """Per-lane success accounting (called by the batcher after a
        dispatch completes on ``lane``)."""
        with self._lock:
            lane.dispatches += 1
        tm = TELEMETRY
        if tm.on:
            tm.add("serve_lane_dispatches", 1)
            tm.observe(f"serve_lane_dispatch_ms.{lane.index}", dt_ms)

    def mark_stalled(self, lane: Lane, error: BaseException) -> int:
        """Brown the lane out: exclude it from routing, fail its
        queued batches with the stall error (they were in-flight on
        the wedged stream — answering them promptly beats burning one
        watchdog deadline each, serially), count it loudly.  Returns
        the number of aborted batches."""
        with self._cond:
            if lane.stalled:
                return 0
            lane.stalled = True
            lane.stalls += 1
            aborted = list(lane.jobs)
            lane.jobs.clear()
            self._cond.notify_all()
        tm = TELEMETRY
        if tm.on:
            tm.add("serve_lane_stalls", 1)
            tm.gauge(f"serve_lane_depth.{lane.index}", 0)
        Log.warning(
            f"serving lane {lane.index}"
            + (f" ({lane.device})" if lane.device is not None else "")
            + f" stalled ({error}); routing around it"
            + (f", failing {len(aborted)} queued batch(es)"
               if aborted else ""))
        for _job, abort in aborted:
            try:
                abort(error)
            except Exception:
                pass
        return len(aborted)

    # -- worker --------------------------------------------------------
    def _worker(self, lane: Lane) -> None:
        while True:
            with self._cond:
                while not lane.jobs:
                    if self._closed:
                        return
                    self._cond.wait()
                job, _abort = lane.jobs.popleft()
                lane.inflight = True
            try:
                if lane.device is not None:
                    import jax
                    with jax.default_device(lane.device):
                        job(lane)
                else:
                    job(lane)
            except Exception as e:
                # jobs own their error propagation (the batcher fails
                # its requests internally); a raise here is a bug in
                # the job wrapper — keep the lane alive, log it
                Log.warning(f"serving lane {lane.index} job crashed "
                            f"outside the batch path: {e!r}")
            finally:
                with self._cond:
                    lane.inflight = False
                    self._cond.notify_all()
                if TELEMETRY.on:
                    with self._lock:
                        depth = lane.depth()
                    TELEMETRY.gauge(f"serve_lane_depth.{lane.index}",
                                    depth)

    # -- lifecycle -----------------------------------------------------
    def drain(self, timeout_s: float = 60.0) -> bool:
        """Wait until every lane is idle with an empty queue."""
        end = time.monotonic() + timeout_s
        with self._cond:
            while any(lane.jobs or lane.inflight
                      for lane in self._lanes):
                left = end - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 1.0))
        return True

    def close(self, timeout_s: float = 60.0) -> None:
        """Drain queued work, stop the workers.  A worker whose
        dispatch was abandoned by the watchdog is a daemon — it never
        blocks process exit."""
        self.drain(timeout_s)
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for lane in self._lanes:
            if lane.thread is not None:
                lane.thread.join(min(timeout_s, 5.0))

    @property
    def closed(self) -> bool:
        return self._closed
