"""Multi-model co-batching: one fused compiled program per model
*group*, one dispatch per coalescing window across every member.

Under mixed-model load the per-model micro-batchers each hold their
own window and dispatch their own (mostly empty) bucket — N models
at low per-model rates pay N compiled programs and N small
dispatches.  When models share a feature width and bucket ladder
(``serve_cobatch=on``) the registry instead forms a
:class:`CoBatchGroup`: the members' tree ensembles are concatenated
into ONE :class:`FusedPredictor` stack with a block-diagonal
tree->class accumulator, concurrent requests for ANY member coalesce
into one dispatch, and each request's result is its model's column
segment of the fused output (the per-row model-id segment finish) —
cutting compile count and small-batch p99 (the Booster-paper
ensemble-aware inference scheduling argument, arXiv 2011.02022).

Byte-identity contract (pinned by ``tests/test_serve_lanes.py``):
the fused level descent is exact integer walking, running a shallow
member's settled rows for the group's max depth is a no-op, each
member's class accumulation is a separate dot over exactly its own
tree slice (``ops/predict.predict_level_ensemble_cobatch``), and the
host-side finish goes through the member Booster's own
``_finish_device_scores`` — so co-batched predictions are
byte-identical to a direct ``Booster.predict`` of the same rows.

Eligibility: only entries whose predict calls route to the bucketed
level-descent predictor can be fused — file-loaded (or otherwise
non-scan-routed) models with no extra predict kwargs.  An in-session
single-class Booster's ``device=True`` call routes through the
binned scan, a DIFFERENT numeric path, so fusing it would break the
parity pin; such entries simply keep their solo batcher.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np

from ..booster import _ServingPredictor
from ..telemetry import TELEMETRY
from .batcher import MicroBatcher


def cobatch_key(booster, predict_kwargs: dict, config,
                routes_device: bool):
    """The group a served entry may fuse into, or None when the entry
    is ineligible.  Models fuse when they share this key: identical
    feature width (the dispatch matrix concatenates rows across
    members) and the one bucket ladder the shared config defines."""
    if str(getattr(config, "serve_cobatch", "off")).lower() not in (
            "on", "true", "1"):
        return None
    if not routes_device:
        return None                     # host-walk entries never fuse
    if set(predict_kwargs or {}) - {"device"}:
        return None                     # custom kwargs: solo batcher
    b = booster
    b._sync_models()
    if not b.models:
        return None
    if b._predict_impl() != "level":
        return None                     # scan/pallas A-B paths: solo
    if b._can_device_predict(1, -1, predict_kwargs.get("device")):
        # in-session fast path routes the binned scan, not the level
        # descent the fused program replicates — fusing would break
        # byte parity with direct predict
        return None
    if not b._can_device_predict_loaded(1, -1,
                                        predict_kwargs.get("device")):
        return None
    return ("cobatch", int(b.num_feature()))


class FusedPredictor(_ServingPredictor):
    """A :class:`_ServingPredictor` over SEVERAL members' concatenated
    trees: same bucket ladder, chunk streaming and OOM downshift as a
    solo predictor, but the class accumulator is block-diagonal and
    the dispatch runs the co-batch kernel — output columns
    ``[k0_g : k0_g + k_g)`` are member g's raw scores."""

    def __init__(self, member_models: List[list],
                 num_classes: List[int], config):
        import jax.numpy as jnp
        all_models = [t for ms in member_models for t in ms]
        super().__init__(all_models, 1, config)
        segments = []
        k_total = sum(num_classes)
        onehot = np.zeros((len(all_models), k_total), np.float32)
        t0 = k0 = 0
        for ms, k in zip(member_models, num_classes):
            for j in range(len(ms)):
                # the member's own flatten_ensemble layout: tree j of
                # a k-class ensemble accumulates into class j % k
                onehot[t0 + j, k0 + (j % k)] = 1.0
            segments.append((t0, len(ms), k0, k))
            t0 += len(ms)
            k0 += k
        self.stack = self.stack._replace(cls_onehot=jnp.asarray(onehot))
        self.segments = tuple(segments)
        self.num_class = max(k_total, 1)
        self.kernel = "level"           # the co-batch kernel IS level

    def _dispatch(self, x2_dev):
        from ..ops import predict as P
        from ..reliability.faults import FAULTS
        FAULTS.fault_point("predict.dispatch")
        return P.predict_level_ensemble_cobatch(
            self.stack, x2_dev, depth=self.depth,
            segments=self.segments)


class _Member(NamedTuple):
    name: str
    booster: object
    used: int                   # tree count the fused slice carries
    k0: int                     # first output column
    k: int                      # output column count
    observer: Optional[Callable]


class CoBatcher(MicroBatcher):
    """A :class:`MicroBatcher` whose requests carry a member tag:
    one queue, one coalescing window, one fused dispatch across every
    member — then a per-request segment finish through the member
    Booster's own postprocess."""

    def __init__(self, predict_fn, members: Dict[str, _Member],
                 config=None, pool=None, name: str = "cobatch",
                 clock=None, start: bool = True):
        self.members = members
        super().__init__(predict_fn, config, clock=clock, start=start,
                         name=name, pool=pool)

    def _finish_request(self, r, out, s):
        m = self.members[r.tag]
        raw = np.ascontiguousarray(
            out[s:s + r.n, m.k0:m.k0 + m.k], dtype=np.float64)
        r.result = m.booster._finish_device_scores(raw, m.used)

    def _run_batch(self, batch, lane=None):
        super()._run_batch(batch, lane)
        if not batch or batch[0].error is not None:
            return
        tags = list(dict.fromkeys(r.tag for r in batch))
        tm = TELEMETRY
        if tm.on:
            tm.add("serve_cobatch_dispatches", 1)
            # sum of per-model dispatches this ONE dispatch replaced:
            # the amortization lint compares serve_cobatch_dispatches
            # against this (fused < sum means fusion actually paid)
            tm.add("serve_cobatch_fused_models", len(tags))
        for tag in tags:
            obs = self.members[tag].observer
            if obs is None:
                continue
            part = [r for r in batch if r.tag == tag]
            try:
                rows_m = (part[0].rows if len(part) == 1
                          else np.concatenate([r.rows for r in part]))
                preds_m = (part[0].result if len(part) == 1
                           else np.concatenate([np.atleast_1d(r.result)
                                                for r in part]))
                obs(rows_m, preds_m)
            except Exception as e:
                if tm.on:
                    tm.add("quality_observe_errors", 1)
                if not self._observer_warned:
                    self._observer_warned = True
                    from ..utils.log import Log
                    Log.warning(
                        "co-batch quality observer crashed "
                        f"({type(e).__name__}: {e}); requests are "
                        "unaffected, monitoring may undercount")


class CoBatchGroup:
    """One fused serving unit over >= 2 compatible entries.  Built and
    warmed OFF the registry lock, installed by pointer flip (each
    member entry's ``cobatch`` attribute), drained like any batcher
    when membership changes."""

    def __init__(self, entries: List, config, pool=None):
        # stable member order: by name — the fused program's segment
        # layout (and its jit cache key) is deterministic across
        # rebuilds with the same membership
        entries = sorted(entries, key=lambda e: e.name)
        member_models = []
        num_classes = []
        metas = []
        for e in entries:
            b = e.booster
            b._sync_models()
            used = b._resolve_tree_count(len(b.models), -1)
            member_models.append(b.models[:used])
            num_classes.append(max(b.num_tree_per_iteration, 1))
            metas.append((e, used))
        self.predictor = FusedPredictor(member_models, num_classes,
                                        config)
        members: Dict[str, _Member] = {}
        for (e, used), (t0, tn, k0, k) in zip(
                metas, self.predictor.segments):
            members[e.name] = _Member(
                e.name, e.booster, used, k0, k,
                e.monitor.observe if e.monitor is not None else None)
        self.names = [e.name for e in entries]
        self.versions = {e.name: e.version for e in entries}
        self._lock = threading.Lock()
        self.batcher = CoBatcher(
            self.predictor, members, config, pool=pool,
            name="cobatch:" + "+".join(self.names))

    def submit(self, name: str, rows: np.ndarray) -> np.ndarray:
        return self.batcher.submit(rows, tag=name)

    def warm(self, batch_sizes, devices=(None,)) -> None:
        """Compile the fused program's bucket ladder on every lane
        device BEFORE the group goes live (warm-before-cutover for
        the group pointer flip)."""
        import contextlib
        nf = None
        for m in self.batcher.members.values():
            nf = m.booster.num_feature()
            break
        if nf is None:
            return
        for dev in devices or (None,):
            if dev is not None:
                import jax
                ctx = jax.default_device(dev)
            else:
                ctx = contextlib.nullcontext()
            with ctx:
                for b in batch_sizes or ():
                    self.predictor(np.zeros((max(int(b), 1), nf)))

    def describe(self) -> dict:
        return {"models": list(self.names),
                "queue_depth": self.batcher.depth()}

    def close(self, drain: bool = True) -> None:
        self.batcher.close(drain=drain)
