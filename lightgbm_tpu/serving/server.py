"""Load-shedding HTTP frontend over the bucketed predictor.

Stdlib-only (the project-wide zero-dependency constraint): the
frontend does not open its own port — it mounts routes on the SAME
listener as the r13 telemetry daemon (``TELEMETRY.serve_metrics``),
so one process exposes ``/predict/<model>``, ``/models``,
``/metrics`` and ``/healthz`` together.

Request surface::

    POST /predict/<model>
        body: JSON {"rows": [[...], ...]} (or a bare array), or CSV
              rows (Content-Type text/csv, one row per line), or the
              zero-copy binary frame (Content-Type
              application/x-ltpu-f32: little-endian float32,
              row-major, width = the model's feature count — no
              framing bytes, no text parse; docs/SERVING.md wire spec)
        Accept: application/x-ltpu-f64 answers raw little-endian
              float64 predictions (X-Model-Version /
              X-Prediction-Shape headers) instead of JSON
        200: {"model": ..., "version": ..., "predictions": [...]}
        400 bad body / 404 unknown model / 405 non-POST
        503 + Retry-After: admission control shed the request
            (queue full, or projected wait > serve_shed_deadline_ms)
        500: handler crash — flight-recorder dump, listener survives
    GET /models
        registry listing {name: {version, versions, queue_depth}}

Predictions serialize through ``float -> repr`` JSON round-tripping,
so a client parsing the body recovers byte-identical float64 values
to a direct ``Booster.predict`` of the same rows (the
``tests/test_serving.py`` parity pin).

Reliability seams: every request passes the ``serving.request``
fault point (an injected fault exercises the 500 path), an unhandled
handler exception dumps the crash flight recorder
(``serving_handler_crash``) and answers 500 without tearing down the
listener, and a device OOM inside the predictor engages the r12
bucket-downshift ladder — counted, not fatal.
"""
from __future__ import annotations

import json
import math
import time
from typing import Optional

import numpy as np

from ..reliability.faults import FAULTS
from ..reliability.watchdog import StallError
from ..telemetry import (TELEMETRY, TRACE_HEADER, clear_trace,
                         new_span_id, new_trace_id, parse_trace_header,
                         set_trace)
from ..utils.log import Log
from .batcher import ShedLoad
from .registry import FeatureWidthMismatch, ModelRegistry


# zero-copy binary wire types (docs/SERVING.md): request rows as
# packed little-endian f32 row-major, responses as packed LE f64
BINARY_F32 = "application/x-ltpu-f32"
BINARY_F64 = "application/x-ltpu-f64"


def parse_binary_rows(body: bytes, num_features: int) -> np.ndarray:
    """Decode the binary wire format: packed little-endian float32,
    row-major, row width = the served model's feature count (carried
    by the URL, not the payload — no per-row framing, no text parse,
    no float repr round-trip).  ``np.frombuffer`` is a zero-copy view
    over the request body; the only copy before dispatch is the exact
    f32->f64 widening, so binary requests keep the byte-identity
    parity pin."""
    if num_features <= 0:
        raise ValueError("model reports no features")
    n = len(body)
    if n == 0:
        raise ValueError("empty request body")
    row_bytes = 4 * int(num_features)
    if n % row_bytes:
        raise ValueError(
            f"binary body is {n} bytes — not a multiple of "
            f"{row_bytes} (f32 x {num_features} features per row)")
    return np.frombuffer(body, dtype="<f4").reshape(-1, num_features)


def parse_rows(body: bytes, content_type: str = "") -> np.ndarray:
    """Decode a request body into an (n, F) float64 matrix.  JSON
    (object with "rows"/"data", or a bare nested array) or CSV
    (one row per line, ``,``/whitespace separated).  Raises
    ValueError on anything else."""
    text = body.decode("utf-8", errors="strict").strip()
    if not text:
        raise ValueError("empty request body")
    ctype = (content_type or "").lower()
    if "csv" in ctype or not text.startswith(("[", "{")):
        rows = [[float(tok) for tok in
                 ln.replace("\t", ",").replace(" ", ",").split(",")
                 if tok != ""]
                for ln in text.splitlines() if ln.strip()]
    else:
        obj = json.loads(text)
        if isinstance(obj, dict):
            obj = obj.get("rows", obj.get("data"))
            if obj is None:
                raise ValueError('JSON body must carry "rows"')
        rows = obj
    arr = np.asarray(rows, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr[None, :]
    if arr.ndim != 2 or arr.shape[1] == 0:
        raise ValueError(f"rows must be a 2D matrix, got shape "
                         f"{arr.shape}")
    return arr


def _json_response(status: int, payload: dict, extra=None):
    return (status, "application/json",
            json.dumps(payload).encode(), extra)


class ServingFrontend:
    """Mounts the serving routes on the shared telemetry listener and
    answers them against a :class:`ModelRegistry`."""

    def __init__(self, registry: ModelRegistry, config=None):
        self.registry = registry
        self.config = config
        self._srv = None
        self._owns_listener = False

    # -- lifecycle -----------------------------------------------------
    def start(self, port: Optional[int] = None):
        """Register routes and ensure the shared HTTP listener runs.
        ``port=None`` resolves ``telemetry_http_port`` (an
        already-running daemon is reused as-is) then ``serve_port``
        (0 = ephemeral).  Returns the server."""
        tm = TELEMETRY
        tm.register_http_route("/predict/", self._predict_route)
        tm.register_http_route("/models", self._models_route)
        tm.register_http_route("/quality/", self._quality_route)
        if port is None:
            port = int(getattr(self.config, "telemetry_http_port", 0)) \
                or int(getattr(self.config, "serve_port", 0))
        self._owns_listener = tm._http is None
        self._srv = tm.serve_metrics(int(port))
        return self._srv

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    def stop(self, drain: bool = True) -> None:
        """Unmount the serving routes and drain the registry.  The
        listener is stopped only if ``start()`` created it — a
        pre-existing ``telemetry_http_port`` daemon keeps scraping
        after serving shuts down."""
        tm = TELEMETRY
        tm.unregister_http_route("/predict/")
        tm.unregister_http_route("/models")
        tm.unregister_http_route("/quality/")
        if drain:
            self.registry.close()
        if self._srv is not None:
            if self._owns_listener:
                tm.stop_metrics_server()
            self._srv = None

    # -- routes --------------------------------------------------------
    def _models_route(self, method, path, body, headers):
        return _json_response(200, self.registry.describe())

    def _quality_route(self, method, path, body, headers):
        """``GET /quality/<model>``: the serving quality monitor's
        full drift report (per-feature PSI + online/reference counts,
        score/leaf drift, thresholds — docs/MODEL_MONITORING.md).
        404 when the model is unknown or no monitor is armed."""
        if method != "GET":
            return _json_response(
                405, {"error": "GET /quality/<model>"},
                {"Allow": "GET"})
        name = path.split("?", 1)[0].rstrip("/").rsplit("/", 1)[-1]
        if not name or name == "quality":
            return _json_response(
                404, {"error": "no model in path; GET "
                               "/quality/<model>"})
        try:
            entry = self.registry.get(name)
        except KeyError:
            return _json_response(
                404, {"error": f"no model named {name!r}",
                      "models": self.registry.names()})
        if entry.monitor is None:
            return _json_response(
                404, {"error": f"no quality monitor armed for "
                               f"{name!r} (quality=off, "
                               "quality_sample_rate=0, or no "
                               "fingerprint-matching profile beside "
                               "the model)",
                      "model": name, "version": entry.version})
        return _json_response(200, entry.monitor.report())

    def _predict_route(self, method, path, body, headers):
        t0 = time.perf_counter()
        tm = TELEMETRY
        # causal trace context (docs/OBSERVABILITY.md, Tracing): adopt
        # the client's X-Ltpu-Trace trace id (a malformed header
        # degrades to untraced), mint this request's own span id, and
        # install the pair in the contextvar for the request's
        # lifetime — the micro-batcher snapshots it at submit, the
        # journal stamps it on any event fired underneath.  With no
        # client header a new trace id is minted only when spans are
        # recording; off/counters stay one mode check.
        inbound = parse_trace_header(
            headers.get(TRACE_HEADER, "") if headers is not None
            else "")
        token = None
        attrs = {}
        if inbound is not None or tm.spans_on:
            trace_id = inbound[0] if inbound is not None \
                else new_trace_id()
            span_id = new_span_id()
            token = set_trace(trace_id, span_id)
            attrs = {"trace": trace_id, "span": span_id}
            if inbound is not None:
                attrs["parent"] = inbound[1]
        span = tm.start_span("serve_request", **attrs)
        try:
            resp = self._handle_predict(method, path, body, headers)
        except Exception as e:
            # handler crash: dump the flight recorder (when armed)
            # with the serving seam, answer 500, keep the listener up
            tm.flight.dump("serving_handler_crash",
                           seam="serving.request",
                           error=repr(e)[:300])
            if tm.on:
                tm.add("serve_errors", 1)
            resp = _json_response(500, {"error": repr(e)[:300]})
        finally:
            tm.end_span(span)
            if token is not None:
                clear_trace(token)
        if token is not None:
            # echo the context so the client can find its request in
            # the merged timeline (and propagate it further)
            status, ctype, rbody, extra = resp
            extra = dict(extra or {})
            extra.setdefault(TRACE_HEADER,
                             f"{attrs['trace']}-{attrs['span']}")
            resp = (status, ctype, rbody, extra)
        if tm.on:
            tm.add("serve_http_requests", 1)
            tm.observe("serve_request_ms",
                       (time.perf_counter() - t0) * 1e3)
        return resp

    def _handle_predict(self, method, path, body, headers):
        FAULTS.fault_point("serving.request")
        if method != "POST":
            return _json_response(
                405, {"error": "POST rows to /predict/<model>"},
                {"Allow": "POST"})
        name = path.split("?", 1)[0].rstrip("/").rsplit("/", 1)[-1]
        if not name or name == "predict":
            return _json_response(
                404, {"error": "no model in path; POST "
                               "/predict/<model>"})
        ctype = (headers.get("Content-Type", "")
                 if headers is not None else "")
        if BINARY_F32 in ctype.lower():
            # binary frame width comes from the served model; a hot
            # swap to a different width between this read and submit
            # is caught by the registry's per-attempt width check
            try:
                nf = self.registry.get(name).booster.num_feature()
            except KeyError:
                return _json_response(
                    404, {"error": f"no model named {name!r}",
                          "models": self.registry.names()})
            try:
                rows = parse_binary_rows(bytes(body), nf)
            except ValueError as e:
                return _json_response(400, {"error": str(e)[:300]})
            if TELEMETRY.on:
                TELEMETRY.add("serve_binary_requests", 1)
        else:
            try:
                rows = parse_rows(bytes(body), ctype)
            except (ValueError, json.JSONDecodeError,
                    UnicodeDecodeError) as e:
                return _json_response(400, {"error": str(e)[:300]})
        try:
            entry, out = self.registry.predict(name, rows)
        except KeyError:
            return _json_response(
                404, {"error": f"no model named {name!r}",
                      "models": self.registry.names()})
        except FeatureWidthMismatch as e:
            # rejected at admission, validated against the exact
            # entry the rows would have been submitted to: a
            # wrong-width matrix inside a coalesced batch would fail
            # the concatenate and 500 every innocent batchmate
            return _json_response(400, {"error": str(e)})
        except ShedLoad as e:
            # load shedding: tell the client when to come back
            # instead of queueing it into a timeout
            return _json_response(
                503, {"error": str(e)},
                {"Retry-After": str(max(1, math.ceil(e.retry_after_s)))})
        except StallError as e:
            # stall-classified (the watchdog blew a serve-dispatch
            # deadline, stacks already flight-dumped): 503, not 500 —
            # the model may recover or be rolled back, so the client
            # should retry elsewhere/later rather than treat it as a
            # bug in its request
            return _json_response(
                503, {"error": f"serving stalled: {e}", "stall": True},
                {"Retry-After": "1"})
        except Exception as e:
            # dispatch failure, not a handler crash: the batcher
            # already counted serve_errors per affected request and
            # the OOM/flight machinery below it owns the dump — a
            # second count + crash-labeled dump here would double
            # every dispatch error
            return _json_response(
                500, {"error": f"prediction failed: {repr(e)[:300]}"})
        accept = (headers.get("Accept", "")
                  if headers is not None else "")
        if BINARY_F64 in accept.lower():
            # binary response: the float64 scores exactly as the
            # predictor produced them, packed little-endian — no repr
            # formatting, no JSON escape pass
            arr = np.ascontiguousarray(np.asarray(out), dtype="<f8")
            return (200, BINARY_F64, arr.tobytes(), {
                "X-Model-Version": str(entry.version),
                "X-Prediction-Shape":
                    "x".join(str(d) for d in arr.shape),
            })
        return _json_response(200, {
            "model": name,
            "version": entry.version,
            # float64 -> Python float -> repr round-trips exactly:
            # the client recovers byte-identical doubles
            "predictions": np.asarray(out).tolist(),
        })


def serve(registry: ModelRegistry, config=None,
          port: Optional[int] = None) -> ServingFrontend:
    """Convenience one-liner: mount ``registry`` and start serving."""
    frontend = ServingFrontend(registry, config)
    srv = frontend.start(port)
    Log.info("serving frontend on "
             f"http://127.0.0.1:{srv.server_address[1]} "
             f"(models: {', '.join(registry.names()) or '<none>'}; "
             "POST /predict/<model>, GET /models /metrics /healthz)")
    return frontend
