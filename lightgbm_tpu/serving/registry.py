"""Model registry: named, versioned Boosters with atomic hot swap.

Deploys must never serve a cold compile: ``publish`` warms the new
version's serving-predictor buckets (``Booster.warm_predictor`` —
with ``compile_cache_dir`` wired this is a disk hit in repeat
processes, visible as ``compile_cache_hits``) BEFORE the cutover, so
the new version's first request dispatches an already-compiled
bucket.  The cutover itself is one pointer flip under the registry
lock; entries are immutable (booster + version + batcher fixed at
publish), so a request that grabbed an entry can never observe a
half-swapped ensemble.  The old version's micro-batcher then drains
its in-flight queue and closes — a submit that raced the swap gets
:class:`~lightgbm_tpu.serving.batcher.BatcherClosed` and the
registry transparently retries against the new current entry, so
hot swap produces zero failed and zero mixed-version responses
(pinned by ``tests/test_serving.py``).

Rollback is the same pointer flip back to the previous version
(kept resident: its booster — and the process-wide compiled
programs underneath — stay warm), with a fresh batcher replacing
the drained one.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..telemetry import TELEMETRY
from ..utils.log import Log
from .batcher import BatcherClosed, MicroBatcher
from .cobatch import CoBatchGroup, cobatch_key
from .lanes import LanePool, resolve_lanes


class FeatureWidthMismatch(ValueError):
    """Request rows don't match the served model's feature count.
    Raised per attempt inside :meth:`ModelRegistry.predict` (so a
    width check can never race a hot swap to a different-width
    model); the HTTP frontend maps it to 400."""

    def __init__(self, expected: int, got: int):
        super().__init__(f"expected {expected} features per row, "
                         f"got {got}")
        self.expected = expected
        self.got = got


class ModelEntry:
    """One immutable (name, version) serving unit: the Booster, its
    predict closure, the micro-batcher that owns its in-flight queue,
    and the publish-time audit metadata (``meta``: who published it,
    when, and at what eval metric — what a rollback decision reads)."""

    __slots__ = ("name", "version", "booster", "batcher", "_predict_fn",
                 "meta", "monitor", "cobatch", "cobatch_k")

    def __init__(self, name: str, version: int, booster, predict_fn,
                 batcher: MicroBatcher, meta=None, monitor=None,
                 cobatch_k=None):
        self.name = name
        self.version = int(version)
        self.booster = booster
        self._predict_fn = predict_fn
        self.batcher = batcher
        self.meta: dict = dict(meta or {})
        # per-version serving quality monitor (lightgbm_tpu/quality/),
        # or None when quality=off / no profile — the off-mode cost is
        # this one attribute staying None
        self.monitor = monitor
        # co-batching (lightgbm_tpu/serving/cobatch.py): the fusion
        # key this entry is eligible under (None = never fuses), and
        # the live group pointer the registry flips when membership
        # changes — requests route to the group's fused batcher while
        # set, to this entry's solo batcher otherwise
        self.cobatch_k = cobatch_k
        self.cobatch = None

    def predict(self, rows: np.ndarray) -> np.ndarray:
        group = self.cobatch
        if group is not None:
            return group.submit(self.name, rows)
        return self.batcher.submit(rows)


class ModelRegistry:
    """Process-local registry of served models (one per frontend)."""

    def __init__(self, config=None):
        self.config = config
        self._lock = threading.Lock()
        # drift→refit hook (quality monitors read it at FIRE time,
        # late-bound): ContinuousLane.start() installs its
        # report_serving_drift here so serving-side drift past
        # quality_drift_refit_threshold lands in the lane's
        # ledger-committed drift tally (docs/MODEL_MONITORING.md)
        self.on_quality_drift = None
        self._current: Dict[str, ModelEntry] = {}
        self._versions: Dict[str, List[ModelEntry]] = {}
        # serving history per name: what _current pointed at before
        # each swap, in order — rollback restores from HERE, not from
        # publish order (after rollback-then-republish, the previous
        # SERVING version is not the previously PUBLISHED one)
        self._history: Dict[str, List[ModelEntry]] = {}
        # lane fleet (lightgbm_tpu/serving/lanes.py): built lazily at
        # first publish from serve_lanes; None when the config
        # resolves to a single lane (today's inline dispatch)
        self._pool: Optional[LanePool] = None
        self._pool_init = False
        # co-batch groups (serving/cobatch.py) by fusion key; control
        # -plane swaps (publish/rollback) serialize on _swap_lock so
        # group membership never races a concurrent publish
        self._groups: Dict[tuple, CoBatchGroup] = {}
        self._swap_lock = threading.Lock()

    # -- lane fleet ----------------------------------------------------
    def _ensure_pool(self) -> Optional[LanePool]:
        """Build the lane pool on first use (``serve_lanes=auto|N``).
        None when the config resolves to one lane — requests then run
        on each batcher's own dispatcher thread exactly as before the
        fleet existed."""
        with self._lock:
            if not self._pool_init:
                self._pool_init = True
                n, devices = resolve_lanes(self.config)
                if n >= 2:
                    self._pool = LanePool(devices, name="serve")
                    Log.info(
                        f"serving lane pool: {n} lanes"
                        + (" (simulated on one device)"
                           if all(d is None for d in devices)
                           else f" on {len(set(map(str, devices)))} "
                                "device(s)"))
            return self._pool

    @property
    def pool(self) -> Optional[LanePool]:
        return self._pool

    # -- publish / swap ------------------------------------------------
    @staticmethod
    def _routes_to_device(predict_kwargs: dict) -> bool:
        """Whether this entry's predict calls will reach the bucketed
        device predictor (what ``warm_predictor`` compiles).  Pinned
        routing wins; auto routing follows the backend."""
        device = predict_kwargs.get("device")
        if device is not None:
            return bool(device)
        import jax
        return jax.default_backend() in ("tpu", "axon")

    def _default_warm(self, predict_kwargs: dict) -> Tuple[int, ...]:
        cfg = self.config
        declared = tuple(getattr(cfg, "predict_warm_buckets", ()) or ())
        if declared:
            # explicitly declared shapes always warm — the operator
            # said so (e.g. ahead of forcing device routing later)
            return declared
        if not self._routes_to_device(predict_kwargs):
            # auto routing on a host backend takes the float64 tree
            # walk: compiling the device bucket ladder would burn
            # publish time on programs no request ever dispatches
            Log.debug("serving registry: implicit warm skipped — "
                      "predict routes to the host walk on this "
                      "backend")
            return ()
        # no declared shapes: warm the WHOLE power-of-two ladder from
        # the single-row bucket up to the coalesced-dispatch cap — a
        # mid-size coalesced batch lands on an intermediate bucket,
        # and warming only the endpoints would leave it a cold
        # compile mid-traffic (with compile_cache_dir wired, repeat
        # deploys disk-hit every rung anyway)
        lo = max(1, int(getattr(cfg, "predict_min_bucket_rows", 16)))
        hi = max(lo, int(getattr(cfg, "serve_max_batch_rows", 1024)))
        ladder = []
        b = lo
        while b < hi:
            ladder.append(b)
            b <<= 1
        ladder.append(hi)
        return tuple(ladder)

    def publish(self, name: str, model, version: Optional[int] = None,
                warm: Optional[Tuple[int, ...]] = None,
                predict_kwargs: Optional[dict] = None,
                log_warm: bool = False,
                published_unix: Optional[float] = None,
                eval_metric: Optional[float] = None,
                source: str = "manual") -> ModelEntry:
        """Register ``model`` (a Booster or a model-file path) as the
        new current version of ``name``.  Buckets are warmed BEFORE
        the pointer flip; the replaced version drains its in-flight
        work and releases its dispatcher.

        Audit metadata (surfaced per version by ``GET /models`` so a
        rollback decision can be traced): ``published_unix`` is the
        publish wall clock PASSED IN BY THE CALLER (the registry never
        stamps it itself — the continuous lane records the clock its
        ledger committed, so a crash-replayed publish carries the same
        timestamp), ``eval_metric`` the gate metric the candidate
        scored at publish, and ``source`` who published it
        (``manual`` | ``continuous``)."""
        with self._swap_lock:
            return self._publish_locked(
                name, model, version=version, warm=warm,
                predict_kwargs=predict_kwargs, log_warm=log_warm,
                published_unix=published_unix,
                eval_metric=eval_metric, source=source)

    def _publish_locked(self, name, model, version=None, warm=None,
                        predict_kwargs=None, log_warm=False,
                        published_unix=None, eval_metric=None,
                        source="manual") -> ModelEntry:
        from ..booster import Booster
        if source not in ("manual", "continuous"):
            raise ValueError(
                f"publish source must be manual/continuous, got "
                f"{source!r}")
        cfg = self.config
        if isinstance(model, str):
            booster = Booster(config=cfg, model_file=model)
        else:
            booster = model
        meta = {"source": source}
        if published_unix is not None:
            meta["published_unix"] = round(float(published_unix), 6)
        if eval_metric is not None:
            meta["eval_metric"] = float(eval_metric)
        kw = dict(predict_kwargs or {})
        pool = self._ensure_pool()

        def predict_fn(rows, _b=booster, _kw=kw):
            return _b.predict(rows, **_kw)

        warm = self._default_warm(kw) if warm is None else tuple(warm)
        if warm:
            # warm-before-cutover: compile (or disk-hit) every
            # declared bucket while the OLD version still serves —
            # on EVERY lane's device, so no lane takes a cold compile
            # after the pointer flip
            booster.warm_predictor(
                warm, log=log_warm,
                devices=pool.warm_devices if pool is not None
                else None)
        # serving quality monitor (lightgbm_tpu/quality/): armed when
        # the knobs allow it AND a fingerprint-matching profile rides
        # the model (sidecar file for a path publish, the in-memory
        # engine.train attachment for a Booster publish); observes
        # every coalesced dispatch read-only through the batcher hook
        from ..quality import maybe_monitor
        monitor = maybe_monitor(model, booster, cfg, name,
                                registry=self)
        with self._lock:
            versions = self._versions.setdefault(name, [])
            if version is None:
                version = max((e.version for e in versions),
                              default=0) + 1
            version = int(version)
            if any(e.version == version for e in versions):
                raise ValueError(
                    f"model {name!r} already has a version {version}")
            entry = ModelEntry(
                name, version, booster, predict_fn,
                MicroBatcher(predict_fn, cfg,
                             name=f"{name}@v{version}",
                             observer=monitor.observe
                             if monitor is not None else None,
                             pool=pool),
                meta=meta, monitor=monitor,
                cobatch_k=cobatch_key(booster, kw, cfg,
                                      self._routes_to_device(kw)))
            versions.append(entry)
            old = self._current.get(name)
            if old is not None:
                self._history.setdefault(name, []).append(old)
            self._current[name] = entry      # THE atomic cutover
        tm = TELEMETRY
        if tm.on:
            tm.add("serve_model_swaps" if old is not None
                   else "serve_model_publishes", 1)
            tm.gauge(f"serve_version.{name}", version)
        tm.journal.emit(
            "publish", seam="serving.request", model=name,
            version=version,
            **({"replaced": old.version} if old is not None else {}))
        if old is not None:
            # new version already serves; finish the old one's queue
            old.batcher.close(drain=True)
        self._refresh_cobatch()
        Log.info(f"serving registry: {name!r} -> v{version}"
                 + (f" (replaced v{old.version})" if old else "")
                 + (f", warmed buckets {list(warm)}" if warm else ""))
        return entry

    def _refresh_cobatch(self) -> None:
        """Recompute fused groups from the current pointers (runs
        under ``_swap_lock`` after every publish/rollback flip).  Each
        fusion key with >= 2 eligible current entries gets one
        :class:`CoBatchGroup`; a new group is built and warmed OFF the
        registry lock, installed by pointer flip on every member
        entry, and only then is the replaced group drained — the same
        warm-before-cutover / drain-after discipline as a version
        swap, so membership changes lose zero requests."""
        with self._lock:
            current = dict(self._current)
        desired: Dict[tuple, list] = {}
        for entry in current.values():
            if entry.cobatch_k is not None:
                desired.setdefault(entry.cobatch_k, []).append(entry)
        desired = {k: es for k, es in desired.items() if len(es) >= 2}
        retired = []
        for key, entries in desired.items():
            old = self._groups.get(key)
            versions = {e.name: e.version for e in entries}
            if old is not None and old.versions == versions:
                continue                 # membership unchanged
            group = CoBatchGroup(entries, self.config,
                                 pool=self._pool)
            devs = (self._pool.warm_devices
                    if self._pool is not None else (None,))
            group.warm(self._default_warm({}) or (1,), devices=devs)
            with self._lock:
                self._groups[key] = group
                for e in entries:
                    e.cobatch = group
            if old is not None:
                retired.append(old)
            Log.info("serving registry: co-batch group "
                     + "+".join(group.names) + " live "
                     + f"({len(group.names)} models, one fused "
                     "program)")
        for key in [k for k in self._groups if k not in desired]:
            retired.append(self._groups.pop(key))
        with self._lock:
            live = set(map(id, self._groups.values()))
            for entry in current.values():
                g = entry.cobatch
                if g is not None and (id(g) not in live
                                      or entry.name not in g.names):
                    entry.cobatch = None
        for g in retired:
            g.close(drain=True)

    def rollback(self, name: str) -> ModelEntry:
        """Pointer-flip ``name`` back to the version that was SERVING
        before the current one took over (the serving history, not
        publish order — after a rollback-then-republish, the previous
        publish may be the very version ops already rolled back as
        bad).  The restored version's compiled programs are still
        resident, so rollback serves warm immediately."""
        with self._swap_lock:
            return self._rollback_locked(name)

    def _rollback_locked(self, name: str) -> ModelEntry:
        with self._lock:
            if name not in self._current:
                raise KeyError(f"no model named {name!r}")
            cur = self._current[name]
            hist = self._history.get(name) or []
            if not hist:
                raise ValueError(
                    f"model {name!r} has no earlier serving version "
                    f"to roll back to (current v{cur.version})")
            prev = hist.pop()
            if prev.batcher.closed:
                prev.batcher = MicroBatcher(
                    prev._predict_fn, self.config,
                    name=f"{name}@v{prev.version}",
                    observer=prev.monitor.observe
                    if prev.monitor is not None else None,
                    pool=self._pool)
            self._current[name] = prev
        tm = TELEMETRY
        if tm.on:
            tm.add("serve_rollbacks", 1)
            tm.gauge(f"serve_version.{name}", prev.version)
        tm.journal.emit(
            "rollback", seam="serving.request", model=name,
            from_version=cur.version, to_version=prev.version)
        cur.batcher.close(drain=True)
        self._refresh_cobatch()
        Log.warning(f"serving registry: rolled {name!r} back "
                    f"v{cur.version} -> v{prev.version}")
        return prev

    # -- lookup / serve ------------------------------------------------
    def get(self, name: str) -> ModelEntry:
        with self._lock:
            entry = self._current.get(name)
        if entry is None:
            raise KeyError(f"no model named {name!r}")
        return entry

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._current)

    def predict(self, name: str,
                rows: np.ndarray) -> Tuple[ModelEntry, np.ndarray]:
        """Serve one request against the current version of ``name``.
        A submit that lands on a version mid-drain (hot-swap race)
        retries against the new current pointer — the caller never
        sees the swap.  Feature width is validated against the SAME
        entry the request is submitted to (per attempt, so a swap to
        a different-width model between check and submit is
        impossible); a mismatch raises
        :class:`FeatureWidthMismatch`, which one bad client gets as
        a 400 instead of failing every batchmate's concatenate."""
        rows = np.asarray(rows, dtype=np.float64)
        if rows.ndim == 1:
            rows = rows[None, :]
        from ..reliability.watchdog import StallError
        for _ in range(64):
            entry = self.get(name)
            nf = entry.booster.num_feature()
            if rows.shape[1] != nf:
                raise FeatureWidthMismatch(nf, rows.shape[1])
            try:
                # entry.predict routes to the fused co-batch group
                # when one is live, the solo batcher otherwise; a
                # group drained by a membership change raises
                # BatcherClosed like any swap race and retries against
                # the refreshed pointers
                return entry, entry.predict(rows)
            except BatcherClosed:
                continue
            except StallError as e:
                # stall classification (docs/RELIABILITY.md): the
                # version's dispatch blew its watchdog_serve_s
                # deadline.  NOT retried here — the same wedged
                # program would stall again and multiply the damage;
                # the error names the model so ops can correlate the
                # flight dump, and the frontend answers 503
                TELEMETRY.flight.note(
                    "stall", f"serve:{name}", version=entry.version)
                raise StallError(
                    f"serving {name!r} v{entry.version}", e.seam,
                    e.deadline_s, e.elapsed_s) from e
        raise RuntimeError(
            f"model {name!r}: current version kept closing underneath "
            "the request (registry shutting down?)")

    def describe(self) -> Dict[str, dict]:
        """The ``/models`` endpoint body.  ``versions`` carries one
        record per published version with its audit metadata
        (``published_unix`` / ``eval_metric`` / ``source`` as passed to
        :meth:`publish`) and whether that version is the one currently
        serving — the trail a rollback decision is audited against.
        Versions with an armed quality monitor additionally carry a
        live ``quality`` block (worst-feature PSI, score drift,
        sampled-row count; full detail on ``GET /quality/<model>``) —
        the registry is the one pane of glass."""
        with self._lock:
            # snapshot ONLY under the registry lock; the monitor
            # summaries (which take each monitor's own lock, possibly
            # held through a whole observation pass) are built after
            # release — a /models poll must never park /predict
            # requests behind a monitoring refresh
            snap = {name: (entry, list(self._versions.get(name, [])),
                           entry.cobatch)
                    for name, entry in self._current.items()}
            pool = self._pool
        body: Dict[str, dict] = {
            name: {
                "version": entry.version,
                "versions": [
                    {"version": e.version,
                     "serving": e is entry, **e.meta,
                     **({"quality": e.monitor.summary()}
                        if e.monitor is not None else {})}
                    for e in versions],
                # group-aware: a fused entry's in-flight work lives in
                # the GROUP's queue, not the (idle) solo batcher's
                "queue_depth": (group.batcher.depth()
                                if group is not None
                                else entry.batcher.depth()),
                **({"cobatch": group.describe()}
                   if group is not None else {}),
                "quality": (entry.monitor.summary()
                            if entry.monitor is not None else None),
            }
            for name, (entry, versions, group) in snap.items()
        }
        if pool is not None:
            # per-lane state (snapshot-and-release inside the pool:
            # a /models poll never parks dispatch routing)
            body["_fleet"] = {
                "n_lanes": pool.n_lanes,
                "healthy_lanes": pool.healthy_count(),
                "lanes": pool.snapshot(),
            }
        return body

    def close(self) -> None:
        """Drain and release every entry (process shutdown): fused
        groups first (they feed the lanes), then solo batchers, then
        the lane pool itself."""
        with self._swap_lock:
            with self._lock:
                entries = [e for vs in self._versions.values()
                           for e in vs]
                groups = list(self._groups.values())
                self._current.clear()
                self._versions.clear()
                self._history.clear()
                self._groups.clear()
                for e in entries:
                    e.cobatch = None
            for g in groups:
                g.close(drain=True)
            for e in entries:
                e.batcher.close(drain=True)
            pool, self._pool, self._pool_init = self._pool, None, False
            if pool is not None:
                pool.close()
