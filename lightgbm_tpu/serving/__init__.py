"""Online serving subsystem: the path from socket to device and back.

Three layers over the r8 shape-bucketed compiled predictor
(docs/SERVING.md):

- :mod:`.batcher` — micro-batching scheduler: concurrent requests
  coalesce into one power-of-two-bucket dispatch under a deadline
  knob, with bounded-queue admission control (load shedding).
- :mod:`.lanes` — the device lane fleet: N parallel dispatch streams
  (``serve_lanes=auto|N``), round-robin routing with work stealing,
  per-lane stall isolation.
- :mod:`.cobatch` — multi-model co-batching: compatible served
  models fuse into ONE compiled program and one coalescing window
  (``serve_cobatch=on``), with a per-request segment finish.
- :mod:`.registry` — named, versioned Boosters with atomic hot swap:
  buckets warm BEFORE cutover (on every lane device), the old
  version drains then releases, rollback is a pointer flip.
- :mod:`.server` — stdlib HTTP frontend sharing one listener with
  the telemetry ``/metrics`` + ``/healthz`` daemon; JSON/CSV bodies
  plus the zero-copy ``application/x-ltpu-f32`` binary frame.

CLI: ``python -m lightgbm_tpu task=serve input_model=model.txt``;
load generator: ``scripts/serve_bench.py``.
"""
from .batcher import BatcherClosed, MicroBatcher, ShedLoad
from .cobatch import CoBatchGroup, cobatch_key
from .lanes import Lane, LanePool, resolve_lanes
from .registry import FeatureWidthMismatch, ModelEntry, ModelRegistry
from .server import (BINARY_F32, BINARY_F64, ServingFrontend,
                     parse_binary_rows, parse_rows, serve)

__all__ = ["MicroBatcher", "ShedLoad", "BatcherClosed",
           "FeatureWidthMismatch", "ModelEntry", "ModelRegistry",
           "ServingFrontend", "parse_rows", "serve",
           "Lane", "LanePool", "resolve_lanes",
           "CoBatchGroup", "cobatch_key",
           "BINARY_F32", "BINARY_F64", "parse_binary_rows"]
