"""Online serving subsystem: the path from socket to device and back.

Three layers over the r8 shape-bucketed compiled predictor
(docs/SERVING.md):

- :mod:`.batcher` — micro-batching scheduler: concurrent requests
  coalesce into one power-of-two-bucket dispatch under a deadline
  knob, with bounded-queue admission control (load shedding).
- :mod:`.registry` — named, versioned Boosters with atomic hot swap:
  buckets warm BEFORE cutover, the old version drains then releases,
  rollback is a pointer flip.
- :mod:`.server` — stdlib HTTP frontend sharing one listener with
  the telemetry ``/metrics`` + ``/healthz`` daemon.

CLI: ``python -m lightgbm_tpu task=serve input_model=model.txt``;
load generator: ``scripts/serve_bench.py``.
"""
from .batcher import BatcherClosed, MicroBatcher, ShedLoad
from .registry import FeatureWidthMismatch, ModelEntry, ModelRegistry
from .server import ServingFrontend, parse_rows, serve

__all__ = ["MicroBatcher", "ShedLoad", "BatcherClosed",
           "FeatureWidthMismatch", "ModelEntry", "ModelRegistry",
           "ServingFrontend", "parse_rows", "serve"]
