"""Shared jaxpr / StableHLO walkers — the ONE implementation of the
compiled-program introspection that `tests/test_carry_hlo.py`,
`tests/test_predict_cache.py` and the `lightgbm_tpu.analysis` rule
engine all used to private-copy.

Every helper takes a plain ``jaxpr`` (a ``jax.core.Jaxpr``; pass
``closed.jaxpr`` for a ClosedJaxpr) and recurses into every sub-jaxpr
reachable through eqn params — scan/while/cond bodies, pjit calls,
custom_* envelopes — so a primitive count is a whole-program count no
matter how deeply XLA's control-flow nesting buries it.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Set


def _sub_jaxprs(eqn) -> Iterator:
    """Every jaxpr hanging off one equation's params (closed jaxprs are
    unwrapped to their inner jaxpr)."""
    for v in eqn.params.values():
        if hasattr(v, "jaxpr"):            # ClosedJaxpr
            yield v.jaxpr
        elif hasattr(v, "eqns"):           # bare Jaxpr
            yield v
        elif isinstance(v, (list, tuple)):
            for b in v:
                if hasattr(b, "jaxpr"):
                    yield b.jaxpr
                elif hasattr(b, "eqns"):
                    yield b


def iter_eqns(jaxpr) -> Iterator:
    """Depth-first generator over every equation in ``jaxpr`` and all
    nested sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def find_primitives(jaxpr, name: str) -> List:
    """All equations (any nesting depth) whose primitive is ``name``."""
    return [e for e in iter_eqns(jaxpr) if e.primitive.name == name]


def count_primitive(jaxpr, name: str) -> int:
    """Whole-program occurrence count of primitive ``name``."""
    return sum(1 for e in iter_eqns(jaxpr) if e.primitive.name == name)


def find_scans(jaxpr, length: Optional[int] = None) -> List:
    """All ``scan`` equations, optionally filtered to an exact trip
    count (``params["length"]``) — how the carry tests pick the
    boosting scan out of a program whose inner kernels scan too."""
    scans = find_primitives(jaxpr, "scan")
    if length is not None:
        scans = [s for s in scans if s.params.get("length") == length]
    return scans


def scan_output_stacks(scan_eqn) -> int:
    """Number of O(length) output buffers (ys) a scan stacks — the
    loop-carried output stores the round-6 chunk-slope diagnosis traced
    the per-iteration dispatch penalty to."""
    return len(scan_eqn.outvars) - scan_eqn.params["num_carry"]


def jaxpr_dtypes(jaxpr) -> Set[str]:
    """Every aval dtype name appearing anywhere in the program
    (inputs, outputs, and every equation's operands/results)."""
    out: Set[str] = set()

    def _add(v):
        aval = getattr(v, "aval", None)
        dt = getattr(aval, "dtype", None)
        if dt is not None:
            out.add(str(dt))

    def _walk(jx):
        for v in list(jx.invars) + list(jx.outvars) + list(jx.constvars):
            _add(v)
        for eqn in jx.eqns:
            for v in list(eqn.invars) + list(eqn.outvars):
                _add(v)
            for sub in _sub_jaxprs(eqn):
                _walk(sub)

    _walk(jaxpr)
    return out


def primitive_names(jaxpr) -> Set[str]:
    """Set of every primitive name in the program (nested included)."""
    return {e.primitive.name for e in iter_eqns(jaxpr)}


def scatter_eqns_with_dtype(jaxpr, dtype_name: str) -> List:
    """Scatter-family equations touching an operand of ``dtype_name``
    — the jaxpr-level form of the "no uint8 scatter" tree-record
    guarantee (more robust than regexing operand types out of the
    StableHLO text, where the type signature trails the region body)."""
    hits = []
    for eqn in iter_eqns(jaxpr):
        if not eqn.primitive.name.startswith("scatter"):
            continue
        if any(str(getattr(v.aval, "dtype", "")) == dtype_name
               for v in eqn.invars if hasattr(v, "aval")):
            hits.append(eqn)
    return hits


# -- StableHLO text helpers -------------------------------------------------

# ops whose presence means the module's shapes are not fully static
DYNAMIC_SHAPE_OPS = (
    "stablehlo.dynamic_reshape",
    "stablehlo.dynamic_broadcast_in_dim",
    "stablehlo.dynamic_iota",
    "stablehlo.dynamic_pad",
    "stablehlo.dynamic_gather",
    "stablehlo.dynamic_conv",
    "stablehlo.real_dynamic_slice",
)

# host-transfer / callback markers in lowered text
HOST_CALLBACK_MARKERS = (
    "stablehlo.infeed",
    "stablehlo.outfeed",
    "xla_python_cpu_callback",
    "xla_python_gpu_callback",
    "xla_ffi_python",
)

# jaxpr primitives that round-trip through the host per dispatch
HOST_CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_callback_call",
})


def count_op(text: str, op: str) -> int:
    """Occurrences of a StableHLO op name in lowered module text."""
    return text.count(op)


def dynamic_shape_markers(text: str) -> List[str]:
    """Dynamic-shape evidence in a lowered module: any dynamic-shape
    op, or an unranked/dynamic tensor type (``tensor<?``)."""
    found = [op for op in DYNAMIC_SHAPE_OPS if op in text]
    if "tensor<?" in text:
        found.append("tensor<?...> (dynamic dimension)")
    return found
