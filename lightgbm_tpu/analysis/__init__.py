"""lightgbm_tpu.analysis — compiled-program lint framework.

Two engines, one CLI:

- **Program-invariant checker** (``hlo_rules``): declarative rules
  HLO001-HLO008 over the lowered jaxpr / StableHLO / compiled HLO of
  the registered hot entry points (``programs``), converting the
  r6-r9 incident learnings (carry stacks, scatter regressions, buffer
  donation, retrace churn) into machine-enforced invariants.
- **Trace-safety AST pass** (``ast_rules``): host-library calls and
  data-dependent Python branching inside jit-reachable functions,
  plus the Config documentation/consumption contract.

Plus the re-homed artifact lints (``CARRY001``, ``TEL001``) and the
suppression engine (``# lint: disable=RULE(reason)``, stale
suppressions flagged as ``SUP001``).

CLI: ``python -m lightgbm_tpu.analysis [--json] [--rules ID,ID]``
(exit 0 = clean; docs/STATIC_ANALYSIS.md is the rule glossary).
"""
from .core import (Context, Finding, Rule, RULES, render_json,
                   render_text, run_rules, unsuppressed)
from . import walker

__all__ = ["Context", "Finding", "Rule", "RULES", "render_json",
           "render_text", "run_rules", "unsuppressed", "walker"]
