"""CLI for the compiled-program lint framework.

Usage::

    python -m lightgbm_tpu.analysis                # text report
    python -m lightgbm_tpu.analysis --json         # machine output
    python -m lightgbm_tpu.analysis --rules HLO003,HLO004
    python -m lightgbm_tpu.analysis --list         # rule glossary

Exit status: 0 clean, 1 unsuppressed finding(s), 2 usage error.
``scripts/bench_smoke.sh`` runs the ``--json`` form and fails CI on
any unsuppressed finding.
"""
from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    # program rules lower on the CPU seam; never touch a TPU tunnel.
    # The parent package may have imported jax already (python -m
    # imports it first), so pin the live config too, not just the env.
    if not os.environ.get("JAX_PLATFORMS"):
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # pragma: no cover - jax-less source checks
            pass
    ap = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="static analysis over the lowered hot programs "
                    "and the package source (docs/STATIC_ANALYSIS.md)")
    ap.add_argument("--json", action="store_true",
                    help="one JSON document instead of the text report")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule IDs (default: all)")
    ap.add_argument("--list", action="store_true",
                    help="print the rule glossary and exit")
    args = ap.parse_args(argv)

    from .core import RULES, render_json, render_text, run_rules, \
        unsuppressed
    from . import ast_rules, hlo_rules, layout_rule, teldoc_rule  # noqa: F401

    if args.list:
        for rid in sorted(RULES):
            r = RULES[rid]
            inc = f"  [{r.incident}]" if r.incident else ""
            print(f"{rid}  {r.title}{inc}")
        return 0

    rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()] \
        or None
    try:
        findings = run_rules(rule_ids)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    ids = rule_ids or sorted(RULES)
    if args.json:
        print(render_json(findings, ids))
    else:
        sys.stdout.write(render_text(findings, ids))
    return 1 if unsuppressed(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
