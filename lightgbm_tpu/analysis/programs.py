"""Registered hot entry points, lowered at small probe shapes.

The invariant checker does not scan arbitrary code — it lowers the
exact programs whose compiled form carries the repo's perf/correctness
guarantees, at the same probe shapes the HLO regression tests always
used:

- the fused train chunk (``GBDT._build_fused_chunk``) at chunk 4 and
  16 — the dispatch-auto probe sizes (r6/r7 carry + donation story),
- the per-iteration fused step (the other r7 donation-crash program),
- ``predict_level_ensemble`` at two tree counts (the r8 gather
  T-invariance claim) plus its serving-bucket shape,
- ``predict_level_ensemble_pallas`` (interpret seam) and the legacy
  ``predict_raw_ensemble`` scan kept for A/B,
- ``unpack_tree_records_device`` (the packed-carry consumer).

Building a :class:`ProgramSet` trains two tiny probe models on the CPU
seam (512x6 and 220x9 — the shapes ``tests/test_carry_hlo.py`` and
``tests/test_predict_cache.py`` pin), so one build serves every rule
and both test files.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

# distinct-traced-signature budget per telemetry entry point for ONE
# full probe build (HLO008).  The counts are small and exact on a fresh
# process: a builder that starts retracing per call (unhashable static
# arg, shape-dependent closure) blows straight through them.
RETRACE_BOUNDS: Dict[str, int] = {
    # 2 carry probes (chunk 4, 16) + the predict-probe training run's
    # dispatch-auto ladder (probe chunks 4/16 at its own score shape,
    # the fitted chunk, and one odd-length tail chunk)
    "gbdt.fused_chunk": 6,
    # engine may fall back to per-iteration steps around chunk edges
    "gbdt.fused_step": 4,
    # T=4 / T=12 gather probes + the serving bucket (shape-shared with
    # the T=12 probe) + one slack
    "predict.level_ensemble": 4,
    "predict.level_ensemble_pallas": 2,
    "predict.binned_scan": 4,
}


class Program:
    """One lowered entry point: jaxpr + (lazy) StableHLO + (lazy)
    compiled-module text + donation flags + rule metadata."""

    def __init__(self, name: str, source: str,
                 jaxpr=None, lowered=None, stablehlo_text: str = None,
                 compiled_text: str = None,
                 meta: Optional[Dict] = None):
        self.name = name
        self.source = source            # repo-relative defining file
        self.jaxpr = jaxpr              # jax.core.Jaxpr (unclosed)
        self._lowered = lowered
        self._stablehlo = stablehlo_text
        self._compiled = compiled_text
        self.meta = dict(meta or {})

    @property
    def stablehlo(self) -> Optional[str]:
        if self._stablehlo is None and self._lowered is not None:
            self._stablehlo = self._lowered.as_text()
        return self._stablehlo

    @property
    def compiled_text(self) -> Optional[str]:
        if self._compiled is None and self._lowered is not None:
            self._compiled = self._lowered.compile().as_text()
        return self._compiled

    @property
    def donated_args(self) -> List[bool]:
        if self._lowered is None:
            return []
        import jax
        return [bool(getattr(a, "donated", False))
                for a in jax.tree_util.tree_leaves(self._lowered.args_info)]

    def __repr__(self):
        return f"<Program {self.name} ({self.source})>"


# -- probe model builders (shared with the HLO regression tests) ------------

def build_probe_gbdt(**params):
    """The carry-probe GBDT: 512x6 binary, 7 leaves — the shape
    tests/test_carry_hlo.py has pinned since round 7."""
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.boosting.gbdt import GBDT
    from lightgbm_tpu.config import Config

    rng = np.random.RandomState(7)
    X = rng.randn(512, 6)
    y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(float)
    cfg = Config.from_params({"objective": "binary", "num_leaves": 7,
                              "verbose": -1, "min_data_in_leaf": 5,
                              **params})
    core = lgb.Dataset(X, label=y).construct(cfg)
    return GBDT(cfg, core)


def chunk_args(g, chunk: int):
    """Probe arguments for the fused chunk at a given chunk length."""
    import jax.numpy as jnp
    keys = jnp.zeros((chunk, 2), jnp.uint32)
    fmasks = jnp.ones((chunk, g.num_class, g.grower.num_features), bool)
    fresh = jnp.zeros(chunk, bool)
    return (g.scores, tuple(), g._full_counts > 0, keys, fmasks, fresh)


def step_args(g):
    """Probe arguments for the per-iteration fused step."""
    import jax.numpy as jnp
    key = jnp.zeros((2,), jnp.uint32)
    fmask = jnp.ones((g.num_class, g.grower.num_features), bool)
    shrink = jnp.asarray(g.shrinkage_rate, jnp.float32)
    return (g.scores, tuple(), g._full_counts > 0, key, fmask, shrink)


def train_probe_booster(f: int = 9, leaves: int = 13, iters: int = 12,
                        n: int = 220, seed: int = 0, **params):
    """The predict-probe booster: 220x9 regression, 13 leaves — the
    shape tests/test_predict_cache.py has pinned since round 8 (unique
    on purpose, so another test's jit cache entries can't mask a
    retrace count)."""
    import numpy as np

    import lightgbm_tpu as lgb

    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = X[:, 0] - 0.4 * X[:, 1]
    p = {"objective": "regression", "verbose": -1,
         "num_leaves": leaves, "min_data_in_leaf": 5, **params}
    bst = lgb.train(p, lgb.Dataset(X, label=y), iters,
                    verbose_eval=False)
    return bst, X


def level_stack(bst, t_count: int):
    """(LevelEnsemble, depth) over the first ``t_count`` trees."""
    import jax.numpy as jnp

    from lightgbm_tpu.ops.predict import LevelEnsemble
    from lightgbm_tpu.tree import flatten_ensemble

    bst._sync_models()
    flat = flatten_ensemble(bst.models[:t_count], 1)
    depth = int(flat.pop("depth"))
    return LevelEnsemble(**{k: jnp.asarray(v)
                            for k, v in flat.items()}), depth


class ProgramSet:
    """Lazy registry of the hot entry-point programs.  One instance
    builds each program (and each probe model) at most once; the
    retrace delta across all builds feeds HLO008."""

    GBDT_SRC = "lightgbm_tpu/boosting/gbdt.py"
    PREDICT_SRC = "lightgbm_tpu/ops/predict.py"

    def __init__(self):
        from lightgbm_tpu.telemetry import TELEMETRY
        self._telemetry = TELEMETRY
        self._baseline = dict(TELEMETRY.retraces())
        self._cache: Dict[str, Program] = {}
        self._gbdt = None
        self._booster = None

    # -- shared probe models ------------------------------------------
    @property
    def gbdt(self):
        if self._gbdt is None:
            self._gbdt = build_probe_gbdt()
        return self._gbdt

    @property
    def booster(self):
        if self._booster is None:
            self._booster = train_probe_booster()
        return self._booster

    # -- programs -----------------------------------------------------
    def _memo(self, name: str, build: Callable[[], Program]) -> Program:
        if name not in self._cache:
            self._cache[name] = build()
        return self._cache[name]

    def fused_chunk(self, chunk: int) -> Program:
        def build():
            import jax
            g = self.gbdt
            fn = g._build_fused_chunk(chunk)
            args = chunk_args(g, chunk)
            jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
            lowered = fn.lower(*args)
            from lightgbm_tpu.tree import TREE_RECORD_SPEC
            return Program(
                f"fused_chunk@{chunk}", self.GBDT_SRC,
                jaxpr=jaxpr, lowered=lowered,
                meta={"boost_chunk_len": chunk,
                      "multi_shape": True,
                      "record_spec_len": len(TREE_RECORD_SPEC),
                      "record_size":
                          g.grower.record_layout.record_size,
                      "packed_carry": g._packed_carry})
        return self._memo(f"fused_chunk@{chunk}", build)

    def fused_step(self) -> Program:
        def build():
            import jax
            g = self.gbdt
            if g._fused_step is None:
                g._build_fused()
            args = step_args(g)
            jaxpr = jax.make_jaxpr(
                lambda *a: g._fused_step(*a))(*args).jaxpr
            lowered = g._fused_step.lower(*args)
            return Program("fused_step", self.GBDT_SRC,
                           jaxpr=jaxpr, lowered=lowered,
                           meta={"multi_shape": True})
        return self._memo("fused_step", build)

    def predict_level(self, t_count: int) -> Program:
        def build():
            import jax
            import jax.numpy as jnp

            from lightgbm_tpu.ops.predict import predict_level_ensemble
            bst, X = self.booster
            stack, depth = level_stack(bst, t_count)
            x2 = jnp.zeros((16, 2 * X.shape[1]), jnp.float32)
            jaxpr = jax.make_jaxpr(
                lambda s, x: predict_level_ensemble(s, x, depth=depth)
            )(stack, x2).jaxpr
            lowered = predict_level_ensemble.lower(stack, x2,
                                                   depth=depth)
            return Program(
                f"predict_level@T{t_count}", self.PREDICT_SRC,
                jaxpr=jaxpr, lowered=lowered,
                meta={"gather_probe_t": t_count, "depth": depth,
                      "multi_shape": True})
        return self._memo(f"predict_level@T{t_count}", build)

    def serving_bucket(self, bucket: int = 16) -> Program:
        """The serving predictor's compiled unit: the level program at
        one power-of-two row bucket over the full probe ensemble —
        what `booster._ServingPredictor` dispatches per request."""
        def build():
            import jax
            import jax.numpy as jnp

            from lightgbm_tpu.ops.predict import predict_level_ensemble
            bst, X = self.booster
            stack, depth = level_stack(bst, 12)
            x2 = jnp.zeros((bucket, 2 * X.shape[1]), jnp.float32)
            jaxpr = jax.make_jaxpr(
                lambda s, x: predict_level_ensemble(s, x, depth=depth)
            )(stack, x2).jaxpr
            lowered = predict_level_ensemble.lower(stack, x2,
                                                   depth=depth)
            return Program(
                f"serving_bucket@{bucket}", self.PREDICT_SRC,
                jaxpr=jaxpr, lowered=lowered,
                meta={"bucket": bucket, "multi_shape": True})
        return self._memo(f"serving_bucket@{bucket}", build)

    def predict_pallas(self) -> Program:
        def build():
            import jax
            import jax.numpy as jnp

            from lightgbm_tpu.ops.predict import (
                predict_level_ensemble_pallas)
            bst, X = self.booster
            stack, depth = level_stack(bst, 12)
            x2 = jnp.zeros((16, 2 * X.shape[1]), jnp.float32)

            def fn(s, x):
                return predict_level_ensemble_pallas(
                    s, x, depth=depth, tile=16, interpret=True)
            jaxpr = jax.make_jaxpr(fn)(stack, x2).jaxpr
            lowered = predict_level_ensemble_pallas.lower(
                stack, x2, depth=depth, tile=16, interpret=True)
            return Program("predict_pallas", self.PREDICT_SRC,
                           jaxpr=jaxpr, lowered=lowered,
                           meta={"multi_shape": True})
        return self._memo("predict_pallas", build)

    def predict_scan(self) -> Program:
        def build():
            import jax
            import jax.numpy as jnp
            import numpy as np

            from lightgbm_tpu.ops.predict import (predict_raw_ensemble,
                                                  split_hi_lo,
                                                  stack_host_trees)
            bst, X = self.booster
            bst._sync_models()
            stack = stack_host_trees(bst.models)
            hi, lo = split_hi_lo(np.asarray(X[:16], np.float64))
            cls = jnp.zeros((len(bst.models),), jnp.int32)
            k_total = jnp.zeros((1, 16), jnp.float32)
            args = (stack, jnp.asarray(hi), jnp.asarray(lo), cls,
                    k_total)
            jaxpr = jax.make_jaxpr(
                lambda *a: predict_raw_ensemble(*a))(*args).jaxpr
            lowered = predict_raw_ensemble.lower(*args)
            return Program("predict_scan", self.PREDICT_SRC,
                           jaxpr=jaxpr, lowered=lowered,
                           meta={"multi_shape": True})
        return self._memo("predict_scan", build)

    def hist_tiered(self) -> Program:
        """The precision-tiered histogram tree step (round 21): a
        probe grower planned with ``hist_precision=tiered`` — the
        int32 quantized-weight accumulation plus its f32 fix-up.
        HLO009's no-f64 / no-callback surface; NOT in
        ``all_programs`` so the HLO003-008 scope is unchanged."""
        def build():
            import jax
            import numpy as np

            g = build_probe_gbdt(hist_precision="tiered",
                                 hist_kernel="pallas",
                                 force_pallas_interpret=True,
                                 max_bin=15).grower
            assert g.use_quant, (
                "tiered probe did not plan onto the quantized "
                "kernels — HLO009 would be checking the wrong program")
            zeros = np.zeros(g.n_padded, np.float32)
            fmask = np.ones(g.num_features, bool)
            args = (zeros, zeros, zeros, fmask, g.ohb, g.bins,
                    g.binsT, g._row_valid)
            jaxpr = jax.make_jaxpr(g._train_tree_impl)(*args).jaxpr
            lowered = jax.jit(g._train_tree_impl).lower(*args)
            return Program("hist_tiered_step",
                           "lightgbm_tpu/learner/grower.py",
                           jaxpr=jaxpr, lowered=lowered,
                           meta={"multi_shape": False})
        return self._memo("hist_tiered_step", build)

    def hist_exchange(self, mode: str = "q16") -> Program:
        """The compressed histogram exchange codec (round 21) lowered
        under a shard_map mesh — delta coding, pmax'd scale payload,
        narrow-int psum, cumsum reconstruction.  HLO009 asserts the
        codec stays device-resident (no host callback) and f32-clean;
        NOT in ``all_programs`` (same scoping as hist_tiered)."""
        def build():
            import functools

            import jax
            import jax.numpy as jnp
            import numpy as np
            from jax.sharding import Mesh
            from jax.sharding import PartitionSpec as P

            from lightgbm_tpu.learner.grower import _get_shard_map
            from lightgbm_tpu.parallel.collectives import \
                exchange_histograms

            devs = jax.devices()
            world = 2 if len(devs) >= 2 else 1
            mesh = Mesh(np.array(devs[:world]), ("data",))

            @functools.partial(_get_shard_map(), mesh=mesh,
                               in_specs=(P(),), out_specs=P())
            def fn(h):
                return exchange_histograms(h, "data", mode=mode,
                                           world=world)

            h = jnp.zeros((6, 4, 16, 3), jnp.float32)
            jaxpr = jax.make_jaxpr(fn)(h).jaxpr
            lowered = jax.jit(fn).lower(h)
            return Program(f"hist_exchange@{mode}",
                           "lightgbm_tpu/parallel/collectives.py",
                           jaxpr=jaxpr, lowered=lowered,
                           meta={"multi_shape": False,
                                 "world": world})
        return self._memo(f"hist_exchange@{mode}", build)

    def unpack_records(self) -> Program:
        def build():
            import jax
            import jax.numpy as jnp

            from lightgbm_tpu.ops.predict import (
                unpack_tree_records_device)
            g = self.gbdt
            layout = g.grower.record_layout

            def fn(rec):
                return unpack_tree_records_device(
                    rec, layout.num_leaves, layout.max_feature_bin)
            rec = jnp.zeros((4, 1, layout.record_size), jnp.uint8)
            jaxpr = jax.make_jaxpr(fn)(rec).jaxpr
            lowered = jax.jit(fn).lower(rec)
            return Program("unpack_records", self.PREDICT_SRC,
                           jaxpr=jaxpr, lowered=lowered,
                           meta={"multi_shape": False})
        return self._memo("unpack_records", build)

    # -- iteration ----------------------------------------------------
    def all_programs(self) -> List[Program]:
        return [
            self.fused_chunk(4),
            self.fused_chunk(16),
            self.fused_step(),
            self.predict_level(4),
            self.predict_level(12),
            self.serving_bucket(16),
            self.predict_pallas(),
            self.predict_scan(),
            self.unpack_records(),
        ]

    def retrace_delta(self) -> Dict[str, int]:
        """Distinct traced signatures ADDED per telemetry entry point
        since this ProgramSet was created (HLO008's measurement)."""
        now = self._telemetry.retraces()
        return {fn: n - self._baseline.get(fn, 0)
                for fn, n in now.items()
                if n - self._baseline.get(fn, 0) > 0}
