"""Program-invariant rules HLO001-HLO009.

Each rule encodes one hard-won compiled-program guarantee as a check
over the registered entry points' lowered artifacts (see
``programs.py``).  The per-program check functions are module-level so
``tests/test_analysis.py`` can aim them at seeded fixture programs;
the registered rule just fans a check across ``ctx.programs``.

Incident index (docs/STATIC_ANALYSIS.md carries the full glossary):

- r6: per-field loop-carried output stacks made per-tree cost grow
  with chunk length (HLO003), and scattered record writes were the
  degenerate lowering the fix had to avoid (HLO004).
- r7: buffer donation on multi-shape jitted programs corrupted the
  native heap (HLO006).
- r8: the level descent's gather count must stay T-independent or
  serving regresses to the per-tree walk (HLO005); the serving bucket
  ladder bounds the retrace surface (HLO008).
- standing TPU discipline: f32 accumulation everywhere (HLO001), no
  host round-trips inside hot programs (HLO002), fully static shapes
  (HLO007).
- r21: the histogram compression programs (precision-tiered
  accumulation, compressed histogram exchange) re-assert both
  standing disciplines at their own probe surfaces (HLO009).
"""
from __future__ import annotations

from typing import Dict, List

from . import walker
from .core import Finding, rule

MAX_CARRY_OUTPUT_BUFFERS = 4


# -- per-program checks (fixture-testable) ----------------------------------

def check_no_f64(program) -> List[Finding]:
    """HLO001: no float64 anywhere in the program."""
    out: List[Finding] = []
    if program.jaxpr is not None:
        bad = sorted(d for d in walker.jaxpr_dtypes(program.jaxpr)
                     if d in ("float64", "complex128"))
        for d in bad:
            out.append(Finding(
                rule="HLO001", file=program.source,
                message=f"program {program.name}: {d} value in the "
                        "jaxpr — a silent f64 promotion doubles HBM "
                        "traffic and falls off the MXU fast path"))
    text = program.stablehlo
    if text and not out and "f64" in text:
        out.append(Finding(
            rule="HLO001", file=program.source,
            message=f"program {program.name}: f64 type in lowered "
                    "StableHLO"))
    return out


def check_no_host_callback(program) -> List[Finding]:
    """HLO002: no host callback / infeed / outfeed in a hot program."""
    out: List[Finding] = []
    if program.jaxpr is not None:
        prims = walker.primitive_names(program.jaxpr) \
            & walker.HOST_CALLBACK_PRIMITIVES
        for p in sorted(prims):
            out.append(Finding(
                rule="HLO002", file=program.source,
                message=f"program {program.name}: host-callback "
                        f"primitive `{p}` — every dispatch would "
                        "round-trip through Python"))
    text = program.stablehlo
    if text and not out:
        for marker in walker.HOST_CALLBACK_MARKERS:
            if marker in text:
                out.append(Finding(
                    rule="HLO002", file=program.source,
                    message=f"program {program.name}: `{marker}` in "
                            "lowered StableHLO"))
    return out


def check_carry_bound(program,
                      bound: int = MAX_CARRY_OUTPUT_BUFFERS
                      ) -> List[Finding]:
    """HLO003: the boosting scan stacks at most ``bound`` O(chunk)
    output buffers (packed carry: records + num_leaves = 2)."""
    chunk = program.meta.get("boost_chunk_len")
    if not chunk or program.jaxpr is None:
        return []
    scans = walker.find_scans(program.jaxpr)
    if not scans:
        return [Finding(
            rule="HLO003", file=program.source,
            message=f"program {program.name}: no lax.scan left in the "
                    "fused chunk — the dispatch loop was unrolled or "
                    "restructured; the carry bound cannot be checked")]
    boost = walker.find_scans(program.jaxpr, length=chunk)
    if not boost:
        return [Finding(
            rule="HLO003", file=program.source,
            message=f"program {program.name}: no scan of length "
                    f"{chunk} (the boosting scan) in the fused chunk")]
    ys = walker.scan_output_stacks(boost[0])
    if ys > bound:
        return [Finding(
            rule="HLO003", file=program.source,
            message=f"program {program.name}: boosting scan stacks "
                    f"{ys} loop-carried output buffers (bound "
                    f"{bound}) — the r6 diagnosis: per-field stacks "
                    "are what made per-tree cost grow with chunk "
                    "length")]
    return []


def check_dus_not_scatter(program) -> List[Finding]:
    """HLO004: tree-record writes lower to static-offset
    dynamic-update-slice, never to a uint8 scatter, and the compiled
    module keeps DUS instructions attributed to tree.py."""
    spec_len = program.meta.get("record_spec_len")
    if not spec_len:
        return []
    out: List[Finding] = []
    if program.jaxpr is not None:
        for eqn in walker.scatter_eqns_with_dtype(program.jaxpr,
                                                  "uint8"):
            out.append(Finding(
                rule="HLO004", file=program.source,
                message=f"program {program.name}: a tree-record write "
                        f"lowered to `{eqn.primitive.name}` on a uint8 "
                        "operand — record emission regressed from "
                        "static-offset dynamic-update-slice to "
                        "scatter"))
    text = program.stablehlo
    if text is not None:
        n_dus = walker.count_op(text, "stablehlo.dynamic_update_slice")
        if n_dus < spec_len:
            out.append(Finding(
                rule="HLO004", file=program.source,
                message=f"program {program.name}: only {n_dus} "
                        "dynamic_update_slice ops in the lowered "
                        f"chunk — expected one per record field "
                        f"({spec_len}); record emission regressed"))
    hlo = program.compiled_text
    if hlo is not None and not out:
        dus_tree = [ln for ln in hlo.splitlines()
                    if "dynamic-update-slice" in ln and "tree.py" in ln]
        if not dus_tree:
            out.append(Finding(
                rule="HLO004", file=program.source,
                message=f"program {program.name}: compiled HLO carries "
                        "no dynamic-update-slice attributed to tree.py "
                        "— XLA rewrote the record writes out of "
                        "in-place form"))
    return out


def check_gather_t_invariance(small, large) -> List[Finding]:
    """HLO005: the level descent's gather count is independent of the
    tree count, and within the per-level budget (8/level + leaf
    fetch)."""
    out: List[Finding] = []
    counts = {p.meta["gather_probe_t"]:
              walker.count_primitive(p.jaxpr, "gather")
              for p in (small, large)}
    ts = sorted(counts)
    if counts[ts[0]] != counts[ts[1]]:
        out.append(Finding(
            rule="HLO005", file=large.source,
            message=f"level-descent gather count grew with tree count "
                    f"({{T={ts[0]}: {counts[ts[0]]}, T={ts[1]}: "
                    f"{counts[ts[1]]}}}) — the descent regressed to "
                    "per-tree gathers"))
    depth = large.meta.get("depth", 6)
    budget = depth * 8 + 2
    if counts[ts[1]] > budget:
        out.append(Finding(
            rule="HLO005", file=large.source,
            message=f"{counts[ts[1]]} gathers for depth {depth} — "
                    f"over the level-synchronous budget ({budget}: "
                    "8/level + leaf fetch)"))
    return out


def check_no_donation(program) -> List[Finding]:
    """HLO006: no donated input buffers on a multi-shape jitted
    program (the r7 native-heap-corruption root cause)."""
    if not program.meta.get("multi_shape"):
        return []
    donated = program.donated_args
    n = sum(donated)
    if n:
        return [Finding(
            rule="HLO006", file=program.source,
            message=f"program {program.name}: {n} donated input "
                    "buffer(s) — donation on a multi-shape jitted "
                    "program is the bisected r7 heap-corruption root "
                    "cause (glibc corrupted double-linked list); keep "
                    "donate_argnums off these programs")]
    return []


def check_static_shapes(program) -> List[Finding]:
    """HLO007: no dynamic-shape ops in the lowered module."""
    text = program.stablehlo
    if text is None:
        return []
    return [Finding(
        rule="HLO007", file=program.source,
        message=f"program {program.name}: dynamic-shape lowering "
                f"`{m}` — hot programs must be fully static so one "
                "compilation serves the bucket/chunk ladder")
        for m in walker.dynamic_shape_markers(text)]


def check_retrace_surface(delta: Dict[str, int],
                          bounds: Dict[str, int]) -> List[Finding]:
    """HLO008: distinct traced signatures per entry point stay within
    the declared probe budget."""
    out: List[Finding] = []
    for fn, n in sorted(delta.items()):
        bound = bounds.get(fn)
        if bound is None:
            continue
        if n > bound:
            out.append(Finding(
                rule="HLO008", file="lightgbm_tpu/telemetry.py",
                message=f"entry point `{fn}` traced {n} distinct "
                        f"signatures during the probe build (budget "
                        f"{bound}) — each is an XLA compilation; the "
                        "retrace surface regressed past the declared "
                        "shape ladder"))
    return out


# -- registered rules -------------------------------------------------------

@rule("HLO001", "no float64 anywhere in hot programs",
      incident="standing f32-accumulation discipline",
      needs_programs=True)
def _hlo001(ctx) -> List[Finding]:
    out: List[Finding] = []
    for p in ctx.programs.all_programs():
        out.extend(check_no_f64(p))
    return out


@rule("HLO002", "no host callback / infeed in hot programs",
      incident="standing no-host-round-trip discipline",
      needs_programs=True)
def _hlo002(ctx) -> List[Finding]:
    out: List[Finding] = []
    for p in ctx.programs.all_programs():
        out.extend(check_no_host_callback(p))
    return out


@rule("HLO003", "fused-chunk carried-output-stack bound (packed carry)",
      incident="r6 chunk-slope diagnosis / r7 packed carry",
      needs_programs=True)
def _hlo003(ctx) -> List[Finding]:
    out: List[Finding] = []
    for chunk in (4, 16):
        out.extend(check_carry_bound(ctx.programs.fused_chunk(chunk)))
    return out


@rule("HLO004", "tree-record writes are DUS, not scatter",
      incident="r7 packed-record emission",
      needs_programs=True)
def _hlo004(ctx) -> List[Finding]:
    return check_dus_not_scatter(ctx.programs.fused_chunk(4))


@rule("HLO005", "level-descent gather count is tree-count-invariant",
      incident="r8 ensemble-vectorized predict",
      needs_programs=True)
def _hlo005(ctx) -> List[Finding]:
    return check_gather_t_invariance(ctx.programs.predict_level(4),
                                     ctx.programs.predict_level(12))


@rule("HLO006", "donation banned on multi-shape fused programs",
      incident="r7 native-heap-corruption bisect",
      needs_programs=True)
def _hlo006(ctx) -> List[Finding]:
    out: List[Finding] = []
    for p in ctx.programs.all_programs():
        out.extend(check_no_donation(p))
    return out


@rule("HLO007", "no dynamic-shape ops in hot programs",
      incident="standing static-shape discipline",
      needs_programs=True)
def _hlo007(ctx) -> List[Finding]:
    out: List[Finding] = []
    for p in ctx.programs.all_programs():
        out.extend(check_static_shapes(p))
    return out


@rule("HLO008", "retrace surface bounded per entry point",
      incident="r8 serving bucket ladder / r9 retrace sentinel",
      needs_programs=True)
def _hlo008(ctx) -> List[Finding]:
    from .programs import RETRACE_BOUNDS
    ctx.programs.all_programs()      # force every probe build first
    return check_retrace_surface(ctx.programs.retrace_delta(),
                                 RETRACE_BOUNDS)


@rule("HLO009", "tiered accumulation f32-clean; exchange codec "
                "device-resident",
      incident="r21 histogram compression arc",
      needs_programs=True)
def _hlo009(ctx) -> List[Finding]:
    """The round-21 compression programs uphold the standing
    disciplines at their own probe surfaces: the precision-tiered
    tree step (int32 accumulation + f32 fix-up) must introduce no
    f64 promotion, and the ``hist_exchange`` codec's quantize /
    pmax-scale / psum / reconstruct chain must lower with no host
    callback — a callback inside the exchange would serialize every
    per-pass histogram sum on the host."""
    probes = [ctx.programs.hist_tiered(),
              ctx.programs.hist_exchange("q16"),
              ctx.programs.hist_exchange("q8")]
    out: List[Finding] = []
    for p in probes:
        for f in check_no_f64(p) + check_no_host_callback(p):
            out.append(Finding(rule="HLO009", file=f.file,
                               line=f.line, message=f.message))
    return out
