"""CARRY001 — packed tree-record layout consistency (re-homed from
``scripts/check_carry_layout.py``, which is now a thin wrapper over
this rule).

The packed single-buffer tree carry (round 7) serializes a grown
TreeArrays into one uint8 record at FIXED offsets
(tree.TreeRecordLayout).  Three places must agree on that layout — the
spec (tree.TREE_RECORD_SPEC), the dtypes the grower materializes in
``_init_state`` (parsed from SOURCE, so a dtype edit trips the rule
even if nothing imports), and the host/device unpack sites — and a
field added to TreeArrays without a matching spec row would silently
drop or corrupt tree state only on the packed path.
"""
from __future__ import annotations

import os
import re
from typing import List

from .core import Finding, rule

SRC = "lightgbm_tpu/tree.py"

# dtype token the grower writes at the emit site -> spec dtype string
GROWER_DTYPE_TO_SPEC = {
    "jnp.int32": "<i4",
    "jnp.float32": "<f4",
    "bool": "|u1",
}


def _f(msg: str) -> Finding:
    return Finding(rule="CARRY001", file=SRC, message=msg)


def check_field_order(spec, tree_arrays_cls) -> List[Finding]:
    spec_names = [name for name, _, _ in spec]
    fields = list(tree_arrays_cls._fields)
    if spec_names != fields:
        return [_f(f"TREE_RECORD_SPEC field order {spec_names} != "
                   f"TreeArrays._fields {fields}")]
    return []


def check_grower_emit_dtypes(spec, grower_src: str) -> List[Finding]:
    """Parse ``_init_state``'s TreeArrays(...) literal for each field's
    dtype token and compare against the spec."""
    out: List[Finding] = []
    m = re.search(r"tree = TreeArrays\((.*?)\n\s*\)", grower_src, re.S)
    if not m:
        return [_f("could not find the `tree = TreeArrays(...)` emit "
                   "site in learner/grower.py _init_state")]
    body = m.group(1)
    # split the literal's kwargs on top-level commas (nested parens in
    # shape tuples rule out a flat regex)
    parts, depth, cur = [], 0, []
    for ch in body:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur))
    emitted = {}
    for part in parts:
        if "=" not in part:
            continue
        name, expr = part.split("=", 1)
        name, expr = name.strip(), expr.strip()
        if not re.fullmatch(r"\w+", name):
            continue
        if name == "num_leaves":
            # scalar: jnp.int32(1)
            emitted[name] = "<i4" if "jnp.int32" in expr else "?"
            continue
        toks = [t for t in GROWER_DTYPE_TO_SPEC
                if re.search(rf"[,(]\s*{re.escape(t)}\s*[,)]", expr)]
        emitted[name] = GROWER_DTYPE_TO_SPEC[toks[0]] if len(toks) == 1 \
            else "?"
    for name, dt, _ in spec:
        if name not in emitted:
            out.append(_f(f"spec field {name!r} has no emit site in "
                          "grower._init_state"))
        elif emitted[name] == "?":
            out.append(_f("could not determine the dtype "
                          "grower._init_state materializes for "
                          f"{name!r}"))
        elif emitted[name] != dt:
            out.append(_f(f"{name!r}: grower emits {emitted[name]}, "
                          f"spec says {dt}"))
    for name in emitted:
        if name not in {n for n, _, _ in spec}:
            out.append(_f(f"grower emits field {name!r} with no spec "
                          "row — it would be DROPPED by the packed "
                          "carry"))
    return out


def check_offsets(layout) -> List[Finding]:
    out: List[Finding] = []
    prev_end = 0
    for name, (off, nbytes, dt, shape) in layout.fields.items():
        if off % 4:
            out.append(_f(f"{name!r}: offset {off} not word-aligned"))
        if off < prev_end:
            out.append(_f(f"{name!r}: offset {off} overlaps previous "
                          f"field (ends at {prev_end})"))
        prev_end = off + nbytes
    if layout.record_size % 64:
        out.append(_f(f"record_size {layout.record_size} not 64-byte "
                      "padded"))
    if prev_end > layout.record_size:
        out.append(_f(f"fields end at {prev_end} past record_size "
                      f"{layout.record_size}"))
    return out


def check_roundtrip(layout, tree_arrays_cls, spec) -> List[Finding]:
    """Functional round-trip: pack a randomized TreeArrays on the CPU
    backend, unpack host-side AND device-side, require exact equality
    field by field."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from lightgbm_tpu.ops.predict import unpack_tree_records_device

    out: List[Finding] = []
    rng = np.random.RandomState(7)
    vals = {}
    for name, (off, nbytes, dt, shape) in layout.fields.items():
        kind = np.dtype(dt).kind
        if name == "num_leaves":
            vals[name] = jnp.int32(5)
        elif kind == "u":
            vals[name] = jnp.asarray(rng.rand(*shape) > 0.5)
        elif kind == "i":
            vals[name] = jnp.asarray(
                rng.randint(-100, 100, size=shape), jnp.int32)
        else:
            vals[name] = jnp.asarray(
                rng.randn(*shape).astype(np.float32))
    tree = tree_arrays_cls(**vals)
    rec = np.asarray(jax.jit(layout.pack_tree_record)(tree))

    host = layout.unpack_tree_record(rec)
    for name, _, _ in spec:
        want = np.asarray(vals[name])
        got = np.asarray(host[name])
        if got.shape != want.shape or not np.array_equal(got, want):
            out.append(_f(f"host round-trip mismatch on {name!r}"))

    dev = unpack_tree_records_device(
        jnp.asarray(rec), layout.num_leaves, layout.max_feature_bin)
    for name, _, _ in spec:
        got = np.asarray(getattr(dev, name))
        want = np.asarray(vals[name])
        if got.shape != want.shape or not np.array_equal(got, want):
            out.append(_f(f"device round-trip mismatch on {name!r}"))
    return out


@rule("CARRY001", "packed tree-record spec, grower emit sites and "
                  "pack/unpack round-trip agree",
      incident="r7 packed single-buffer tree carry")
def _carry001(ctx) -> List[Finding]:
    from lightgbm_tpu.learner.grower import TreeArrays
    from lightgbm_tpu.tree import TREE_RECORD_SPEC, TreeRecordLayout

    grower_src = ctx.sources.get(
        "lightgbm_tpu/learner/grower.py")
    if grower_src is None:
        with open(os.path.join(ctx.repo, "lightgbm_tpu", "learner",
                               "grower.py")) as fh:
            grower_src = fh.read()

    out: List[Finding] = []
    out.extend(check_field_order(TREE_RECORD_SPEC, TreeArrays))
    out.extend(check_grower_emit_dtypes(TREE_RECORD_SPEC, grower_src))
    for L, B in ((31, 64), (8, 16)):
        out.extend(check_offsets(TreeRecordLayout(L, B)))
    out.extend(check_roundtrip(TreeRecordLayout(8, 16), TreeArrays,
                               TREE_RECORD_SPEC))
    return out
