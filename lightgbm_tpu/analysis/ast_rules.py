"""Trace-safety AST pass + config-consistency rules.

TRC001/TRC002 walk the package source (never the lowered programs):
a ``np.*`` / ``math.*`` / ``time.*`` / Python-RNG call inside a
jit-reachable function executes at TRACE time — it silently bakes a
constant into the compiled program (the value the host happened to
produce at trace time), or worse, forces a host sync.  Data-dependent
Python branching on a ``jnp`` expression is the classic
ConcretizationTypeError-or-silent-specialization hazard.  The
call graph is seeded from the registered hot entry points
(``programs.py``) and expanded conservatively: over-approximating
reachability is safe (a spurious finding gets a reviewed suppression),
under-approximating is not.

CFG001/CFG002 pin the Config contract: every knob documented in
``docs/Parameters.md`` (CFG001) and actually read somewhere in the
package (CFG002) — an accepted-but-never-read knob is a user-facing
lie (the r-series reviews found four).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from .core import Finding, rule

# host modules whose calls are trace-time hazards inside jit-reachable
# code (numpy collapses traced arrays to constants; time/random make
# the trace nondeterministic)
HOST_MODULES = {"numpy", "math", "time", "random"}

# host-module calls that are legitimate at trace time: dtype/metadata
# constructors and scalar casts of static Python values.  Everything
# else needs a fix or a reviewed `# lint: disable=TRC001(...)`.
SAFE_HOST_CALLS = {
    "numpy.dtype", "numpy.iinfo", "numpy.finfo",
    "numpy.float32", "numpy.float64", "numpy.int32", "numpy.int64",
    "numpy.uint8", "numpy.uint32", "numpy.int8", "numpy.bool_",
    "math.ceil", "math.floor", "math.log2", "math.sqrt", "math.inf",
    "math.isinf", "math.isnan", "math.prod",
}

# jit-reachable roots: the functions the registered entry points trace
# into.  (file suffix, function name) — names resolve against the AST
# index, so a rename here fails loudly in tests.
JIT_SEEDS: List[Tuple[str, str]] = [
    ("boosting/gbdt.py", "_boost_one"),
    ("learner/grower.py", "_train_tree_impl"),
    ("learner/grower.py", "emit_tree_record"),
    ("ops/predict.py", "predict_level_ensemble"),
    ("ops/predict.py", "predict_level_ensemble_pallas"),
    ("ops/predict.py", "_level_step"),
    ("ops/predict.py", "predict_raw_ensemble"),
    ("ops/predict.py", "_walk_raw"),
    ("ops/predict.py", "predict_binned"),
    ("ops/predict.py", "unpack_tree_records_device"),
]


class _FnInfo:
    __slots__ = ("path", "name", "node", "module")

    def __init__(self, path: str, name: str, node: ast.AST,
                 module: str):
        self.path = path
        self.name = name
        self.node = node
        self.module = module


class SourceIndex:
    """Package-wide AST index: functions, per-module import aliases,
    internal-module imports — everything the call-graph expansion and
    the hazard scans read."""

    def __init__(self, sources: Dict[str, str]):
        self.trees: Dict[str, ast.Module] = {}
        self.functions: Dict[str, List[_FnInfo]] = {}   # name -> defs
        self.by_module: Dict[str, Dict[str, List[_FnInfo]]] = {}
        self.host_aliases: Dict[str, Dict[str, str]] = {}
        self.jnp_aliases: Dict[str, Set[str]] = {}
        self.internal_imports: Dict[str, Set[str]] = {}
        for path, text in sources.items():
            try:
                tree = ast.parse(text)
            except SyntaxError:
                continue
            self.trees[path] = tree
            self._index_module(path, tree)

    def _index_module(self, path: str, tree: ast.Module) -> None:
        host: Dict[str, str] = {}
        jnp: Set[str] = set()
        internal: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    top = a.name.split(".")[0]
                    alias = a.asname or top
                    if top in HOST_MODULES:
                        host[alias] = a.name
                    if a.name == "jax.numpy":
                        jnp.add(alias)
                    if top == "lightgbm_tpu":
                        internal.add(alias)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                top = mod.split(".")[0]
                for a in node.names:
                    alias = a.asname or a.name
                    if top in HOST_MODULES:
                        host[alias] = f"{mod}.{a.name}"
                    if mod == "jax" and a.name == "numpy":
                        jnp.add(alias)
                    if node.level or top == "lightgbm_tpu":
                        internal.add(alias)
        self.host_aliases[path] = host
        self.jnp_aliases[path] = jnp
        self.internal_imports[path] = internal

        mod_fns: Dict[str, List[_FnInfo]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = _FnInfo(path, node.name, node, path)
                self.functions.setdefault(node.name, []).append(info)
                mod_fns.setdefault(node.name, []).append(info)
        self.by_module[path] = mod_fns

    # -- call-graph expansion -----------------------------------------
    def resolve_call(self, path: str, call: ast.Call) -> List[_FnInfo]:
        """Conservative callee resolution (documented in the module
        docstring): same-module first, then package-wide for private
        (``_``-prefixed) or package-unique names."""
        fn = call.func
        name: Optional[str] = None
        if isinstance(fn, ast.Name):
            name = fn.id
        elif isinstance(fn, ast.Attribute):
            name = fn.attr
        if not name:
            return []
        local = self.by_module.get(path, {}).get(name, [])
        if local:
            return local
        cands = self.functions.get(name, [])
        if name.startswith("_") or len(cands) == 1:
            return cands
        return []

    def reachable(self, seeds: List[Tuple[str, str]]) -> List[_FnInfo]:
        """BFS the call graph from (file-suffix, fn-name) seeds."""
        work: List[_FnInfo] = []
        for suffix, name in seeds:
            found = [f for f in self.functions.get(name, [])
                     if f.path.endswith(suffix)]
            work.extend(found)
        seen: Set[int] = set()
        out: List[_FnInfo] = []
        while work:
            info = work.pop()
            key = id(info.node)
            if key in seen:
                continue
            seen.add(key)
            out.append(info)
            for node in ast.walk(info.node):
                if isinstance(node, ast.Call):
                    work.extend(self.resolve_call(info.path, node))
        return out


def _attr_chain(node: ast.AST) -> Optional[Tuple[str, str]]:
    """``np.random.uniform`` -> ("np", "random.uniform")."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        return node.id, ".".join(reversed(parts))
    return None


def scan_host_calls(index: SourceIndex, fns: List[_FnInfo]
                    ) -> List[Finding]:
    """TRC001 over a set of reachable functions."""
    out: List[Finding] = []
    seen: Set[Tuple[str, int, str]] = set()
    for info in fns:
        aliases = index.host_aliases.get(info.path, {})
        if not aliases:
            continue
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func)
            if not chain:
                continue
            root, path_ = chain
            mod = aliases.get(root)
            if not mod:
                continue
            full = f"{mod}.{path_}" if path_ else mod
            # normalize to the canonical module head for the allowlist
            head = mod.split(".")[0]
            canon = f"{head}." + full.split(".", 1)[1] \
                if "." in full else full
            if canon in SAFE_HOST_CALLS:
                continue
            key = (info.path, node.lineno, canon)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                rule="TRC001", file=info.path, line=node.lineno,
                message=f"`{root}.{path_}(...)` inside jit-reachable "
                        f"`{info.name}` — a {head} call at trace time "
                        "bakes a host constant into the compiled "
                        "program (or forces a host sync); use jnp/"
                        "jax.random, or hoist to the dispatch side"))
    return out


def scan_python_branching(index: SourceIndex, fns: List[_FnInfo]
                          ) -> List[Finding]:
    """TRC002: Python ``if``/``while`` on a jnp expression inside a
    jit-reachable function."""
    out: List[Finding] = []
    seen: Set[Tuple[str, int]] = set()
    for info in fns:
        jnp = index.jnp_aliases.get(info.path, set())
        if not jnp:
            continue
        for node in ast.walk(info.node):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            for sub in ast.walk(node.test):
                chain = _attr_chain(sub) if isinstance(
                    sub, ast.Attribute) else None
                if chain and chain[0] in jnp:
                    key = (info.path, node.lineno)
                    if key in seen:
                        break
                    seen.add(key)
                    kind = "if" if isinstance(node, ast.If) else "while"
                    out.append(Finding(
                        rule="TRC002", file=info.path,
                        line=node.lineno,
                        message=f"Python `{kind}` on a jnp expression "
                                f"inside jit-reachable `{info.name}` — "
                                "data-dependent Python control flow "
                                "either fails to trace or silently "
                                "specializes on the trace-time value; "
                                "use lax.cond/jnp.where"))
                    break
    return out


# -- Config consistency -----------------------------------------------------

def config_field_lines(config_src: str) -> Dict[str, int]:
    """{field name: definition line} from the Config dataclass body."""
    tree = ast.parse(config_src)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {stmt.target.id: stmt.lineno
                    for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)}
    return {}


def documented_params(doc_text: str) -> Set[str]:
    """First-column backticked names of the Parameters.md tables."""
    return set(re.findall(r"^\|\s*`(\w+)`\s*\|", doc_text, re.M))


def config_reads(sources: Dict[str, str]) -> Set[str]:
    """Every attribute name read (Load context) or getattr'd by string
    anywhere in the package — the CFG002 notion of a knob being
    consumed."""
    reads: Set[str] = set()
    for path, text in sources.items():
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                reads.add(node.attr)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ("getattr", "hasattr") \
                    and len(node.args) >= 2 \
                    and isinstance(node.args[1], ast.Constant) \
                    and isinstance(node.args[1].value, str):
                reads.add(node.args[1].value)
    return reads


# -- registered rules -------------------------------------------------------

def _reachable_fns(ctx) -> Tuple[SourceIndex, List[_FnInfo]]:
    # index + BFS are pure functions of ctx.sources — cached on the
    # Context so TRC001/TRC002 (and anything else) share one build
    return ctx.source_index, ctx.jit_reachable()


@rule("TRC001", "no host-library calls in jit-reachable functions",
      incident="trace-time constants / host syncs in device code")
def _trc001(ctx) -> List[Finding]:
    index, fns = _reachable_fns(ctx)
    return scan_host_calls(index, fns)


@rule("TRC002", "no Python branching on jnp values in jit-reachable "
                "functions",
      incident="trace-time specialization / ConcretizationTypeError")
def _trc002(ctx) -> List[Finding]:
    index, fns = _reachable_fns(ctx)
    return scan_python_branching(index, fns)


@rule("CFG001", "every Config knob documented in docs/Parameters.md",
      incident="accepted-but-undocumented knobs")
def _cfg001(ctx) -> List[Finding]:
    cfg_rel = "lightgbm_tpu/config.py"
    cfg_src = ctx.sources.get(cfg_rel)
    if cfg_src is None:                       # fixture source set
        return []
    doc_path = os.path.join(ctx.repo, "docs", "Parameters.md")
    try:
        with open(doc_path) as fh:
            documented = documented_params(fh.read())
    except FileNotFoundError:
        return [Finding(rule="CFG001", file="docs/Parameters.md",
                        message="docs/Parameters.md missing — run "
                                "scripts/gen_parameter_docs.py")]
    out: List[Finding] = []
    for name, line in sorted(config_field_lines(cfg_src).items()):
        if name not in documented:
            out.append(Finding(
                rule="CFG001", file=cfg_rel, line=line,
                message=f"Config knob `{name}` is not documented in "
                        "docs/Parameters.md — run scripts/"
                        "gen_parameter_docs.py"))
    return out


@rule("CFG002", "every Config knob read at least once in the package",
      incident="accepted-but-never-read knobs (user-facing no-ops)")
def _cfg002(ctx) -> List[Finding]:
    cfg_rel = "lightgbm_tpu/config.py"
    cfg_src = ctx.sources.get(cfg_rel)
    if cfg_src is None:
        return []
    reads = config_reads(ctx.sources)
    out: List[Finding] = []
    for name, line in sorted(config_field_lines(cfg_src).items()):
        if name not in reads:
            out.append(Finding(
                rule="CFG002", file=cfg_rel, line=line,
                message=f"Config knob `{name}` is never read anywhere "
                        "in the package — an accepted parameter that "
                        "does nothing; wire it or remove it"))
    return out
