"""Rule engine for the compiled-program lint framework.

A :class:`Rule` is a stable-ID'd check (``HLO004``, ``TRC001``, ...)
over one of three surfaces — lowered programs of the registered hot
entry points, the package's Python AST, or cross-artifact consistency
(spec vs emit site, span map vs glossary).  Rules emit
:class:`Finding` records; the engine applies source-comment
suppressions, reports unused suppressions, and renders one text or
JSON report.  ``python -m lightgbm_tpu.analysis`` is the CLI;
``scripts/bench_smoke.sh`` fails CI on any unsuppressed finding.

Suppression syntax (checked for staleness — a suppression that matches
no finding is itself a finding, rule ``SUP001``)::

    x = np.empty(n)  # lint: disable=TRC001(host buffer, dispatch side)

Trailing form suppresses that rule on that line; a standalone
``# lint: disable=...`` comment line suppresses the rule for the whole
file (program-level findings are attributed to the entry point's
defining file at line 0, so file scope is how they are waived).
"""
from __future__ import annotations

import dataclasses
import io
import json
import os
import re
from typing import Callable, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

JSON_SCHEMA_VERSION = 1

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=((?:[A-Z]+\d+\([^)]*\)(?:\s*,\s*)?)+)")
SUPPRESS_ITEM_RE = re.compile(r"([A-Z]+\d+)\(([^)]*)\)")


@dataclasses.dataclass
class Finding:
    """One rule violation (or suppressed would-be violation)."""
    rule: str
    message: str
    file: str = ""          # repo-relative source path
    line: int = 0           # 1-based; 0 = whole-file / program-level
    suppressed: bool = False
    reason: str = ""        # suppression reason when suppressed

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)

    def location(self) -> str:
        if self.file and self.line:
            return f"{self.file}:{self.line}"
        return self.file or "<repo>"


@dataclasses.dataclass
class Rule:
    id: str
    title: str
    incident: str           # which hard-won learning the rule encodes
    check: Callable         # (Context) -> List[Finding]
    needs_programs: bool = False


RULES: Dict[str, Rule] = {}


def rule(id: str, title: str, incident: str = "",
         needs_programs: bool = False):
    """Register a rule check function under a stable ID."""
    def deco(fn):
        RULES[id] = Rule(id=id, title=title, incident=incident,
                         check=fn, needs_programs=needs_programs)
        return fn
    return deco


@dataclasses.dataclass
class Suppression:
    file: str
    line: int               # line the comment sits on
    rule: str
    reason: str
    file_scope: bool        # standalone comment = whole-file scope
    used: bool = False


def parse_suppressions(path: str, text: str) -> List[Suppression]:
    """All ``# lint: disable=RULE(reason)`` comments in one file."""
    out: List[Suppression] = []
    for i, raw in enumerate(text.splitlines(), start=1):
        m = SUPPRESS_RE.search(raw)
        if not m:
            continue
        standalone = raw.strip().startswith("#")
        for rid, reason in SUPPRESS_ITEM_RE.findall(m.group(1)):
            out.append(Suppression(file=path, line=i, rule=rid,
                                   reason=reason.strip(),
                                   file_scope=standalone))
    return out


class Context:
    """Shared state handed to every rule: repo sources, lazily-built
    entry-point programs, and the selected-rule set."""

    def __init__(self, repo: str = REPO,
                 sources: Optional[Dict[str, str]] = None,
                 programs=None):
        self.repo = repo
        self._sources = sources
        self._programs = programs
        self._source_index = None
        self._reachable = None

    # -- sources ------------------------------------------------------
    @property
    def sources(self) -> Dict[str, str]:
        """{repo-relative path: text} for every package source file
        (the analysis package itself is excluded: it is host-only
        tooling, never jit-reachable, and must not self-lint its rule
        fixtures)."""
        if self._sources is None:
            srcs: Dict[str, str] = {}
            pkg = os.path.join(self.repo, "lightgbm_tpu")
            for root, _dirs, files in os.walk(pkg):
                if os.sep + "analysis" in root:
                    continue
                for f in sorted(files):
                    if not f.endswith(".py"):
                        continue
                    path = os.path.join(root, f)
                    rel = os.path.relpath(path, self.repo)
                    with open(path) as fh:
                        srcs[rel] = fh.read()
            self._sources = srcs
        return self._sources

    def suppression_sources(self) -> Dict[str, str]:
        """Sources scanned for ``# lint: disable`` comments: the
        package files plus the out-of-package files TEL001 lints
        (bench.py, scripts/profile_train.py) — a finding attributed
        to those files must be waivable like any other."""
        from .teldoc_rule import EXTRA_SOURCES
        out = dict(self.sources)
        for rel in EXTRA_SOURCES:
            path = os.path.join(self.repo, rel)
            if rel not in out and os.path.exists(path):
                with open(path) as fh:
                    out[rel] = fh.read()
        return out

    # -- programs -----------------------------------------------------
    @property
    def programs(self):
        if self._programs is None:
            from .programs import ProgramSet
            self._programs = ProgramSet()
        return self._programs

    # -- AST index (shared by TRC001/TRC002/CFG002) -------------------
    @property
    def source_index(self):
        if self._source_index is None:
            from .ast_rules import SourceIndex
            self._source_index = SourceIndex(self.sources)
        return self._source_index

    def jit_reachable(self):
        if self._reachable is None:
            from .ast_rules import JIT_SEEDS
            self._reachable = self.source_index.reachable(JIT_SEEDS)
        return self._reachable


def _apply_suppressions(findings: List[Finding],
                        sups: List[Suppression]) -> None:
    """Mark findings covered by a suppression; mark suppressions used.
    Trailing comments cover their own line; standalone comments cover
    the file."""
    by_file: Dict[str, List[Suppression]] = {}
    for s in sups:
        by_file.setdefault(s.file, []).append(s)
    for f in findings:
        for s in by_file.get(f.file, ()):
            if s.rule != f.rule:
                continue
            if s.file_scope or (f.line and s.line == f.line):
                f.suppressed = True
                f.reason = s.reason
                s.used = True
                break


def run_rules(rule_ids: Optional[List[str]] = None,
              ctx: Optional[Context] = None,
              check_suppressions: bool = True) -> List[Finding]:
    """Run the selected rules (default: all registered) and apply
    suppressions.  Returns every finding, suppressed ones included —
    callers gate on the unsuppressed subset."""
    # rule modules self-register on import
    from . import ast_rules, hlo_rules, layout_rule, teldoc_rule  # noqa: F401

    ctx = ctx or Context()
    ids = list(rule_ids) if rule_ids else sorted(RULES)
    unknown = [i for i in ids if i not in RULES]
    if unknown:
        raise ValueError(f"unknown rule id(s): {unknown}; "
                         f"known: {sorted(RULES)}")
    findings: List[Finding] = []
    for rid in ids:
        findings.extend(RULES[rid].check(ctx))

    sups: List[Suppression] = []
    for rel, text in ctx.suppression_sources().items():
        sups.extend(parse_suppressions(rel, text))
    _apply_suppressions(findings, sups)
    if check_suppressions:
        for s in sups:
            if not s.used and (s.rule in ids or s.rule not in RULES):
                findings.append(Finding(
                    rule="SUP001",
                    message=(f"unused suppression for {s.rule}"
                             + (f" ({s.reason})" if s.reason else "")
                             + " — the finding it waived no longer "
                               "fires; delete the comment"),
                    file=s.file, line=s.line))
    return findings


def unsuppressed(findings: List[Finding]) -> List[Finding]:
    return [f for f in findings if not f.suppressed]


def render_text(findings: List[Finding],
                rule_ids: Optional[List[str]] = None) -> str:
    out = io.StringIO()
    live = unsuppressed(findings)
    for f in sorted(live, key=lambda f: (f.rule, f.file, f.line)):
        out.write(f"{f.rule} {f.location()}: {f.message}\n")
    n_sup = len(findings) - len(live)
    ids = rule_ids or sorted(RULES)
    if live:
        out.write(f"lightgbm_tpu.analysis: {len(live)} finding(s) "
                  f"({n_sup} suppressed) across {len(ids)} rule(s)\n")
    else:
        out.write(f"lightgbm_tpu.analysis: clean — {len(ids)} rule(s), "
                  f"0 findings ({n_sup} suppressed)\n")
    return out.getvalue()


def render_json(findings: List[Finding],
                rule_ids: Optional[List[str]] = None) -> str:
    live = unsuppressed(findings)
    ids = rule_ids or sorted(RULES)
    doc = {
        "version": JSON_SCHEMA_VERSION,
        "rules_run": ids,
        "findings": [f.to_dict() for f in sorted(
            findings, key=lambda f: (f.rule, f.file, f.line))],
        "counts": {
            "total": len(findings),
            "suppressed": len(findings) - len(live),
            "unsuppressed": len(live),
        },
        "clean": not live,
    }
    return json.dumps(doc, sort_keys=True)
