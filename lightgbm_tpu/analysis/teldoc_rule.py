"""TEL001 — telemetry span/phase names <-> docs/OBSERVABILITY.md span
map, both directions (re-homed from
``scripts/check_telemetry_coverage.py``, now a thin wrapper here).

The span map is the contract between the instrumentation and anyone
reading a Perfetto trace — an undocumented span is a mystery slice in
the UI, and a documented-but-deleted span means the doc (and any
dashboard built on it) silently rotted.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Set

from .core import Finding, rule

CALL_RE = re.compile(
    r"\.(?:span|start_span|phase)\(\s*(?:f?)([\"'])([^\"']+)\1")
DYNAMIC_RE = re.compile(r"\.(?:span|start_span|phase)\(\s*[^\"')]")
DOC = "docs/OBSERVABILITY.md"

# telemetry.py itself defines the API (its internal span("device_wait")
# helper IS a real span and is scanned too); profile_train.py and
# bench.py sit outside the package but emit real spans
EXTRA_SOURCES = ("scripts/profile_train.py", "bench.py")


def code_spans(sources: Dict[str, str]) -> Dict[str, Set[str]]:
    """{span name: files using it} plus dynamic-name findings are
    handled in the rule body (they cannot be in the glossary)."""
    names: Dict[str, Set[str]] = {}
    for rel, src in sources.items():
        for m in CALL_RE.finditer(src):
            names.setdefault(m.group(2), set()).add(rel)
    return names


def dynamic_span_findings(sources: Dict[str, str]) -> List[Finding]:
    out: List[Finding] = []
    for rel, src in sources.items():
        for m in DYNAMIC_RE.finditer(src):
            frag = src[m.start():m.start() + 60].splitlines()[0]
            # allow the API definition sites in telemetry.py and
            # variable-forwarding helpers that pass a `name` parameter
            if rel.endswith("telemetry.py") or re.match(
                    r"\.(?:span|start_span|phase)\(\s*(?:self|name|f?\")",
                    frag):
                continue
            line = src[:m.start()].count("\n") + 1
            out.append(Finding(
                rule="TEL001", file=rel, line=line,
                message="dynamic span/phase name cannot be linted "
                        f"against the glossary: {frag!r}"))
    return out


def doc_spans(text: str) -> Set[str]:
    names: Set[str] = set()
    in_table = False
    for line in text.splitlines():
        if line.startswith("| Span |") or line.startswith("| Phase |"):
            in_table = True
            continue
        if in_table:
            m = re.match(r"\|\s*`([^`]+)`\s*\|", line)
            if m:
                names.add(m.group(1))
            elif not line.startswith("|"):
                in_table = False
    return names


@rule("TEL001", "span/phase names consistent with the "
                "docs/OBSERVABILITY.md span map, both directions",
      incident="r9 telemetry subsystem")
def _tel001(ctx) -> List[Finding]:
    sources = dict(ctx.sources)
    for rel in EXTRA_SOURCES:
        path = os.path.join(ctx.repo, rel)
        if os.path.exists(path) and rel not in sources:
            with open(path) as fh:
                sources[rel] = fh.read()

    doc_path = os.path.join(ctx.repo, DOC)
    try:
        with open(doc_path) as fh:
            doc = doc_spans(fh.read())
    except FileNotFoundError:
        return [Finding(rule="TEL001", file=DOC,
                        message=f"{DOC} missing — the span map is the "
                                "observability contract")]
    out = dynamic_span_findings(sources)
    code = code_spans(sources)
    if not doc:
        out.append(Finding(
            rule="TEL001", file=DOC,
            message=f"no span map tables parsed from {DOC}"))
    for name, sites in sorted(code.items()):
        if name not in doc:
            out.append(Finding(
                rule="TEL001", file=sorted(sites)[0],
                message=f"span {name!r} (used in "
                        f"{', '.join(sorted(sites))}) is missing from "
                        f"the {DOC} span map"))
    for name in sorted(doc - set(code)):
        out.append(Finding(
            rule="TEL001", file=DOC,
            message=f"{DOC} documents span {name!r} but no span(/"
                    "phase( call with that name exists in the code"))
    return out
