"""Host-side tree model: struct-of-arrays, prediction, serialization.

Mirrors the reference Tree (include/LightGBM/tree.h:20-392,
src/io/tree.cpp) — array-of-nodes with negative-encoded leaf children,
``decision_type`` bitfield (bit0 categorical, bit1 default-left,
bits2-3 missing type — tree.h:14-15,183-202) and the v2.1.1 text format
(Tree::ToString).  The device grower (learner/grower.py) emits bin-space
TreeArrays; ``Tree.from_grower_arrays`` converts thresholds to real
values through the BinMappers so saved models are interchangeable with
the reference's.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

import numpy as np

from .binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO
from .utils.log import Log

K_CATEGORICAL_MASK = 1
K_DEFAULT_LEFT_MASK = 2
K_ZERO_THRESHOLD = 1e-35


# ---------------------------------------------------------------------------
# Packed tree-record layout (round 7).
#
# The fused dispatch scan used to carry EIGHTEEN O(chunk)-sized stacked
# output buffers — one per TreeArrays field plus the num_leaves series —
# and the TPU backend's handling of that many loop-carried output stacks
# is what made per-tree time grow linearly with chunk length
# (docs/ROOFLINE.md round-6 delta: per-tree ≈ 25.75 + 0.075·chunk ms).
# This layout packs one grown tree into ONE contiguous byte buffer with
# FIXED offsets derived from (num_leaves L, max_feature_bin B), so the
# scan carries a single uint8 output stack (plus the small num_leaves
# series for the deferred stop check).  The grower emits it with
# static-offset dynamic-update-slice writes (pack_tree_record); the
# host unpacks after dispatch (unpack_tree_record) and the device
# unpacks for in-session prediction (ops/predict.py
# unpack_tree_records_device).
#
# TREE_RECORD_SPEC is the single source of truth: field order MUST
# equal learner.grower.TreeArrays._fields, dtypes are little-endian
# (matching both numpy .view and jax.lax.bitcast_convert_type byte
# enumeration), and shapes are symbolic in the dims {L, M, B} with
# M = L - 1.  scripts/check_carry_layout.py lints the spec against the
# grower's emit sites and fails on drift.
# ---------------------------------------------------------------------------
TREE_RECORD_SPEC = (
    ("num_leaves", "<i4", ()),
    ("leaf_value", "<f4", ("L",)),
    ("leaf_weight", "<f4", ("L",)),
    ("leaf_count", "<f4", ("L",)),
    ("leaf_parent", "<i4", ("L",)),
    ("leaf_depth", "<i4", ("L",)),
    ("node_feature", "<i4", ("M",)),
    ("node_threshold", "<i4", ("M",)),
    ("node_default_left", "|u1", ("M",)),
    ("node_is_cat", "|u1", ("M",)),
    ("node_cat_mask", "|u1", ("M", "B")),
    ("node_gain", "<f4", ("M",)),
    ("node_value", "<f4", ("M",)),
    ("node_weight", "<f4", ("M",)),
    ("node_count", "<f4", ("M",)),
    ("node_left", "<i4", ("M",)),
    ("node_right", "<i4", ("M",)),
)


class TreeRecordLayout:
    """Fixed byte offsets of one packed tree record for a given
    (num_leaves, max_feature_bin) shape.  ``fields`` maps field name ->
    (offset, nbytes, numpy dtype string, concrete shape)."""

    def __init__(self, num_leaves: int, max_feature_bin: int):
        self.num_leaves = int(num_leaves)
        self.max_feature_bin = int(max_feature_bin)
        dims = {"L": self.num_leaves,
                "M": self.num_leaves - 1,   # matches TreeArrays' node dim
                "B": self.max_feature_bin}
        self.fields: Dict[str, tuple] = {}
        off = 0
        for name, dt, shape_sym in TREE_RECORD_SPEC:
            shape = tuple(dims[s] for s in shape_sym)
            count = 1
            for s in shape:
                count *= s
            nbytes = count * np.dtype(dt).itemsize
            # every field starts word-aligned and the record is padded
            # to a 64-byte multiple: sub-word starts/odd-sized carry
            # buffers are exactly what backends mishandle, and the pad
            # costs bytes, not buffers
            off = (off + 3) & ~3
            self.fields[name] = (off, nbytes, dt, shape)
            off += nbytes
        self.record_size = (off + 63) & ~63

    # ------------------------------------------------------------------
    def pack_tree_record(self, tree):
        """Device-side: serialize one grown TreeArrays into a (record_
        size,) uint8 buffer with static-offset dynamic-update-slice
        writes (lax.dynamic_update_slice, NOT ``.at[...].set`` — jnp's
        indexed update lowers to a windowed scatter, while an explicit
        DUS is the in-place form the fused chunk's HLO regression test
        pins)."""
        import jax
        import jax.numpy as jnp

        buf = jnp.zeros((self.record_size,), jnp.uint8)
        for name, (off, nbytes, dt, shape) in self.fields.items():
            arr = getattr(tree, name)
            kind = np.dtype(dt).kind
            if kind == "u":                       # bools stored as bytes
                by = arr.astype(jnp.uint8).reshape(-1)
            else:
                tgt = jnp.int32 if kind == "i" else jnp.float32
                by = jax.lax.bitcast_convert_type(
                    arr.astype(tgt), jnp.uint8).reshape(-1)
            buf = jax.lax.dynamic_update_slice(buf, by, (off,))
        return buf

    # ------------------------------------------------------------------
    def unpack_tree_record(self, buf: np.ndarray) -> Dict[str, np.ndarray]:
        """Host-side: one packed record (uint8 numpy) back to the
        TreeArrays field dict Tree.from_grower_arrays consumes."""
        buf = np.ascontiguousarray(np.asarray(buf, dtype=np.uint8))
        out: Dict[str, np.ndarray] = {}
        for name, (off, nbytes, dt, shape) in self.fields.items():
            raw = buf[off:off + nbytes]
            if np.dtype(dt).kind == "u":
                arr = raw.astype(bool)
            else:
                arr = raw.view(dt)
            out[name] = arr.reshape(shape) if shape else arr.reshape(())[()]
        return out


def ensemble_cat_width(models: List["Tree"]) -> int:
    """Widest per-node categorical bitset (in uint32 words) across an
    ensemble — the padded W of every device tree stack."""
    W = 1
    for t in models:
        for i in range(t.num_leaves - 1):
            if t.decision_type[i] & K_CATEGORICAL_MASK:
                ci = int(t.threshold[i])
                W = max(W, t.cat_boundaries[ci + 1] - t.cat_boundaries[ci])
    return W


def tree_cat_words(t: "Tree", width: int) -> np.ndarray:
    """One tree's per-node categorical bitsets as a dense
    (num_leaves-1, width) uint32 block (zero-padded)."""
    m = max(t.num_leaves - 1, 0)
    cw = np.zeros((m, width), np.uint32)
    for i in range(m):
        if t.decision_type[i] & K_CATEGORICAL_MASK:
            ci = int(t.threshold[i])
            lo, hi = t.cat_boundaries[ci], t.cat_boundaries[ci + 1]
            words = np.asarray(t.cat_threshold[lo:hi], dtype=np.uint32)
            cw[i, :len(words)] = words
    return cw


def split_threshold_parts(thr: np.ndarray):
    """f64 thresholds -> (hi, lo) f32 pair for the device two-float
    compare.  +-inf thresholds (a split keeping the NaN/overflow bin on
    one side) must keep lo finite: inf - inf is NaN, and a NaN residual
    poisons the compare into always-right, diverging from the host
    walk's ``fv <= +inf`` (the r7 fix — ONE definition, shared by every
    device tree stacker)."""
    hi = thr.astype(np.float32)
    with np.errstate(invalid="ignore"):
        lo = (thr - hi.astype(np.float64)).astype(np.float32)
    return hi, np.where(np.isnan(lo), np.float32(0), lo)


def flatten_ensemble(models: List["Tree"], num_class: int = 1
                     ) -> Dict[str, np.ndarray]:
    """Ensemble-level SoA node layout for the level-synchronous device
    descent (ops/predict.py predict_level_ensemble / _pallas).

    The per-tree node arrays of the whole ensemble land in ONE flat
    node axis — tree ``t``'s node ``i`` at flat slot ``t*M + i`` (M =
    the batch max node count) — with child pointers PRE-RESOLVED into
    that flat space (internal child ``c`` -> ``t*M + c``; leaf ``l`` ->
    ``-(t*L + l) - 1``, indexing the flat leaf-value vector), so the
    descent never forms ``t*M + node`` on device and one (N, T) gather
    per small table serves every tree at once.  The split feature is
    pre-DOUBLED (``2*f``) to index the interleaved (N, 2F) hi/lo
    matrix: a single take_along_axis per level fetches BOTH float
    parts of the two-float threshold compare for every (row, tree)
    pair — the whole-ensemble replacement for the per-tree scan's two
    full-matrix gathers per node step.

    Returns the LevelEnsemble field dict (numpy; feat2/thr_hi/thr_lo/
    dtype_/left/right/leaf_value/cat_words/root/cls_onehot) plus the
    static ``depth`` bound (max tree depth — the unrolled level count
    that settles every row).
    """
    T = len(models)
    if T == 0:
        raise ValueError("flatten_ensemble needs at least one tree")
    M = max(max(t.num_leaves - 1 for t in models), 1)
    L = M + 1
    W = ensemble_cat_width(models)
    feat2 = np.zeros((T, M), np.int32)
    thr = np.zeros((T, M), np.float64)
    dt = np.zeros((T, M), np.int32)
    left = np.zeros((T, M), np.int64)
    right = np.zeros((T, M), np.int64)
    lv = np.zeros((T, L), np.float32)
    cw = np.zeros((T, M, W), np.uint32)
    root = np.zeros(T, np.int32)
    depth = 0
    for k, t in enumerate(models):
        m = t.num_leaves - 1
        if m <= 0:
            # stump: the root IS leaf 0 — encode it settled
            lv[k, 0] = t.leaf_value[0] if len(t.leaf_value) else 0.0
            root[k] = -(k * L) - 1
            continue
        root[k] = k * M
        depth = max(depth, t.max_depth())
        feat2[k, :m] = 2 * t.split_feature[:m]
        thr[k, :m] = t.threshold[:m]
        dt[k, :m] = t.decision_type[:m]
        # child pointers resolved into the flat node/leaf spaces
        for arr, out in ((t.left_child, left), (t.right_child, right)):
            c = np.asarray(arr[:m], np.int64)
            out[k, :m] = np.where(c >= 0, k * M + c, -(k * L + (-c - 1)) - 1)
        lv[k, :t.num_leaves] = t.leaf_value[:t.num_leaves]
        cw[k, :m] = tree_cat_words(t, W)
    hi, lo = split_threshold_parts(thr)
    k_cls = max(num_class, 1)
    cls_onehot = np.zeros((T, k_cls), np.float32)
    cls_onehot[np.arange(T), np.arange(T) % k_cls] = 1.0
    return {
        "feat2": feat2.reshape(-1),
        "thr_hi": hi.reshape(-1),
        "thr_lo": lo.reshape(-1),
        "dtype_": dt.reshape(-1),
        "left": left.reshape(-1).astype(np.int32),
        "right": right.reshape(-1).astype(np.int32),
        "leaf_value": lv.reshape(-1),
        "cat_words": cw.reshape(-1).view(np.int32),
        "root": root,
        "cls_onehot": cls_onehot,
        "depth": depth,
    }


def _make_decision_type(is_cat: bool, default_left: bool,
                        missing_type: int) -> int:
    dt = 0
    if is_cat:
        dt |= K_CATEGORICAL_MASK
    if default_left:
        dt |= K_DEFAULT_LEFT_MASK
    dt |= (missing_type & 3) << 2
    return dt


def _construct_bitset(values: List[int]) -> List[int]:
    """Common::ConstructBitset (reference utils/common.h:815-824)."""
    if not values:
        return []
    n_words = max(values) // 32 + 1
    words = [0] * n_words
    for v in values:
        words[v // 32] |= (1 << (v % 32))
    return words


def _find_in_bitset(words: np.ndarray, pos: np.ndarray) -> np.ndarray:
    """Vectorized FindInBitset (reference utils/common.h:827-835)."""
    n = len(words)
    i1 = pos // 32
    ok = (i1 >= 0) & (i1 < n)
    i1c = np.clip(i1, 0, max(n - 1, 0))
    if n == 0:
        return np.zeros(len(pos), dtype=bool)
    return ok & (((words[i1c] >> (pos % 32)) & 1) > 0)


class Tree:
    """One decision tree in model space (real thresholds/categories)."""

    def __init__(self, num_leaves: int):
        self.num_leaves = num_leaves
        m = max(num_leaves - 1, 0)
        self.split_feature = np.zeros(m, dtype=np.int32)   # real feature idx
        self.split_gain = np.zeros(m, dtype=np.float64)
        self.threshold = np.zeros(m, dtype=np.float64)
        self.decision_type = np.zeros(m, dtype=np.int32)
        self.left_child = np.zeros(m, dtype=np.int32)
        self.right_child = np.zeros(m, dtype=np.int32)
        self.leaf_value = np.zeros(num_leaves, dtype=np.float64)
        self.leaf_count = np.zeros(num_leaves, dtype=np.int64)
        self.internal_value = np.zeros(m, dtype=np.float64)
        self.internal_count = np.zeros(m, dtype=np.int64)
        self.shrinkage = 1.0
        # categorical storage (reference tree.h cat_boundaries_/cat_threshold_)
        self.num_cat = 0
        self.cat_boundaries: List[int] = [0]
        self.cat_threshold: List[int] = []

    # ------------------------------------------------------------------
    @classmethod
    def from_grower_arrays(cls, arrs: Dict[str, np.ndarray],
                           dataset) -> "Tree":
        """Convert device TreeArrays (bin space) to model space."""
        num_leaves = int(arrs["num_leaves"])
        t = cls(num_leaves)
        m = num_leaves - 1
        if m <= 0:
            t.leaf_value[0] = float(arrs["leaf_value"][0])
            t.leaf_count[0] = int(arrs["leaf_count"][0])
            return t
        feats = dataset.features
        t.leaf_value = arrs["leaf_value"][:num_leaves].astype(np.float64)
        t.leaf_count = np.round(
            arrs["leaf_count"][:num_leaves]).astype(np.int64)
        t.split_gain = arrs["node_gain"][:m].astype(np.float64)
        t.internal_value = arrs["node_value"][:m].astype(np.float64)
        t.internal_count = np.round(arrs["node_count"][:m]).astype(np.int64)
        t.left_child = arrs["node_left"][:m].astype(np.int32)
        t.right_child = arrs["node_right"][:m].astype(np.int32)
        node_feat = arrs["node_feature"][:m]
        node_thr = arrs["node_threshold"][:m]
        node_dl = arrs["node_default_left"][:m]
        node_cat = arrs["node_is_cat"][:m]
        cat_mask = arrs["node_cat_mask"][:m]
        for i in range(m):
            fv = feats[int(node_feat[i])]
            t.split_feature[i] = fv.feature_idx
            if node_cat[i]:
                cats = [fv.mapper.bin_2_categorical[b]
                        for b in np.nonzero(cat_mask[i][:fv.num_bin])[0]
                        if fv.mapper.bin_2_categorical[b] >= 0]
                words = _construct_bitset(cats)
                t.threshold[i] = t.num_cat
                t.num_cat += 1
                t.cat_boundaries.append(t.cat_boundaries[-1] + len(words))
                t.cat_threshold.extend(words)
                t.decision_type[i] = _make_decision_type(
                    True, False, fv.missing_type)
            else:
                t.threshold[i] = fv.mapper.bin_to_value(int(node_thr[i]))
                t.decision_type[i] = _make_decision_type(
                    False, bool(node_dl[i]), fv.missing_type)
        return t

    # ------------------------------------------------------------------
    def apply_shrinkage(self, rate: float) -> None:
        """reference tree.h:139 Shrinkage()."""
        self.leaf_value *= rate
        self.internal_value *= rate
        self.shrinkage *= rate

    # ------------------------------------------------------------------
    def predict_leaf(self, X: np.ndarray) -> np.ndarray:
        """Vectorized GetLeaf (reference tree.h:487-499): returns the
        leaf index per row of raw feature matrix X."""
        n = X.shape[0]
        if self.num_leaves <= 1:
            return np.zeros(n, dtype=np.int32)
        node = np.zeros(n, dtype=np.int32)
        active = node >= 0
        # every step resolves one level; bounded by num_leaves
        for _ in range(self.num_leaves):
            if not active.any():
                break
            idx = node[active]
            fvals = X[active, self.split_feature[idx]]
            dt = self.decision_type[idx]
            is_cat = (dt & K_CATEGORICAL_MASK) > 0
            default_left = (dt & K_DEFAULT_LEFT_MASK) > 0
            mtype = (dt >> 2) & 3
            thr = self.threshold[idx]
            nan_mask = np.isnan(fvals)
            fv = np.where(nan_mask & (mtype != 2), 0.0, fvals)
            is_zero = (fv > -K_ZERO_THRESHOLD) & (fv <= K_ZERO_THRESHOLD)
            use_default = ((mtype == 1) & is_zero) | \
                          ((mtype == 2) & np.isnan(fv))
            go_left = np.where(use_default, default_left, fv <= thr)
            if is_cat.any():
                cat_left = np.zeros(len(idx), dtype=bool)
                for j in np.nonzero(is_cat)[0]:
                    v = fvals[j]
                    if np.isnan(v) or int(v) < 0:
                        cat_left[j] = False
                        continue
                    ci = int(thr[j])
                    lo, hi = self.cat_boundaries[ci], self.cat_boundaries[ci + 1]
                    words = np.asarray(self.cat_threshold[lo:hi],
                                       dtype=np.uint32)
                    cat_left[j] = bool(_find_in_bitset(
                        words, np.asarray([int(v)]))[0])
                go_left = np.where(is_cat, cat_left, go_left)
            nxt = np.where(go_left, self.left_child[idx],
                           self.right_child[idx])
            node[active] = nxt
            active = node >= 0
        return (-node - 1).astype(np.int32)

    def predict(self, X: np.ndarray) -> np.ndarray:
        return self.leaf_value[self.predict_leaf(X)]

    # ------------------------------------------------------------------
    def max_depth(self) -> int:
        if self.num_leaves <= 1:
            return 0
        depth = np.zeros(self.num_leaves - 1, dtype=np.int32)
        leaf_depth = np.zeros(self.num_leaves, dtype=np.int32)
        for i in range(self.num_leaves - 1):
            for child in (self.left_child[i], self.right_child[i]):
                if child >= 0:
                    depth[child] = depth[i] + 1
                else:
                    leaf_depth[-child - 1] = depth[i] + 1
        return int(leaf_depth.max())

    # ------------------------------------------------------------------
    def to_string(self) -> str:
        """v2.1.1 Tree::ToString (reference src/io/tree.cpp)."""
        m = self.num_leaves - 1
        out = []
        out.append(f"num_leaves={self.num_leaves}")
        out.append(f"num_cat={self.num_cat}")
        out.append("split_feature=" + _join_int(self.split_feature[:m]))
        out.append("split_gain=" + _join_float(self.split_gain[:m]))
        out.append("threshold=" + _join_float(self.threshold[:m], 20))
        out.append("decision_type=" + _join_int(self.decision_type[:m]))
        out.append("left_child=" + _join_int(self.left_child[:m]))
        out.append("right_child=" + _join_int(self.right_child[:m]))
        out.append("leaf_value=" + _join_float(self.leaf_value, 20))
        out.append("leaf_count=" + _join_int(self.leaf_count))
        out.append("internal_value=" + _join_float(self.internal_value[:m]))
        out.append("internal_count=" + _join_int(self.internal_count[:m]))
        if self.num_cat > 0:
            out.append("cat_boundaries=" + _join_int(self.cat_boundaries))
            out.append("cat_threshold=" + _join_int(self.cat_threshold))
        out.append(f"shrinkage={self.shrinkage:g}")
        out.append("")
        return "\n".join(out)

    # ------------------------------------------------------------------
    @classmethod
    def from_string(cls, text: str) -> "Tree":
        kv: Dict[str, str] = {}
        for line in text.splitlines():
            line = line.strip()
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k] = v
        num_leaves = int(kv["num_leaves"])
        t = cls(num_leaves)
        t.num_cat = int(kv.get("num_cat", "0"))
        m = num_leaves - 1

        def ints(key, n):
            if n == 0 or key not in kv or not kv[key].strip():
                return np.zeros(n, dtype=np.int64)
            return np.array(kv[key].split(), dtype=np.int64)

        def floats(key, n):
            if n == 0 or key not in kv or not kv[key].strip():
                return np.zeros(n, dtype=np.float64)
            return np.array(kv[key].split(), dtype=np.float64)

        t.split_feature = ints("split_feature", m).astype(np.int32)
        t.split_gain = floats("split_gain", m)
        t.threshold = floats("threshold", m)
        t.decision_type = ints("decision_type", m).astype(np.int32)
        t.left_child = ints("left_child", m).astype(np.int32)
        t.right_child = ints("right_child", m).astype(np.int32)
        t.leaf_value = floats("leaf_value", num_leaves)
        t.leaf_count = ints("leaf_count", num_leaves)
        t.internal_value = floats("internal_value", m)
        t.internal_count = ints("internal_count", m)
        if t.num_cat > 0:
            t.cat_boundaries = [int(x) for x in kv["cat_boundaries"].split()]
            t.cat_threshold = [int(x) for x in kv["cat_threshold"].split()]
        t.shrinkage = float(kv.get("shrinkage", "1"))
        return t

    # ------------------------------------------------------------------
    def leaf_output(self, leaf: int) -> float:
        return float(self.leaf_value[leaf])

    def set_leaf_output(self, leaf: int, value: float) -> None:
        self.leaf_value[leaf] = value


def _join_int(arr) -> str:
    return " ".join(str(int(x)) for x in arr)


def _join_float(arr, precision: int = 10) -> str:
    return " ".join(f"{float(x):.{precision}g}" for x in arr)
