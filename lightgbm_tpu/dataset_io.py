"""Binned-dataset binary cache.

The analog of the reference's save_binary / LoadFromBinFile
(reference: dataset.cpp:18 token + :528-607 writer,
dataset_loader.cpp:171,266-486 auto-detected fast load): persists the
fully-binned matrix, mappers and metadata so repeat training skips
parsing + bin finding — the direct ancestor of a TPU HBM-resident
packed-bin snapshot.
"""
from __future__ import annotations

import pickle
from typing import Optional

import numpy as np

from .dataset import Dataset
from .utils.log import Log

BINARY_TOKEN = b"______LightGBM_TPU_Binary_File_Token______\n"
FORMAT_VERSION = 1

# Virtual file schemes (the reference's VirtualFileReader/Writer +
# HDFSFile seam, src/io/file_io.cpp:54-165).  HDFS itself is a
# PERMANENT descope — no Hadoop client exists in the target
# environments — but the dispatch seam is kept: register an opener for
# a scheme ("hdfs", "s3", "gs", ...) and every binary-cache read/write
# routes through it.  fsspec plugs in as
# ``register_file_scheme("s3", fsspec.open)``.
_SCHEME_OPENERS = {}


def register_file_scheme(scheme: str, opener) -> None:
    """``opener(path, mode)`` must return a binary file-like object."""
    _SCHEME_OPENERS[scheme.lower()] = opener


def _open(filename: str, mode: str):
    if "://" in filename:
        scheme = filename.split("://", 1)[0].lower()
        op = _SCHEME_OPENERS.get(scheme)
        if op is None:
            Log.fatal(
                f"no opener registered for scheme '{scheme}://' — "
                "register one with lightgbm_tpu.dataset_io."
                "register_file_scheme (HDFS is intentionally out of "
                "scope; any fsspec-style opener plugs in here)")
        return op(filename, mode)
    return open(filename, mode)


def save_binary(dataset: Dataset, filename: str) -> None:
    payload = {
        "version": FORMAT_VERSION,
        "num_data": dataset.num_data,
        "num_total_features": dataset.num_total_features,
        "mappers": dataset.mappers,
        "used_features": dataset.used_features,
        "group_bins": dataset.group_bins,
        "group_num_bin": dataset.group_num_bin,
        "group_is_multi": dataset.group_is_multi,
        "bundles": dataset._bundles,
        "feature_names": dataset.feature_names,
        "max_bin": dataset.max_bin,
        "label": dataset.metadata.label,
        "weight": dataset.metadata.weight,
        "query_boundaries": dataset.metadata.query_boundaries,
        "init_score": dataset.metadata.init_score,
        "monotone": dataset.monotone_constraints,
        "categorical_features": dataset._categorical_features,
    }
    with _open(filename, "wb") as f:
        f.write(BINARY_TOKEN)
        pickle.dump(payload, f, protocol=4)
    Log.info(f"Saved binned dataset to binary file {filename}")


def is_binary_file(filename: str) -> bool:
    try:
        with _open(filename, "rb") as f:
            return f.read(len(BINARY_TOKEN)) == BINARY_TOKEN
    except Exception:
        # a probe, not an assertion: unreadable paths, unregistered
        # schemes, and opener-specific errors all mean "not a binary
        # dataset file" here
        return False


def load_binary(filename: str) -> Dataset:
    with _open(filename, "rb") as f:
        token = f.read(len(BINARY_TOKEN))
        if token != BINARY_TOKEN:
            Log.fatal(f"{filename} is not a lightgbm_tpu binary dataset")
        payload = pickle.load(f)
    if payload.get("version") != FORMAT_VERSION:
        Log.fatal("Unsupported binary dataset version")
    ds = Dataset.__new__(Dataset)
    Dataset.__init__(ds)
    ds.num_data = payload["num_data"]
    ds.num_total_features = payload["num_total_features"]
    ds.mappers = payload["mappers"]
    ds.used_features = payload["used_features"]
    ds.group_bins = payload["group_bins"]
    ds.group_num_bin = payload["group_num_bin"]
    ds.group_is_multi = payload["group_is_multi"]
    ds._bundles = payload["bundles"]
    ds.feature_names = payload["feature_names"]
    ds.max_bin = payload["max_bin"]
    ds._categorical_features = payload["categorical_features"]
    ds.monotone_constraints = payload["monotone"]
    # rebuild FeatureView list from bundles + mappers
    from .dataset import FeatureView
    feats = []
    for gidx, bundle in enumerate(ds._bundles):
        if len(bundle) == 1:
            fidx = bundle[0]
            feats.append(FeatureView(fidx, gidx, 0, 0, ds.mappers[fidx],
                                     collapsed_default=False))
        else:
            total = 1
            for sub, fidx in enumerate(bundle):
                m = ds.mappers[fidx]
                offset = total
                nb = m.num_bin - (1 if m.default_bin == 0 else 0)
                feats.append(FeatureView(fidx, gidx, sub, offset, m,
                                         collapsed_default=True))
                total += nb
    feats.sort(key=lambda f: f.feature_idx)
    ds.features = feats
    from .dataset import Metadata
    ds.metadata = Metadata(ds.num_data)
    ds.metadata.label = payload["label"]
    ds.metadata.weight = payload["weight"]
    ds.metadata.query_boundaries = payload["query_boundaries"]
    ds.metadata.init_score = payload["init_score"]
    Log.info(f"Loaded binned dataset from binary file {filename}")
    return ds
