"""Binned-dataset binary cache.

The analog of the reference's save_binary / LoadFromBinFile
(reference: dataset.cpp:18 token + :528-607 writer,
dataset_loader.cpp:171,266-486 auto-detected fast load): persists the
fully-binned matrix, mappers and metadata so repeat training skips
parsing + bin finding — the direct ancestor of a TPU HBM-resident
packed-bin snapshot.

Format v2 (round 11, default): after the shared token, an 8-byte
magic + a pickled header (schema version, mappers, metadata, the
``group_bins`` shape) + the RAW packed bin matrix bytes.  ``load_binary``
``np.memmap``s that raw section read-only, so a reload is near
zero-copy — the OS pages bins in on first device upload and the
process RSS stays bounded by what training actually touches, instead
of a full unpickled duplicate of the matrix.  v1 files (one pickle
holding everything, written by ``binary_cache_v2=false`` or older
versions) still load, with a deprecation warning.
"""
from __future__ import annotations

import os
import pickle
import struct
import zlib
from typing import Optional

import numpy as np

from .dataset import Dataset
from .utils.log import Log

BINARY_TOKEN = b"______LightGBM_TPU_Binary_File_Token______\n"
MAGIC_V2 = b"LTPUBC2\n"
FORMAT_VERSION = 2
# v3 = v2 container + a ``bin_packing`` header field describing the
# nibble-packed storage layout (packing.py).  The version only bumps
# for datasets that ARE packed: 8-bit datasets keep writing plain v2
# (loadable by every prior build), while a packed cache read by an
# older build refuses on the unknown version instead of silently
# mis-binning packed bytes as group columns.
FORMAT_VERSION_PACKED = 3
# v4 = v3 + a ``crumb_groups`` field in the ``bin_packing`` layout
# state (the 2-bit crumb section, packing.py three-section layout).
# Same refusal shape one tier up: nibble-only packed caches keep
# writing v3 (loadable by every r18+ build), while a crumb-carrying
# cache read by a pre-crumb build refuses on the unknown version
# instead of silently mis-widening crumb bytes as nibble pairs.
FORMAT_VERSION_CRUMB = 4
# hard sanity bound on the v2 header blob (mappers + metadata for even
# a 10k-feature dataset pickle to a few MB; a length field past this is
# a corrupted or hostile file, not a real header)
_MAX_HEADER_BYTES = 1 << 31
# r20 trailing integrity footer: magic + crc32(header blob) +
# crc32(bin section).  The raw bin section is otherwise UNPROTECTED —
# a torn write or flipped page there would train silently wrong.  The
# footer trails the bins so the memmap offset of every existing v2/v3/
# v4 file is unchanged; pre-footer files load with a warning.
FOOTER_MAGIC = b"LTPUFTR1"
_FOOTER = struct.Struct("<II")
_FOOTER_LEN = len(FOOTER_MAGIC) + _FOOTER.size
_CRC_FOLD_MIN = 4096


def _section_crc(buf) -> int:
    """Digest of one cache section, tiered exactly like the wire-frame
    digest (parallel/transport.py): plain crc32 below the fold
    threshold, crc32 of the 64-bit XOR word-fold above it — the fold
    is memory-bandwidth-bound, so verifying a multi-GB bin matrix
    costs a streaming read, not a software-crc32 crawl."""
    mv = memoryview(buf).cast("B")
    n = len(mv)
    if n < _CRC_FOLD_MIN:
        return zlib.crc32(mv) & 0xFFFFFFFF
    nw = n & ~7
    words = np.frombuffer(mv[:nw], dtype="<u8")
    fold = int(np.bitwise_xor.reduce(words))
    crc = zlib.crc32(fold.to_bytes(8, "little"))
    return zlib.crc32(mv[nw:], crc) & 0xFFFFFFFF


def _verify_footer(f, filename: str, header_blob: bytes, gb) -> None:
    """Read + verify the trailing footer; ``f`` must be positioned at
    the first byte after the bin section.  Anything
    between "no trailing bytes at all" (a legacy pre-footer cache,
    loads with a warning) and "a well-formed footer whose digests
    match" is rejected loudly — a half-written footer IS a torn
    write."""
    tail = f.read()
    if not tail:
        Log.warning(
            f"{filename}: no integrity footer (pre-footer cache) — "
            "loading unverified; re-save to add section digests")
        return
    if (len(tail) != _FOOTER_LEN
            or tail[:len(FOOTER_MAGIC)] != FOOTER_MAGIC):
        Log.fatal(
            f"{filename}: corrupted v2 trailer ({len(tail)} trailing "
            "bytes after the bin section do not form an integrity "
            "footer — torn write?)")
    want_h, want_b = _FOOTER.unpack(tail[len(FOOTER_MAGIC):])
    got_h = _section_crc(header_blob)
    if got_h != want_h:
        Log.fatal(
            f"{filename}: v2 header digest mismatch (recorded "
            f"{want_h:#010x}, computed {got_h:#010x}) — the cache is "
            "corrupt; delete and rebuild it")
    got_b = _section_crc(gb) if gb is not None else 0
    if got_b != want_b:
        Log.fatal(
            f"{filename}: v2 bin-section digest mismatch (recorded "
            f"{want_b:#010x}, computed {got_b:#010x}) — the cache is "
            "corrupt; delete and rebuild it")

# Virtual file schemes (the reference's VirtualFileReader/Writer +
# HDFSFile seam, src/io/file_io.cpp:54-165).  HDFS itself is a
# PERMANENT descope — no Hadoop client exists in the target
# environments — but the dispatch seam is kept: register an opener for
# a scheme ("hdfs", "s3", "gs", ...) and every binary-cache read/write
# routes through it.  fsspec plugs in as
# ``register_file_scheme("s3", fsspec.open)``.
_SCHEME_OPENERS = {}


def register_file_scheme(scheme: str, opener) -> None:
    """``opener(path, mode)`` must return a binary file-like object."""
    _SCHEME_OPENERS[scheme.lower()] = opener


def _open(filename: str, mode: str):
    # fault seam: every binary-cache read/write opens through here —
    # injected IO errors exercise the loud-rejection paths without a
    # real disk failure (docs/RELIABILITY.md, seam registry)
    from .reliability.faults import FAULTS
    FAULTS.fault_point("dataset.cache_io")
    if "://" in filename:
        scheme = filename.split("://", 1)[0].lower()
        op = _SCHEME_OPENERS.get(scheme)
        if op is None:
            Log.fatal(
                f"no opener registered for scheme '{scheme}://' — "
                "register one with lightgbm_tpu.dataset_io."
                "register_file_scheme (HDFS is intentionally out of "
                "scope; any fsspec-style opener plugs in here)")
        return op(filename, mode)
    return open(filename, mode)


def _payload(dataset: Dataset, with_bins: bool) -> dict:
    """The pickled state shared by both format versions; v2 keeps the
    bin matrix OUT of the pickle (raw section instead)."""
    out = {
        "num_data": dataset.num_data,
        "num_total_features": dataset.num_total_features,
        "mappers": dataset.mappers,
        "used_features": dataset.used_features,
        "group_num_bin": dataset.group_num_bin,
        "group_is_multi": dataset.group_is_multi,
        "bundles": dataset._bundles,
        "feature_names": dataset.feature_names,
        "max_bin": dataset.max_bin,
        "label": dataset.metadata.label,
        "weight": dataset.metadata.weight,
        "query_boundaries": dataset.metadata.query_boundaries,
        "init_score": dataset.metadata.init_score,
        "monotone": dataset.monotone_constraints,
        "categorical_features": dataset._categorical_features,
    }
    if with_bins:
        out["group_bins"] = dataset.group_bins
    return out


def save_binary(dataset: Dataset, filename: str,
                version: Optional[int] = None) -> None:
    """Persist a constructed dataset.  ``version`` defaults to the
    dataset config's ``binary_cache_v2`` knob (v2 unless disabled)."""
    if version is None:
        version = 2 if getattr(dataset.config, "binary_cache_v2", True) \
            else 1
    if version == 1:
        if getattr(dataset, "bin_layout", None) is not None:
            # the v1 pickle has no layout field: a packed matrix would
            # reload as plain 8-bit group columns and silently mis-bin
            Log.fatal(
                f"{filename}: the v1 binary format cannot represent a "
                "nibble-packed bin matrix "
                f"({dataset.bin_layout!r}) — save with "
                "binary_cache_v2=true (the default) or construct with "
                "bin_packing=8bit")
        payload = dict(_payload(dataset, with_bins=True), version=1)
        with _open(filename, "wb") as f:
            f.write(BINARY_TOKEN)
            pickle.dump(payload, f, protocol=4)
        Log.info(f"Saved binned dataset to binary file {filename} (v1)")
        return
    lay = getattr(dataset, "bin_layout", None)
    header = dict(_payload(dataset, with_bins=False),
                  version=(FORMAT_VERSION if lay is None
                           else (FORMAT_VERSION_CRUMB
                                 if lay.crumb_groups
                                 else FORMAT_VERSION_PACKED)))
    if lay is not None:
        header["bin_packing"] = lay.to_state()
    gb = dataset.group_bins
    if gb is not None:
        gb = np.ascontiguousarray(gb, dtype=np.uint8)
        header["bins_shape"] = [int(s) for s in gb.shape]
    else:
        header["bins_shape"] = None
    blob = pickle.dumps(header, protocol=4)
    with _open(filename, "wb") as f:
        f.write(BINARY_TOKEN)
        f.write(MAGIC_V2)
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        if gb is not None:
            # raw bytes, no pickle framing: this section is what
            # load_binary memmaps in place
            f.write(memoryview(gb).cast("B"))
        f.write(FOOTER_MAGIC)
        f.write(_FOOTER.pack(_section_crc(blob),
                             _section_crc(gb) if gb is not None else 0))
    Log.info(f"Saved binned dataset to binary file {filename} "
             f"(v{header['version']})")


def is_binary_file(filename: str) -> bool:
    try:
        with _open(filename, "rb") as f:
            return f.read(len(BINARY_TOKEN)) == BINARY_TOKEN
    except Exception:
        # a probe, not an assertion: unreadable paths, unregistered
        # schemes, and opener-specific errors all mean "not a binary
        # dataset file" here
        return False


def _read_v2(f, filename: str):
    """Header + (memmapped when possible) bin matrix of a v2 file whose
    token+magic were already consumed.  Corrupted headers and truncated
    bin sections are rejected loudly — a half-written cache must never
    train silently wrong."""
    raw = f.read(8)
    if len(raw) < 8:
        Log.fatal(f"{filename}: truncated v2 binary dataset header")
    (blob_len,) = struct.unpack("<Q", raw)
    if blob_len > _MAX_HEADER_BYTES:
        Log.fatal(f"{filename}: corrupted v2 header (implausible "
                  f"header length {blob_len})")
    blob = f.read(blob_len)
    if len(blob) != blob_len:
        Log.fatal(f"{filename}: truncated v2 binary dataset header")
    try:
        payload = pickle.loads(blob)
    except Exception as e:
        Log.fatal(f"{filename}: corrupted v2 binary dataset header "
                  f"({type(e).__name__}: {e})")
    if payload.get("version") not in (FORMAT_VERSION,
                                      FORMAT_VERSION_PACKED,
                                      FORMAT_VERSION_CRUMB):
        Log.fatal(f"{filename}: unsupported binary dataset version "
                  f"{payload.get('version')!r}")
    shape = payload.get("bins_shape")
    offset = len(BINARY_TOKEN) + len(MAGIC_V2) + 8 + blob_len
    if shape is None:
        _verify_footer(f, filename, blob, None)
        return payload, None
    shape = tuple(int(s) for s in shape)
    need = int(np.prod(shape, dtype=np.int64))
    if "://" not in filename and os.path.isfile(filename):
        if os.path.getsize(filename) - offset < need:
            Log.fatal(f"{filename}: truncated v2 bin section (need "
                      f"{need} bytes)")
        # the zero-copy path: the packed matrix stays a read-only
        # page-cache mapping; RSS grows only with pages actually read
        # (the footer digest below streams it once through the page
        # cache — evictable, never an unpickled in-RSS duplicate)
        gb = np.memmap(filename, dtype=np.uint8, mode="r",
                       offset=offset, shape=shape)
        f.seek(offset + need)
    else:
        buf = f.read(need)
        if len(buf) != need:
            Log.fatal(f"{filename}: truncated v2 bin section (need "
                      f"{need} bytes)")
        gb = np.frombuffer(buf, dtype=np.uint8).reshape(shape)
    _verify_footer(f, filename, blob, gb)
    return payload, gb


def load_binary(filename: str, config=None) -> Dataset:
    """Load a binary dataset cache.  With ``config``, the run's
    resolved ``bin_packing`` is checked against the file's recorded
    layout.  A 4bit run refuses an unpacked cache loudly (4bit is
    never a default — if it resolved, the user asked for it).  A
    packed cache under an 8bit config loads WITH A WARNING and keeps
    its recorded layout: "8bit" is also the default, so a refusal
    would lock a default-params run out of the cache it just built —
    and no mis-binning is possible either way, because every consumer
    reads through the dataset's self-describing ``bin_layout`` (and a
    pre-packing build refuses the v3 version outright).  ``auto``
    accepts whatever layout the cache carries."""
    with _open(filename, "rb") as f:
        token = f.read(len(BINARY_TOKEN))
        if token != BINARY_TOKEN:
            Log.fatal(f"{filename} is not a lightgbm_tpu binary dataset")
        magic = f.read(len(MAGIC_V2))
        if magic == MAGIC_V2:
            payload, group_bins = _read_v2(f, filename)
            version = int(payload.get("version", 2))
        else:
            # v1: the bytes just read are the head of the pickle stream
            Log.warning(
                f"{filename} is a v1 (pickle-payload) binary dataset — "
                "loading works but costs a full in-RSS copy of the bin "
                "matrix; re-save it to get the memmap-able v2 format")
            try:
                payload = pickle.loads(magic + f.read())
            except Exception as e:
                Log.fatal(f"{filename}: corrupted v1 binary dataset "
                          f"({type(e).__name__}: {e})")
            if payload.get("version") != 1:
                Log.fatal("Unsupported binary dataset version "
                          f"{payload.get('version')!r}")
            group_bins = payload["group_bins"]
            version = 1
    ds = _restore_dataset(payload, group_bins)
    if config is not None:
        _check_packing(filename, ds, config)
    Log.info(f"Loaded binned dataset from binary file {filename} "
             f"(v{version})")
    return ds


def _check_packing(filename: str, ds: Dataset, config) -> None:
    """Loud layout/intent mismatch handling (see load_binary)."""
    from .packing import resolve_bin_packing
    want = resolve_bin_packing(config)
    lay = ds.bin_layout
    if want == "8bit" and lay is not None:
        Log.warning(
            f"{filename}: cache holds a nibble-packed bin matrix "
            f"({lay!r}); bin_packing=8bit applies to NEW "
            "constructions — the cached layout is kept as recorded "
            "(delete the file and re-save from an 8bit construction "
            "for an unpacked cache)")
    if want == "4bit" and lay is None:
        Log.fatal(
            f"{filename}: cache holds an 8-bit bin matrix but this "
            "run asked for bin_packing=4bit — the cached EFB group "
            "layout differs from a 4-bit construction; rebuild the "
            "cache under bin_packing=4bit (delete the file) or run "
            "with bin_packing=auto/8bit")
    if want == "2bit" and (lay is None or lay.crumb_groups == 0):
        Log.fatal(
            f"{filename}: cache holds "
            + ("an 8-bit" if lay is None else "a crumb-free packed")
            + " bin matrix but this run asked for bin_packing=2bit — "
            "the cached group layout differs from a 2-bit "
            "construction; rebuild the cache under bin_packing=2bit "
            "(delete the file) or run with bin_packing=auto/8bit")


def _restore_dataset(payload: dict, group_bins) -> Dataset:
    """Rebuild a Dataset from a cache payload (either version)."""
    from .binning import BIN_CATEGORICAL
    from .dataset import FeatureView, Metadata

    ds = Dataset.__new__(Dataset)
    Dataset.__init__(ds)
    from .packing import BinLayout
    ds.num_data = payload["num_data"]
    ds.num_total_features = payload["num_total_features"]
    ds.mappers = payload["mappers"]
    ds.used_features = payload["used_features"]
    ds.group_bins = group_bins
    # pre-packing caches carry no layout field -> 8-bit storage
    ds.bin_layout = BinLayout.from_state(payload.get("bin_packing"))
    ds.group_num_bin = payload["group_num_bin"]
    ds.group_is_multi = payload["group_is_multi"]
    ds._bundles = payload["bundles"]
    ds.feature_names = payload["feature_names"]
    ds.max_bin = payload["max_bin"]
    ds._categorical_features = payload["categorical_features"]
    ds.monotone_constraints = payload["monotone"]
    for m in ds.mappers:
        # categorical lookup cache: mappers pickled by an older version
        # lack the slot — rebuild now so per-chunk binning against a
        # reloaded cache never re-materializes the dict arrays
        if m.bin_type == BIN_CATEGORICAL \
                and getattr(m, "_cat_lut", None) is None:
            m._build_cat_cache()
    # rebuild FeatureView list from bundles + mappers
    feats = []
    for gidx, bundle in enumerate(ds._bundles):
        if len(bundle) == 1:
            fidx = bundle[0]
            feats.append(FeatureView(fidx, gidx, 0, 0, ds.mappers[fidx],
                                     collapsed_default=False))
        else:
            total = 1
            for sub, fidx in enumerate(bundle):
                m = ds.mappers[fidx]
                offset = total
                nb = m.num_bin - (1 if m.default_bin == 0 else 0)
                feats.append(FeatureView(fidx, gidx, sub, offset, m,
                                         collapsed_default=True))
                total += nb
    feats.sort(key=lambda f: f.feature_idx)
    ds.features = feats
    ds.metadata = Metadata(ds.num_data)
    ds.metadata.label = payload["label"]
    ds.metadata.weight = payload["weight"]
    ds.metadata.query_boundaries = payload["query_boundaries"]
    ds.metadata.init_score = payload["init_score"]
    return ds
