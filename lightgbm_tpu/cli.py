"""Command-line application: train / predict / convert_model / refit
/ serve.

The analog of the reference CLI driver (reference: src/main.cpp,
src/application/application.cpp:30-268 — param parsing with config
file + k=v args, task dispatch, data loading, prediction output file)
plus the online-serving entry point the reference never had:
``task=serve`` publishes ``input_model`` into a model registry
(buckets warmed before traffic) and serves ``POST /predict/<model>``
from the shared telemetry listener (docs/SERVING.md).

Usage:  python -m lightgbm_tpu config=train.conf [key=value ...]
"""
from __future__ import annotations

import sys
from typing import Dict, List

import numpy as np

from .basic import Dataset
from .booster import Booster
from .config import Config
from .engine import train as _train
from .utils.log import Log


def parse_args(argv: List[str]) -> Dict[str, str]:
    """CLI `k=v` pairs + config file contents, CLI wins
    (reference application.cpp:48-81)."""
    cli: Dict[str, str] = {}
    for tok in argv:
        if "=" in tok:
            k, v = tok.split("=", 1)
            cli[k.strip()] = v.strip()
    params: Dict[str, str] = {}
    cfg_file = cli.get("config", cli.get("config_file"))
    if cfg_file:
        with open(cfg_file) as f:
            for line in f:
                line = line.split("#", 1)[0].strip()
                if "=" in line:
                    k, v = line.split("=", 1)
                    params[k.strip()] = v.strip()
    params.update(cli)
    return params


def run(argv: List[str]) -> int:
    params = parse_args(argv)
    config = Config.from_params(params)
    Log.set_level(config.verbose)
    task = config.task
    if task == "train":
        _task_train(params, config)
    elif task in ("predict", "prediction", "test"):
        _task_predict(params, config)
    elif task == "convert_model":
        _task_convert(params, config)
    elif task == "refit":
        _task_refit(params, config)
    elif task == "serve":
        _task_serve(params, config)
    else:
        Log.fatal(f"Unknown task {task}")
    from .telemetry import TELEMETRY
    if TELEMETRY.on and config.telemetry_out:
        # explicit export at task end (the atexit hook is only the
        # safety net): telemetry=trace telemetry_out=/tmp/run writes
        # /tmp/run.jsonl + /tmp/run.perfetto.json (ui.perfetto.dev);
        # multi-host runs write per-host .host<i> shards — merge with
        # `python -m lightgbm_tpu.telemetry merge`
        paths = TELEMETRY.export(config.telemetry_out)
        Log.info("telemetry written: " + ", ".join(paths))
    if TELEMETRY.on and config.telemetry_prom_out:
        # Prometheus textfile (node-exporter textfile-collector
        # pattern): serving latency histograms + counters/gauges in
        # scrape format (docs/OBSERVABILITY.md, Prometheus export)
        Log.info("prometheus metrics written: "
                 + TELEMETRY.write_prom(config.telemetry_prom_out))
    return 0


def _task_train(params, config: Config) -> None:
    if not config.data:
        Log.fatal("No training data: set data=<file>")
    if config.num_machines > 1:
        # socket rendezvous config (reference application.cpp:87-105):
        # machines= inline list wins, machine_list_file= is the file
        # form; forwarded to the call-compat network surface
        machines = config.machines
        if not machines and config.machine_list_file:
            import os
            if not os.path.exists(config.machine_list_file):
                Log.fatal("machine_list_file not found: "
                          f"{config.machine_list_file}")
            with open(config.machine_list_file) as f:
                machines = ",".join(ln.strip() for ln in f
                                    if ln.strip())
        if machines:
            from .capi import LGBM_NetworkInit
            from .reliability.faults import FAULTS
            from .reliability.retry import RetryPolicy, retry_call

            def _net_init():
                FAULTS.fault_point("distributed.init")
                return LGBM_NetworkInit(machines,
                                        config.local_listen_port,
                                        config.time_out,
                                        config.num_machines)
            # transient rendezvous failures (peers still starting,
            # port in TIME_WAIT) retry with growing backoff for the
            # reference's time_out budget (minutes, the reference's
            # socket-timeout semantic) — the TIME budget governs the
            # rendezvous patience, not the dispatch retry count
            policy = RetryPolicy.from_config(config)
            policy.budget_s = config.time_out * 60.0
            retry_call(_net_init, seam="distributed.init",
                       policy=policy)
    if config.sharded_shards > 1:
        # mesh-sharded construction (docs/Parallel-Learning-Guide.md,
        # "Sharded construction"): Dataset.construct routes through
        # lightgbm_tpu/sharded/ — distributed bin finding, per-shard
        # streaming ingest, per-device placement over the mesh row
        # axis, optional shard-cache v2 under sharded_cache_dir
        Log.info(f"sharded construction armed: "
                 f"{config.sharded_shards} participant shard(s)"
                 + (f", cache {config.sharded_cache_dir}"
                    if config.sharded_cache_dir else ""))
    # input_model (continued training) seeds scores from raw data —
    # retain it in that case (reference CLI keeps data in memory too)
    train_set = Dataset(config.data, params=params,
                        free_raw_data=not config.input_model)
    if config.is_save_binary_file:
        # reference DatasetLoader::SaveBinaryFile writes the cache at
        # LOAD time, not after training: constructing once here reuses
        # the core for the training run below AND persists the
        # (memmap-able v2) cache even if a long run is interrupted —
        # the next invocation short-circuits straight to load_binary
        train_set.save_binary(config.data + ".bin")
        Log.info(f"Saved binned dataset to {config.data}.bin")
    valid_sets = []
    valid_names = []
    for i, vf in enumerate(config.valid_data):
        valid_sets.append(Dataset(vf, reference=train_set, params=params))
        valid_names.append(f"valid_{i}" if len(config.valid_data) > 1
                           else "valid_1")
    booster = _train(params, train_set, config.num_iterations,
                     valid_sets=valid_sets, valid_names=valid_names,
                     init_model=config.input_model or None)
    booster.save_model(config.output_model)
    Log.info(f"Finished training; model saved to {config.output_model}")


def _task_predict(params, config: Config) -> None:
    if not config.input_model:
        Log.fatal("No model file: set input_model=<file>")
    # the parsed config rides along so CLI predict knobs
    # (predict_kernel, predict_bucket, predict_chunk_rows, ...) reach
    # the serving predictor
    booster = Booster(config=config, model_file=config.input_model)
    if config.predict_warm_buckets:
        # deploy-script warm-up without the Python API: pre-compile
        # the declared serving buckets (and log each bucket's warm
        # compile wall) before the first real prediction
        booster.warm_predictor(config.predict_warm_buckets, log=True)
    from .data_loader import load_file
    X, _, _ = load_file(config.data, config)
    pred = booster.predict(
        X,
        num_iteration=config.num_iteration_predict,
        raw_score=config.is_predict_raw_score,
        pred_leaf=config.is_predict_leaf_index,
        pred_contrib=config.is_predict_contrib,
        pred_early_stop=config.pred_early_stop,
        pred_early_stop_freq=config.pred_early_stop_freq,
        pred_early_stop_margin=config.pred_early_stop_margin)
    out = np.atleast_2d(np.asarray(pred))
    if out.shape[0] == 1 and X.shape[0] != 1:
        out = out.T
    with open(config.output_result, "w") as f:
        for row in (out if out.ndim > 1 else out[:, None]):
            f.write("\t".join(f"{v:g}" for v in np.atleast_1d(row)) + "\n")
    Log.info(f"Finished prediction; results saved to "
             f"{config.output_result}")


def _task_convert(params, config: Config) -> None:
    if not config.input_model:
        Log.fatal("No model file: set input_model=<file>")
    if config.convert_model_language not in ("", "cpp"):
        Log.fatal("Only cpp is supported for convert_model_language")
    booster = Booster(model_file=config.input_model)
    from .codegen import model_to_ifelse_cpp
    code = model_to_ifelse_cpp(booster)
    with open(config.convert_model, "w") as f:
        f.write(code)
    Log.info(f"Finished converting model to if-else code at "
             f"{config.convert_model}")


def _task_serve(params, config: Config) -> None:
    """Online serving (docs/SERVING.md): publish input_model into a
    registry (warming its buckets first — predict_warm_buckets, or
    the 1-row + serve_max_batch_rows defaults), then serve
    POST /predict/<model> with micro-batching and load shedding from
    the shared /metrics + /healthz listener until interrupted.

    With ``continuous_ingest_dir`` set, the continuous-training lane
    (docs/CONTINUOUS_TRAINING.md) runs BESIDE the frontend: new data
    slices dropped into the directory are append-constructed against
    the base dataset (``data=``), trained from the last good model
    (``continuous_mode=continue|refit``), eval-gated and hot-published
    into the SAME registry this frontend serves from — control it via
    GET/POST /continuous on the shared listener."""
    if not config.input_model:
        Log.fatal("No model file: set input_model=<file>")
    import os
    import signal
    import threading

    from .serving import ModelRegistry, ServingFrontend
    # graceful SIGTERM drain (docs/RELIABILITY.md): the orchestrator's
    # polite shutdown (kubectl delete, systemd stop) must not look
    # like a crash — on SIGTERM the process stops admission (routes
    # unmounted), drains every in-flight coalesced batch, lets the
    # continuous lane finish its phase (the ledger commit is the
    # phase boundary), and exits 0.  Only SIGKILL is a crash, and the
    # r12 checkpoint/ledger machinery owns that path.  Installed
    # BEFORE the first publish so a shutdown during warm-up is
    # graceful too.
    stop = threading.Event()

    def _on_sigterm(signum, frame):
        Log.info("SIGTERM: stopping admission and draining in-flight "
                 "work (serving queues + continuous lane)")
        stop.set()

    prev_term = signal.signal(signal.SIGTERM, _on_sigterm)
    name = os.path.splitext(
        os.path.basename(config.input_model))[0] or "model"
    registry = ModelRegistry(config)
    entry = registry.publish(name, config.input_model, log_warm=True)
    frontend = ServingFrontend(registry, config)
    srv = frontend.start()
    port = srv.server_address[1]
    Log.info(f"serving model {name!r} at "
             f"http://127.0.0.1:{port}/predict/{name} "
             '(POST JSON {"rows": [[...]]} or CSV rows, or binary '
             "application/x-ltpu-f32; GET /models /metrics /healthz)")
    if registry.pool is not None:
        Log.info(f"lane fleet: {registry.pool.n_lanes} dispatch "
                 f"lanes (serve_lanes={config.serve_lanes}); per-lane "
                 "state on GET /models under '_fleet'")
    if entry.monitor is not None:
        # model-quality drift monitors (docs/MODEL_MONITORING.md):
        # armed from the <input_model>.quality.json sidecar a
        # quality=on training run saved beside the model
        Log.info(f"quality monitors armed for {name!r}: sample "
                 f"stride {entry.monitor.stride}, drift report at "
                 f"http://127.0.0.1:{port}/quality/{name} "
                 f"(ltpu_quality_* gauges on /metrics)")
    lane = None
    if config.continuous_ingest_dir:
        if not config.data:
            Log.fatal("continuous_ingest_dir is set but data= is not: "
                      "the lane needs the base dataset whose bin "
                      "mappers ingested slices bind to")
        from .continuous import ContinuousLane
        lane = ContinuousLane(config, registry, name=name,
                              train_params=dict(params)).start()
        Log.info(f"continuous-training lane armed: watching "
                 f"{config.continuous_ingest_dir} "
                 f"(mode={config.continuous_mode}, poll "
                 f"{config.continuous_poll_s:g}s; GET/POST "
                 f"http://127.0.0.1:{port}/continuous)")
    try:
        stop.wait()                   # serve until SIGTERM or SIGINT
    except KeyboardInterrupt:
        Log.info("interrupt: draining serving queues")
    finally:
        if prev_term is not None:
            # None = the previous disposition was installed outside
            # Python (embedding host); signal.signal(None) would raise
            signal.signal(signal.SIGTERM, prev_term)
        if lane is not None:
            lane.stop()
        frontend.stop(drain=True)
        Log.info("serving drained cleanly; exiting 0")


def _task_refit(params, config: Config) -> None:
    if not config.input_model:
        Log.fatal("No model file: set input_model=<file>")
    # the parsed config rides along (like task=predict) so predict
    # knobs reach the pred_leaf pass and the telemetry/export knobs
    # configured on the command line govern the refit run too
    booster = Booster(config=config, model_file=config.input_model)
    from .data_loader import load_file
    X, label, _ = load_file(config.data, config)
    booster.refit(X, label, params)
    booster.save_model(config.output_model)
    Log.info(f"Finished refitting; model saved to {config.output_model}")


def main() -> int:
    return run(sys.argv[1:])


if __name__ == "__main__":
    sys.exit(main())
