"""lightgbm_tpu: a TPU-native gradient boosting framework.

A from-scratch re-design of LightGBM (v2.1.1 feature surface) for
JAX/XLA on TPU: HBM-resident packed bin matrix, MXU one-hot-matmul
histograms, fully-jitted leaf-wise tree growth, XLA-collective
distributed training.  User API mirrors the reference python package
(lgb.train / Dataset / Booster / sklearn wrappers).
"""
from .basic import Dataset, Booster
from .config import Config
from .engine import train, cv
from .utils.log import Log, LightGBMError

__version__ = "0.1.0"

__all__ = ["Dataset", "Booster", "Config", "train", "cv", "Log",
           "LightGBMError", "__version__"]
