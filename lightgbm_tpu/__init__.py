"""lightgbm_tpu: a TPU-native gradient boosting framework.

A from-scratch re-design of LightGBM (v2.1.1 feature surface) for
JAX/XLA on TPU: HBM-resident packed bin matrix, MXU one-hot-matmul
histograms, fully-jitted leaf-wise tree growth, XLA-collective
distributed training.  User API mirrors the reference python package
(lgb.train / Dataset / Booster / sklearn wrappers).
"""
from .basic import Dataset, Booster
from .config import Config
from .engine import train, cv, CVBooster
from .utils.log import Log, LightGBMError
from .callback import (early_stopping, print_evaluation, record_evaluation,
                       reset_parameter, telemetry_snapshot)
from . import telemetry
from .telemetry import TELEMETRY
from .sklearn import LGBMModel, LGBMRegressor, LGBMClassifier, LGBMRanker
from . import plotting
from .plotting import (plot_importance, plot_metric, plot_tree,
                       create_tree_digraph)

__version__ = "0.1.0"

__all__ = ["Dataset", "Booster", "Config", "train", "cv", "CVBooster", "Log",
           "LightGBMError", "early_stopping", "print_evaluation",
           "record_evaluation", "reset_parameter", "telemetry_snapshot",
           "telemetry", "TELEMETRY", "LGBMModel",
           "LGBMRegressor", "LGBMClassifier", "LGBMRanker",
           "plot_importance", "plot_metric", "plot_tree",
           "create_tree_digraph", "__version__"]
