/*
 * Native C API over an embedded CPython running the lightgbm_tpu core.
 *
 * The reference's C API (reference: src/c_api.cpp) wraps a C++ core for
 * Python/R/Java callers; here the core IS Python (JAX programs), so the
 * native library embeds the interpreter and forwards the same flat
 * function surface down to lightgbm_tpu.capi.  Marshalling crosses the
 * boundary once per call with numpy arrays built over the caller's
 * buffers (copied at construction, matching the reference's
 * copy-on-create semantics for CreateFromMat).
 */
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "../../include/lightgbm_tpu_c_api.h"

namespace {

thread_local std::string g_last_error;  // per-thread, like the reference
std::vector<std::string> g_extra_paths;
std::mutex g_path_mutex;
std::mutex g_init_mutex;
PyObject* g_capi = nullptr;        // lightgbm_tpu.capi module
PyObject* g_np = nullptr;          // numpy module
bool g_we_initialized = false;
// last GetField result per dataset handle: keeps the buffer alive until
// the next call (mirrors the reference returning internal pointers)
std::map<intptr_t, PyObject*> g_field_cache;

void set_error_from_python() {
  PyObject *ptype = nullptr, *pvalue = nullptr, *ptb = nullptr;
  PyErr_Fetch(&ptype, &pvalue, &ptb);
  PyErr_NormalizeException(&ptype, &pvalue, &ptb);
  g_last_error = "python error";
  if (pvalue != nullptr) {
    PyObject* s = PyObject_Str(pvalue);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(ptype);
  Py_XDECREF(pvalue);
  Py_XDECREF(ptb);
}

// One-time interpreter + module setup.  Returns 0 on success.  Caller
// must NOT hold the GIL.  After a successful first init by this
// library, the GIL is released so any host thread can enter.
int ensure_init_locked() {
  if (g_capi != nullptr) return 0;
  std::lock_guard<std::mutex> init_lk(g_init_mutex);
  if (g_capi != nullptr) return 0;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // no signal handlers: we are a guest
    g_we_initialized = true;
  }
  PyGILState_STATE st = PyGILState_Ensure();
  int rc = -1;
  {
    std::lock_guard<std::mutex> lk(g_path_mutex);
    if (!g_extra_paths.empty()) {
      PyObject* sys_path = PySys_GetObject("path");  // borrowed
      for (const std::string& p : g_extra_paths) {
        PyObject* str = PyUnicode_FromString(p.c_str());
        if (str != nullptr && sys_path != nullptr) {
          PyList_Append(sys_path, str);
        }
        Py_XDECREF(str);
      }
      g_extra_paths.clear();
    }
  }
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* mod = np ? PyImport_ImportModule("lightgbm_tpu.capi") : nullptr;
  if (mod != nullptr) {
    g_np = np;
    g_capi = mod;
    rc = 0;
  } else {
    set_error_from_python();
    Py_XDECREF(np);
  }
  PyGILState_Release(st);
  if (g_we_initialized) {
    // drop the GIL held by the initializing thread since
    // Py_InitializeEx, so later PyGILState_Ensure calls (from any
    // thread, including this one — e.g. a retry after a failed import)
    // can take it.  Must happen on failure too, else a bad first init
    // deadlocks every subsequent call.
    static PyThreadState* saved = nullptr;
    if (saved == nullptr && PyGILState_Check()) saved = PyEval_SaveThread();
  }
  return rc;
}

// RAII GIL scope used by every API entry point.
class GilScope {
 public:
  GilScope() : state_(PyGILState_Ensure()) {}
  ~GilScope() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

const char* dtype_name(int data_type) {
  switch (data_type) {
    case C_API_DTYPE_FLOAT32: return "float32";
    case C_API_DTYPE_FLOAT64: return "float64";
    case C_API_DTYPE_INT32: return "int32";
    case C_API_DTYPE_INT64: return "int64";
    default: return nullptr;
  }
}

size_t dtype_size(int data_type) {
  switch (data_type) {
    case C_API_DTYPE_FLOAT32: return 4;
    case C_API_DTYPE_FLOAT64: return 8;
    case C_API_DTYPE_INT32: return 4;
    case C_API_DTYPE_INT64: return 8;
    default: return 0;
  }
}

// numpy array copied from a C buffer: np.frombuffer(mv, dtype).copy(),
// optionally reshaped (nrow, ncol) with Fortran order for column-major.
PyObject* array_from_buffer(const void* data, int data_type, int64_t nelem,
                            int64_t nrow = -1, int64_t ncol = -1,
                            int is_row_major = 1) {
  const char* dt = dtype_name(data_type);
  if (dt == nullptr) {
    g_last_error = "unknown data_type";
    return nullptr;
  }
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<void*>(data)),
      static_cast<Py_ssize_t>(nelem * dtype_size(data_type)), PyBUF_READ);
  if (mv == nullptr) { set_error_from_python(); return nullptr; }
  PyObject* flat = PyObject_CallMethod(g_np, "frombuffer", "Os", mv, dt);
  Py_DECREF(mv);
  if (flat == nullptr) { set_error_from_python(); return nullptr; }
  PyObject* arr = nullptr;
  if (nrow >= 0) {
    // row-major: reshape (nrow, ncol); col-major: reshape (ncol, nrow)
    // then transpose — both then copied to fresh owned memory
    PyObject* shaped = PyObject_CallMethod(
        flat, "reshape", "(LL)",
        static_cast<long long>(is_row_major ? nrow : ncol),
        static_cast<long long>(is_row_major ? ncol : nrow));
    Py_DECREF(flat);
    if (shaped == nullptr) { set_error_from_python(); return nullptr; }
    PyObject* oriented = shaped;
    if (!is_row_major) {
      oriented = PyObject_GetAttrString(shaped, "T");
      Py_DECREF(shaped);
      if (oriented == nullptr) { set_error_from_python(); return nullptr; }
    }
    arr = PyObject_CallMethod(oriented, "copy", nullptr);
    Py_DECREF(oriented);
  } else {
    arr = PyObject_CallMethod(flat, "copy", nullptr);
    Py_DECREF(flat);
  }
  if (arr == nullptr) set_error_from_python();
  return arr;
}

// Call g_capi.<name>(*args).  Returns new ref or nullptr (error set).
PyObject* call_capi(const char* name, PyObject* args) {
  PyObject* fn = PyObject_GetAttrString(g_capi, name);
  if (fn == nullptr) { set_error_from_python(); Py_XDECREF(args); return nullptr; }
  PyObject* res = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (res == nullptr) set_error_from_python();
  return res;
}

// The Python capi functions return 0/-1 and fill a one-element list
// "out".  This helper runs one and extracts out[0] as a new reference.
// Returns 0 on success.
int call_with_out(const char* name, PyObject* args_tuple_without_out,
                  PyObject** out_obj) {
  PyObject* out_list = PyList_New(1);
  Py_INCREF(Py_None);
  PyList_SetItem(out_list, 0, Py_None);
  Py_ssize_t n = PyTuple_Size(args_tuple_without_out);
  PyObject* args = PyTuple_New(n + 1);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* item = PyTuple_GetItem(args_tuple_without_out, i);
    Py_INCREF(item);
    PyTuple_SetItem(args, i, item);
  }
  PyTuple_SetItem(args, n, out_list);  // steals out_list
  Py_DECREF(args_tuple_without_out);
  Py_INCREF(out_list);                 // keep alive to read after call
  PyObject* res = call_capi(name, args);
  int rc = -1;
  if (res != nullptr) {
    rc = static_cast<int>(PyLong_AsLong(res));
    Py_DECREF(res);
  }
  if (rc == 0 && out_obj != nullptr) {
    *out_obj = PyList_GetItem(out_list, 0);
    Py_XINCREF(*out_obj);
  }
  if (rc != 0) {
    // Python-side _api decorator stashed the message; surface it
    PyObject* err = call_capi("LGBM_GetLastError", PyTuple_New(0));
    if (err != nullptr) {
      const char* c = PyUnicode_AsUTF8(err);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(err);
    }
  }
  Py_DECREF(out_list);
  return rc;
}

// Plain int-returning capi call (no out param).
int call_simple(const char* name, PyObject* args) {
  PyObject* res = call_capi(name, args);
  if (res == nullptr) return -1;
  int rc = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  if (rc != 0) {
    PyObject* err = call_capi("LGBM_GetLastError", PyTuple_New(0));
    if (err != nullptr) {
      const char* c = PyUnicode_AsUTF8(err);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(err);
    }
  }
  return rc;
}

intptr_t handle_int(const void* h) {
  return reinterpret_cast<intptr_t>(h);
}

// Copy a numpy array (any dtype) into a double* buffer.
int copy_to_doubles(PyObject* arr, double* out, int64_t* out_len) {
  PyObject* flat = PyObject_CallMethod(g_np, "ascontiguousarray", "Os",
                                       arr, "float64");
  if (flat == nullptr) { set_error_from_python(); return -1; }
  PyObject* rav = PyObject_CallMethod(flat, "ravel", nullptr);
  Py_DECREF(flat);
  if (rav == nullptr) { set_error_from_python(); return -1; }
  Py_buffer view;
  if (PyObject_GetBuffer(rav, &view, PyBUF_CONTIG_RO) != 0) {
    set_error_from_python();
    Py_DECREF(rav);
    return -1;
  }
  int64_t n = static_cast<int64_t>(view.len / sizeof(double));
  if (out != nullptr) std::memcpy(out, view.buf, view.len);
  if (out_len != nullptr) *out_len = n;
  PyBuffer_Release(&view);
  Py_DECREF(rav);
  return 0;
}

int copy_string_out(PyObject* str, int64_t buffer_len, int64_t* out_len,
                    char* out_str) {
  Py_ssize_t n = 0;
  const char* c = PyUnicode_AsUTF8AndSize(str, &n);
  if (c == nullptr) { set_error_from_python(); return -1; }
  if (out_len != nullptr) *out_len = static_cast<int64_t>(n) + 1;
  if (out_str != nullptr && buffer_len > 0) {
    int64_t ncopy = (static_cast<int64_t>(n) + 1 < buffer_len)
                        ? static_cast<int64_t>(n) + 1 : buffer_len;
    std::memcpy(out_str, c, static_cast<size_t>(ncopy));
    out_str[ncopy - 1] = '\0';
  }
  return 0;
}

// Writable float64 numpy view over a caller buffer (no copy) — for
// out_result parameters the Python side fills by slice assignment.
PyObject* writable_f64(double* buf, int64_t nelem) {
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(buf),
      static_cast<Py_ssize_t>(nelem * sizeof(double)), PyBUF_WRITE);
  if (mv == nullptr) { set_error_from_python(); return nullptr; }
  PyObject* arr = PyObject_CallMethod(g_np, "frombuffer", "Os", mv,
                                      "float64");
  Py_DECREF(mv);
  if (arr == nullptr) set_error_from_python();
  return arr;
}

// Copy a Python sequence of strings into caller char* buffers (>= 256
// bytes each) — the Get*Names output convention.  A name that does
// not fit is an ERROR, never a silent truncation (a truncated name
// would corrupt any name-keyed lookup downstream).
int copy_names_out(PyObject* seq, int* out_len, char** out_strs) {
  Py_ssize_t n = PySequence_Size(seq);
  if (n < 0) { set_error_from_python(); return -1; }
  if (out_len != nullptr) *out_len = static_cast<int>(n);
  if (out_strs != nullptr) {
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* item = PySequence_GetItem(seq, i);
      const char* c = item ? PyUnicode_AsUTF8(item) : nullptr;
      if (c == nullptr) {
        set_error_from_python();
        Py_XDECREF(item);
        return -1;
      }
      if (std::strlen(c) >= 256) {
        g_last_error = "name longer than the 256-byte Get*Names "
                       "buffer convention: " + std::string(c, 64);
        Py_DECREF(item);
        return -1;
      }
      std::strcpy(out_strs[i], c);
      Py_DECREF(item);
    }
  }
  return 0;
}

// numpy (indptr, indices, data) triple from reference-style CSR/CSC
// buffers.  Fills three new references; returns 0 on success.
int csx_arrays(const void* indptr, int indptr_type, const int32_t* indices,
               const void* data, int data_type, int64_t nindptr,
               int64_t nelem, PyObject** out_indptr, PyObject** out_indices,
               PyObject** out_data) {
  if (indptr_type != C_API_DTYPE_INT32 && indptr_type != C_API_DTYPE_INT64) {
    g_last_error = "indptr_type must be int32 or int64";
    return -1;
  }
  PyObject* p = array_from_buffer(indptr, indptr_type, nindptr);
  if (p == nullptr) return -1;
  PyObject* ix = array_from_buffer(indices, C_API_DTYPE_INT32, nelem);
  if (ix == nullptr) { Py_DECREF(p); return -1; }
  PyObject* d = array_from_buffer(data, data_type, nelem);
  if (d == nullptr) { Py_DECREF(p); Py_DECREF(ix); return -1; }
  *out_indptr = p;
  *out_indices = ix;
  *out_data = d;
  return 0;
}

#define LTPU_ENTER()                      \
  if (ensure_init_locked() != 0) return -1; \
  GilScope gil_scope__

}  // namespace

extern "C" {

int LTPU_AddSysPath(const char* path) {
  if (path == nullptr) return -1;
  std::lock_guard<std::mutex> lk(g_path_mutex);
  g_extra_paths.emplace_back(path);
  return 0;
}

int LTPU_EnsureInitialized(void) { return ensure_init_locked(); }

const char* LGBM_GetLastError(void) {
  return g_last_error.c_str();
}

/* -------------------------------------------------------- Dataset */

int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out) {
  LTPU_ENTER();
  PyObject* ref = reference ? PyLong_FromSsize_t(handle_int(reference))
                            : (Py_INCREF(Py_None), Py_None);
  PyObject* args = Py_BuildValue("(ssN)", filename,
                                 parameters ? parameters : "", ref);
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_DatasetCreateFromFile", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<DatasetHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
  }
  return rc;
}

int LGBM_DatasetCreateFromMat(const void* data, int data_type, int32_t nrow,
                              int32_t ncol, int is_row_major,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  LTPU_ENTER();
  PyObject* arr = array_from_buffer(data, data_type,
                                    static_cast<int64_t>(nrow) * ncol,
                                    nrow, ncol, is_row_major);
  if (arr == nullptr) return -1;
  PyObject* ref = reference ? PyLong_FromSsize_t(handle_int(reference))
                            : (Py_INCREF(Py_None), Py_None);
  PyObject* args = Py_BuildValue("(NsN)", arr,
                                 parameters ? parameters : "", ref);
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_DatasetCreateFromMat", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<DatasetHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
  }
  return rc;
}

int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element, int type) {
  LTPU_ENTER();
  PyObject* arr = array_from_buffer(field_data, type, num_element);
  if (arr == nullptr) return -1;
  PyObject* args = Py_BuildValue("(nsN)", handle_int(handle), field_name,
                                 arr);
  return call_simple("LGBM_DatasetSetField", args);
}

int LGBM_DatasetGetField(DatasetHandle handle, const char* field_name,
                         int* out_len, const void** out_ptr, int* out_type) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(ns)", handle_int(handle), field_name);
  PyObject* arr = nullptr;
  int rc = call_with_out("LGBM_DatasetGetField", args, &arr);
  if (rc != 0) return rc;
  if (arr == nullptr || arr == Py_None) {
    g_last_error = "field not set";
    Py_XDECREF(arr);
    return -1;
  }
  // normalize to a contiguous owned array and cache it per handle
  PyObject* contig = PyObject_CallMethod(g_np, "ascontiguousarray", "O",
                                         arr);
  Py_DECREF(arr);
  if (contig == nullptr) { set_error_from_python(); return -1; }
  Py_buffer view;
  // PyBUF_FORMAT is required or view.format stays NULL and float32
  // fields misdetect as int32 (their bits then read as ~1.07e9)
  if (PyObject_GetBuffer(contig, &view,
                         PyBUF_CONTIG_RO | PyBUF_FORMAT) != 0) {
    set_error_from_python();
    Py_DECREF(contig);
    return -1;
  }
  int dtype = -1;
  size_t item = static_cast<size_t>(view.itemsize);
  const char* fmt = view.format ? view.format : "";
  if (std::strcmp(fmt, "f") == 0) dtype = C_API_DTYPE_FLOAT32;
  else if (std::strcmp(fmt, "d") == 0) dtype = C_API_DTYPE_FLOAT64;
  else if (item == 4) dtype = C_API_DTYPE_INT32;
  else if (item == 8) dtype = C_API_DTYPE_INT64;
  if (out_ptr != nullptr) *out_ptr = view.buf;
  if (out_len != nullptr) {
    *out_len = static_cast<int>(view.len / (item ? item : 1));
  }
  if (out_type != nullptr) *out_type = dtype;
  PyBuffer_Release(&view);  // buffer memory owned by `contig`, cached below
  intptr_t key = handle_int(handle);
  auto it = g_field_cache.find(key);
  if (it != g_field_cache.end()) Py_DECREF(it->second);
  g_field_cache[key] = contig;
  return 0;
}

int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_DatasetGetNumData", args, &v);
  if (rc == 0) { *out = static_cast<int32_t>(PyLong_AsLong(v)); Py_DECREF(v); }
  return rc;
}

int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_DatasetGetNumFeature", args, &v);
  if (rc == 0) { *out = static_cast<int32_t>(PyLong_AsLong(v)); Py_DECREF(v); }
  return rc;
}

int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(ns)", handle_int(handle), filename);
  return call_simple("LGBM_DatasetSaveBinary", args);
}

int LGBM_DatasetFree(DatasetHandle handle) {
  LTPU_ENTER();
  intptr_t key = handle_int(handle);
  auto it = g_field_cache.find(key);
  if (it != g_field_cache.end()) {
    Py_DECREF(it->second);
    g_field_cache.erase(it);
  }
  PyObject* args = Py_BuildValue("(n)", key);
  return call_simple("LGBM_DatasetFree", args);
}

/* -------------------------------------------------------- Booster */

int LGBM_BoosterCreate(const DatasetHandle train_data,
                       const char* parameters, BoosterHandle* out) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(ns)", handle_int(train_data),
                                 parameters ? parameters : "");
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_BoosterCreate", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<BoosterHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
  }
  return rc;
}

int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  LTPU_ENTER();
  PyObject* iters = PyList_New(1);
  Py_INCREF(Py_None);
  PyList_SetItem(iters, 0, Py_None);
  PyObject* args = Py_BuildValue("(sO)", filename, iters);
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_BoosterCreateFromModelfile", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<BoosterHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
    if (out_num_iterations != nullptr) {
      PyObject* it0 = PyList_GetItem(iters, 0);
      *out_num_iterations =
          (it0 != Py_None) ? static_cast<int>(PyLong_AsLong(it0)) : 0;
    }
  }
  Py_DECREF(iters);
  return rc;
}

int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out) {
  LTPU_ENTER();
  PyObject* iters = PyList_New(1);
  Py_INCREF(Py_None);
  PyList_SetItem(iters, 0, Py_None);
  PyObject* args = Py_BuildValue("(sO)", model_str, iters);
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_BoosterLoadModelFromString", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<BoosterHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
    if (out_num_iterations != nullptr) {
      PyObject* it0 = PyList_GetItem(iters, 0);
      *out_num_iterations =
          (it0 != Py_None) ? static_cast<int>(PyLong_AsLong(it0)) : 0;
    }
  }
  Py_DECREF(iters);
  return rc;
}

int LGBM_BoosterFree(BoosterHandle handle) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  return call_simple("LGBM_BoosterFree", args);
}

int LGBM_BoosterAddValidData(BoosterHandle handle,
                             const DatasetHandle valid_data) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(nn)", handle_int(handle),
                                 handle_int(valid_data));
  return call_simple("LGBM_BoosterAddValidData", args);
}

int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_BoosterGetNumClasses", args, &v);
  if (rc == 0) { *out_len = static_cast<int>(PyLong_AsLong(v)); Py_DECREF(v); }
  return rc;
}

int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished) {
  LTPU_ENTER();
  PyObject* fin = PyList_New(1);
  Py_INCREF(Py_None);
  PyList_SetItem(fin, 0, Py_None);
  PyObject* args = Py_BuildValue("(nO)", handle_int(handle), fin);
  int rc = call_simple("LGBM_BoosterUpdateOneIter", args);
  if (rc == 0 && is_finished != nullptr) {
    PyObject* f0 = PyList_GetItem(fin, 0);
    *is_finished = (f0 != Py_None) ? static_cast<int>(PyLong_AsLong(f0)) : 0;
  }
  Py_DECREF(fin);
  return rc;
}

int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle, const float* grad,
                                    const float* hess, int64_t num_elements,
                                    int* is_finished) {
  LTPU_ENTER();
  PyObject* g = array_from_buffer(grad, C_API_DTYPE_FLOAT32, num_elements);
  if (g == nullptr) return -1;
  PyObject* h = array_from_buffer(hess, C_API_DTYPE_FLOAT32, num_elements);
  if (h == nullptr) { Py_DECREF(g); return -1; }
  PyObject* fin = PyList_New(1);
  Py_INCREF(Py_None);
  PyList_SetItem(fin, 0, Py_None);
  PyObject* args = Py_BuildValue("(nNNO)", handle_int(handle), g, h, fin);
  int rc = call_simple("LGBM_BoosterUpdateOneIterCustom", args);
  if (rc == 0 && is_finished != nullptr) {
    PyObject* f0 = PyList_GetItem(fin, 0);
    *is_finished = (f0 != Py_None) ? static_cast<int>(PyLong_AsLong(f0)) : 0;
  }
  Py_DECREF(fin);
  return rc;
}

int LGBM_BoosterRollbackOneIter(BoosterHandle handle) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  return call_simple("LGBM_BoosterRollbackOneIter", args);
}

int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                    int* out_iteration) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_BoosterGetCurrentIteration", args, &v);
  if (rc == 0) {
    *out_iteration = static_cast<int>(PyLong_AsLong(v));
    Py_DECREF(v);
  }
  return rc;
}

int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_BoosterGetEvalCounts", args, &v);
  if (rc == 0) { *out_len = static_cast<int>(PyLong_AsLong(v)); Py_DECREF(v); }
  return rc;
}

int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(ni)", handle_int(handle), data_idx);
  PyObject* vals = nullptr;
  int rc = call_with_out("LGBM_BoosterGetEval", args, &vals);
  if (rc != 0) return rc;
  Py_ssize_t n = PySequence_Size(vals);
  if (out_len != nullptr) *out_len = static_cast<int>(n);
  if (out_results != nullptr) {
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* item = PySequence_GetItem(vals, i);
      out_results[i] = PyFloat_AsDouble(item);
      Py_XDECREF(item);
    }
  }
  Py_DECREF(vals);
  return 0;
}

int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result) {
  LTPU_ENTER();
  (void)parameter;  // reserved (the reference parses extra params here)
  PyObject* arr = array_from_buffer(data, data_type,
                                    static_cast<int64_t>(nrow) * ncol,
                                    nrow, ncol, is_row_major);
  if (arr == nullptr) return -1;
  PyObject* args = Py_BuildValue("(nNii)", handle_int(handle), arr,
                                 predict_type, num_iteration);
  PyObject* pred = nullptr;
  int rc = call_with_out("LGBM_BoosterPredictForMat", args, &pred);
  if (rc != 0) return rc;
  rc = copy_to_doubles(pred, out_result, out_len);
  Py_DECREF(pred);
  return rc;
}

int LGBM_BoosterSaveModel(BoosterHandle handle, int num_iteration,
                          const char* filename) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(nis)", handle_int(handle), num_iteration,
                                 filename);
  return call_simple("LGBM_BoosterSaveModel", args);
}

int LGBM_BoosterSaveModelToString(BoosterHandle handle, int num_iteration,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(ni)", handle_int(handle), num_iteration);
  PyObject* s = nullptr;
  int rc = call_with_out("LGBM_BoosterSaveModelToString", args, &s);
  if (rc != 0) return rc;
  rc = copy_string_out(s, buffer_len, out_len, out_str);
  Py_DECREF(s);
  return rc;
}

int LGBM_BoosterDumpModel(BoosterHandle handle, int num_iteration,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(ni)", handle_int(handle), num_iteration);
  PyObject* d = nullptr;
  int rc = call_with_out("LGBM_BoosterDumpModel", args, &d);
  if (rc != 0) return rc;
  // dump_model returns a dict; serialize to JSON text for the C caller
  PyObject* json_mod = PyImport_ImportModule("json");
  if (json_mod == nullptr) { set_error_from_python(); Py_DECREF(d); return -1; }
  PyObject* s = PyObject_CallMethod(json_mod, "dumps", "O", d);
  Py_DECREF(json_mod);
  Py_DECREF(d);
  if (s == nullptr) { set_error_from_python(); return -1; }
  rc = copy_string_out(s, buffer_len, out_len, out_str);
  Py_DECREF(s);
  return rc;
}

int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type, double* out_results) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(nii)", handle_int(handle), num_iteration,
                                 importance_type);
  PyObject* imp = nullptr;
  int rc = call_with_out("LGBM_BoosterFeatureImportance", args, &imp);
  if (rc != 0) return rc;
  rc = copy_to_doubles(imp, out_results, nullptr);
  Py_DECREF(imp);
  return rc;
}

/* -------------------------------------------------------- Network */

int LGBM_NetworkInit(const char* machines, int local_listen_port,
                     int listen_time_out, int num_machines) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(siii)", machines ? machines : "",
                                 local_listen_port, listen_time_out,
                                 num_machines);
  return call_simple("LGBM_NetworkInit", args);
}

int LGBM_NetworkFree(void) {
  LTPU_ENTER();
  return call_simple("LGBM_NetworkFree", PyTuple_New(0));
}

/* ---------------------------------------------- full-surface tail
 * (round 4: the SWIG-breadth symbols so JNI/R hosts see the same
 * flat ABI the reference's swig/lightgbmlib.i wraps) */

int LGBM_SetLastError(const char* msg) {
  g_last_error = msg ? msg : "";
  if (g_capi != nullptr) {
    GilScope gil_scope__;
    call_simple("LGBM_SetLastError", Py_BuildValue("(s)", msg ? msg : ""));
  }
  return 0;
}

int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t nindptr, int64_t nelem,
                              int64_t num_col, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  LTPU_ENTER();
  PyObject *p, *ix, *d;
  if (csx_arrays(indptr, indptr_type, indices, data, data_type, nindptr,
                 nelem, &p, &ix, &d) != 0) return -1;
  PyObject* ref = reference ? PyLong_FromSsize_t(handle_int(reference))
                            : (Py_INCREF(Py_None), Py_None);
  PyObject* args = Py_BuildValue("(NNNLsN)", p, ix, d,
                                 static_cast<long long>(num_col),
                                 parameters ? parameters : "", ref);
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_DatasetCreateFromCSR", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<DatasetHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
  }
  return rc;
}

int LGBM_DatasetCreateFromCSC(const void* col_ptr, int col_ptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out) {
  LTPU_ENTER();
  PyObject *p, *ix, *d;
  if (csx_arrays(col_ptr, col_ptr_type, indices, data, data_type, ncol_ptr,
                 nelem, &p, &ix, &d) != 0) return -1;
  PyObject* ref = reference ? PyLong_FromSsize_t(handle_int(reference))
                            : (Py_INCREF(Py_None), Py_None);
  PyObject* args = Py_BuildValue("(NNNLsN)", p, ix, d,
                                 static_cast<long long>(num_row),
                                 parameters ? parameters : "", ref);
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_DatasetCreateFromCSC", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<DatasetHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
  }
  return rc;
}

int LGBM_DatasetGetSubset(const DatasetHandle handle,
                          const int32_t* used_row_indices,
                          int32_t num_used_row_indices,
                          const char* parameters, DatasetHandle* out) {
  LTPU_ENTER();
  PyObject* idx = array_from_buffer(used_row_indices, C_API_DTYPE_INT32,
                                    num_used_row_indices);
  if (idx == nullptr) return -1;
  PyObject* args = Py_BuildValue("(nNis)", handle_int(handle), idx,
                                 static_cast<int>(num_used_row_indices),
                                 parameters ? parameters : "");
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_DatasetGetSubset", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<DatasetHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
  }
  return rc;
}

int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                const char** feature_names,
                                int num_feature_names) {
  LTPU_ENTER();
  PyObject* names = PyList_New(num_feature_names);
  if (names == nullptr) { set_error_from_python(); return -1; }
  for (int i = 0; i < num_feature_names; ++i) {
    PyObject* s = PyUnicode_FromString(feature_names[i]);
    if (s == nullptr) {
      set_error_from_python();
      Py_DECREF(names);
      return -1;
    }
    PyList_SetItem(names, i, s);  // steals
  }
  PyObject* args = Py_BuildValue("(nNi)", handle_int(handle), names,
                                 num_feature_names);
  return call_simple("LGBM_DatasetSetFeatureNames", args);
}

int LGBM_DatasetGetFeatureNames(DatasetHandle handle, char** out_strs,
                                int* out_len) {
  LTPU_ENTER();
  /* python slice-assigns the names into out_strs (its optional
   * out_len defaults to None); count comes from the filled list */
  PyObject* strs = PyList_New(0);
  PyObject* args = Py_BuildValue("(nO)", handle_int(handle), strs);
  int rc = call_simple("LGBM_DatasetGetFeatureNames", args);
  if (rc == 0) rc = copy_names_out(strs, out_len, out_strs);
  Py_DECREF(strs);
  return rc;
}

int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(nL)", handle_int(reference),
                                 static_cast<long long>(num_total_row));
  PyObject* h = nullptr;
  int rc = call_with_out("LGBM_DatasetCreateByReference", args, &h);
  if (rc == 0) {
    *out = reinterpret_cast<DatasetHandle>(PyLong_AsSsize_t(h));
    Py_DECREF(h);
  }
  return rc;
}

int LGBM_DatasetPushRows(DatasetHandle handle, const void* data,
                         int data_type, int32_t nrow, int32_t ncol,
                         int32_t start_row) {
  LTPU_ENTER();
  PyObject* arr = array_from_buffer(data, data_type,
                                    static_cast<int64_t>(nrow) * ncol);
  if (arr == nullptr) return -1;
  PyObject* args = Py_BuildValue("(nNiii)", handle_int(handle), arr,
                                 static_cast<int>(nrow),
                                 static_cast<int>(ncol),
                                 static_cast<int>(start_row));
  return call_simple("LGBM_DatasetPushRows", args);
}

int LGBM_DatasetPushRowsByCSR(DatasetHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int64_t start_row) {
  LTPU_ENTER();
  PyObject *p, *ix, *d;
  if (csx_arrays(indptr, indptr_type, indices, data, data_type, nindptr,
                 nelem, &p, &ix, &d) != 0) return -1;
  PyObject* args = Py_BuildValue("(nNNNLi)", handle_int(handle), p, ix, d,
                                 static_cast<long long>(num_col),
                                 static_cast<int>(start_row));
  return call_simple("LGBM_DatasetPushRowsByCSR", args);
}

int LGBM_BoosterMerge(BoosterHandle handle, BoosterHandle other_handle) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(nn)", handle_int(handle),
                                 handle_int(other_handle));
  return call_simple("LGBM_BoosterMerge", args);
}

int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_BoosterNumberOfTotalModel", args, &v);
  if (rc == 0) {
    *out_models = static_cast<int>(PyLong_AsLong(v));
    Py_DECREF(v);
  }
  return rc;
}

int LGBM_BoosterResetParameter(BoosterHandle handle,
                               const char* parameters) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(ns)", handle_int(handle),
                                 parameters ? parameters : "");
  return call_simple("LGBM_BoosterResetParameter", args);
}

int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                  const DatasetHandle train_data) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(nn)", handle_int(handle),
                                 handle_int(train_data));
  return call_simple("LGBM_BoosterResetTrainingData", args);
}

int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_BoosterGetNumFeature", args, &v);
  if (rc == 0) { *out_len = static_cast<int>(PyLong_AsLong(v)); Py_DECREF(v); }
  return rc;
}

int LGBM_BoosterGetFeatureNames(BoosterHandle handle, int* out_len,
                                char** out_strs) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* names = nullptr;  /* python: out_strs[0] = [names] */
  int rc = call_with_out("LGBM_BoosterGetFeatureNames", args, &names);
  if (rc != 0) return rc;
  rc = copy_names_out(names, out_len, out_strs);
  Py_DECREF(names);
  return rc;
}

int LGBM_BoosterGetEvalNames(BoosterHandle handle, int* out_len,
                             char** out_strs) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(n)", handle_int(handle));
  PyObject* names = nullptr;
  int rc = call_with_out("LGBM_BoosterGetEvalNames", args, &names);
  if (rc != 0) return rc;
  rc = copy_names_out(names, out_len, out_strs);
  Py_DECREF(names);
  return rc;
}

int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                              int64_t* out_len) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(ni)", handle_int(handle), data_idx);
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_BoosterGetNumPredict", args, &v);
  if (rc == 0) {
    *out_len = static_cast<int64_t>(PyLong_AsLongLong(v));
    Py_DECREF(v);
  }
  return rc;
}

int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                           int64_t* out_len, double* out_result) {
  LTPU_ENTER();
  /* capacity from GetNumPredict, then let python slice-assign into a
   * writable view of the caller's buffer */
  PyObject* nargs = Py_BuildValue("(ni)", handle_int(handle), data_idx);
  PyObject* nv = nullptr;
  int rc = call_with_out("LGBM_BoosterGetNumPredict", nargs, &nv);
  if (rc != 0) return rc;
  int64_t cap = static_cast<int64_t>(PyLong_AsLongLong(nv));
  Py_DECREF(nv);
  PyObject* arr = writable_f64(out_result, cap);
  if (arr == nullptr) return -1;
  PyObject* len_list = PyList_New(1);
  Py_INCREF(Py_None);
  PyList_SetItem(len_list, 0, Py_None);
  PyObject* args = Py_BuildValue("(niON)", handle_int(handle), data_idx,
                                 len_list, arr);
  rc = call_simple("LGBM_BoosterGetPredict", args);
  if (rc == 0 && out_len != nullptr) {
    PyObject* n0 = PyList_GetItem(len_list, 0);
    *out_len = (n0 != Py_None)
                   ? static_cast<int64_t>(PyLong_AsLongLong(n0)) : 0;
  }
  Py_DECREF(len_list);
  return rc;
}

int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double* out_val) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(nii)", handle_int(handle), tree_idx,
                                 leaf_idx);
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_BoosterGetLeafValue", args, &v);
  if (rc == 0) { *out_val = PyFloat_AsDouble(v); Py_DECREF(v); }
  return rc;
}

int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double val) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(niid)", handle_int(handle), tree_idx,
                                 leaf_idx, val);
  return call_simple("LGBM_BoosterSetLeafValue", args);
}

int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                               int predict_type, int num_iteration,
                               int64_t* out_len) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(niii)", handle_int(handle), num_row,
                                 predict_type, num_iteration);
  PyObject* v = nullptr;
  int rc = call_with_out("LGBM_BoosterCalcNumPredict", args, &v);
  if (rc == 0) {
    *out_len = static_cast<int64_t>(PyLong_AsLongLong(v));
    Py_DECREF(v);
  }
  return rc;
}

int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result) {
  LTPU_ENTER();
  (void)parameter;  // reserved, as in PredictForMat
  PyObject *p, *ix, *d;
  if (csx_arrays(indptr, indptr_type, indices, data, data_type, nindptr,
                 nelem, &p, &ix, &d) != 0) return -1;
  PyObject* args = Py_BuildValue("(nNNNLii)", handle_int(handle), p, ix, d,
                                 static_cast<long long>(num_col),
                                 predict_type, num_iteration);
  PyObject* pred = nullptr;
  int rc = call_with_out("LGBM_BoosterPredictForCSR", args, &pred);
  if (rc != 0) return rc;
  rc = copy_to_doubles(pred, out_result, out_len);
  Py_DECREF(pred);
  return rc;
}

int LGBM_BoosterPredictForCSC(BoosterHandle handle, const void* col_ptr,
                              int col_ptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result) {
  LTPU_ENTER();
  (void)parameter;
  PyObject *p, *ix, *d;
  if (csx_arrays(col_ptr, col_ptr_type, indices, data, data_type, ncol_ptr,
                 nelem, &p, &ix, &d) != 0) return -1;
  PyObject* args = Py_BuildValue("(nNNNLii)", handle_int(handle), p, ix, d,
                                 static_cast<long long>(num_row),
                                 predict_type, num_iteration);
  PyObject* pred = nullptr;
  int rc = call_with_out("LGBM_BoosterPredictForCSC", args, &pred);
  if (rc != 0) return rc;
  rc = copy_to_doubles(pred, out_result, out_len);
  Py_DECREF(pred);
  return rc;
}

int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header, int predict_type,
                               int num_iteration, const char* parameter,
                               const char* result_filename) {
  LTPU_ENTER();
  PyObject* args = Py_BuildValue("(nsiiiss)", handle_int(handle),
                                 data_filename, data_has_header,
                                 predict_type, num_iteration,
                                 parameter ? parameter : "",
                                 result_filename);
  return call_simple("LGBM_BoosterPredictForFile", args);
}

}  /* extern "C" */
