// Dense value->bin binning: the hot half of dataset preparation.
//
// Bit-identical to the Python path's np.searchsorted(bounds, v, 'left')
// (reference ValueToBin binary search, include/LightGBM/bin.h:450-486):
// numpy's searchsorted runs ~20M values/s on this host (per-element
// dtype-dispatched compares); a compiled std::lower_bound over the
// per-feature bound arrays runs ~10x that, which is what keeps the
// 10.5M-row HIGGS prep from being dominated by binning on a 1-core
// host (round-3 verdict weak #4).
//
// Round 11 extends the library over the whole construction pipeline:
// ltpu_bin_dense_mt fans the row blocks over std::threads (disjoint
// output rows, so the result is byte-identical at every thread count),
// ltpu_bin_cat runs the categorical LUT lookup, and ltpu_bin_bundle
// applies the EFB offset/default-collapse write (feature_group.h:
// 128-136) — the last per-feature Python fallbacks in _bin_rows_dense.
#include <algorithm>
#include <cmath>
#include <thread>
#include <vector>

namespace {

constexpr long BKMAX = 512;

// Bin rows [i0_lo, i0_hi) of a row-major (n, f_total) matrix into the
// feature-major (n_used, n) output.  Loop order: row blocks OUTER,
// features INNER.  A row-major X column gather strides f_total*8
// bytes, so feature-outer order misses DRAM on every value once the
// matrix is wide (136-feature MS-LTR prep ran 2x slower per value than
// 28-feature HIGGS).  With the row block held in cache, only the first
// feature's gather touches DRAM; the rest hit L2.  BK shrinks for very
// wide rows so the block (BK * f_total * 8B) stays cache-resident.
void bin_dense_range(
    const double* X, long i0_lo, long i0_hi, long n, long f_total,
    const long* feat_idx, long n_used,
    const double* bounds_flat, const long* bounds_off,
    const unsigned char* use_nan, const long* nan_bin,
    unsigned char* out /* (n_used, n) feature-major */) {
  long bk = BKMAX;
  if (f_total > 0) {
    const long fit = (2L << 20) / (8 * f_total);  // ~2 MB of block
    if (fit < bk) bk = fit < 64 ? 64 : (fit / 64) * 64;
  }
  double buf[BKMAX];
  unsigned short cnt[BKMAX];
  unsigned char nanv[BKMAX];
  for (long i0 = i0_lo; i0 < i0_hi; i0 += bk) {
    const long m = (i0_hi - i0 < bk) ? (i0_hi - i0) : bk;
    const double* xb = X + i0 * f_total;
    for (long j = 0; j < n_used; ++j) {
      const double* ub = bounds_flat + bounds_off[j];
      const long len = bounds_off[j + 1] - bounds_off[j];
      const double* col = xb + feat_idx[j];
      const bool un = use_nan[j] != 0;
      const unsigned char nb = (unsigned char)nan_bin[j];
      unsigned char* o = out + j * n + i0;
      // branchless compare-count (== lower_bound index for a sorted
      // array) over a contiguous row buffer: the per-value binary
      // search costs ~6 dependent mispredicting branches on random
      // data; this form runs at SIMD compare throughput
      for (long i = 0; i < m; ++i) {
        double v = col[i * f_total];
        const bool is_nan = std::isnan(v);
        nanv[i] = is_nan ? 1 : 0;
        buf[i] = is_nan ? 0.0 : v;
        cnt[i] = 0;
      }
      for (long b = 0; b < len; ++b) {
        const double ubb = ub[b];
        for (long i = 0; i < m; ++i) cnt[i] += (ubb < buf[i]) ? 1 : 0;
      }
      for (long i = 0; i < m; ++i)
        o[i] = (nanv[i] && un) ? nb : (unsigned char)cnt[i];
    }
  }
}

}  // namespace

extern "C" void ltpu_bin_dense(
    const double* X, long n, long f_total,
    const long* feat_idx, long n_used,
    const double* bounds_flat, const long* bounds_off,
    const unsigned char* use_nan, const long* nan_bin,
    unsigned char* out /* (n_used, n) feature-major */) {
  bin_dense_range(X, 0, n, n, f_total, feat_idx, n_used, bounds_flat,
                  bounds_off, use_nan, nan_bin, out);
}

// Threaded form: contiguous block-aligned row ranges per thread.  Each
// range writes a disjoint slice of every output row, so the packed
// result is byte-identical at any thread count.
extern "C" void ltpu_bin_dense_mt(
    const double* X, long n, long f_total,
    const long* feat_idx, long n_used,
    const double* bounds_flat, const long* bounds_off,
    const unsigned char* use_nan, const long* nan_bin,
    unsigned char* out, long n_threads) {
  if (n_threads <= 1 || n < 2 * BKMAX) {
    bin_dense_range(X, 0, n, n, f_total, feat_idx, n_used, bounds_flat,
                    bounds_off, use_nan, nan_bin, out);
    return;
  }
  const long max_t = (n + BKMAX - 1) / BKMAX;
  if (n_threads > max_t) n_threads = max_t;
  // block-aligned split so every thread's internal blocking matches
  // the serial walk's block boundaries
  const long per = ((n / n_threads + BKMAX - 1) / BKMAX) * BKMAX;
  std::vector<std::thread> ts;
  for (long t = 0; t < n_threads; ++t) {
    const long lo = t * per;
    if (lo >= n) break;
    const long hi = std::min(n, lo + per);
    ts.emplace_back(bin_dense_range, X, lo, hi, n, f_total, feat_idx,
                    n_used, bounds_flat, bounds_off, use_nan, nan_bin,
                    out);
  }
  for (auto& th : ts) th.join();
}

// Categorical value->bin: the compiled form of BinMapper.value_to_bin's
// LUT path (bin.h:450-486 CategoricalBin::ValueToBin).  lut[k] is
// category k's bin (pre-filled with the unseen bin for unmapped keys);
// NaN and negative values route to the unseen bin like the Python
// path's iv = -1.  out_stride lets the caller write a packed-matrix
// column in place (stride = num_groups) or a contiguous scratch row
// (stride = 1, feeding ltpu_bin_bundle).
extern "C" void ltpu_bin_cat(
    const double* X, long n, long f_total, long col,
    const int* lut, long lut_len, long unseen_bin,
    unsigned char* out, long out_stride) {
  const double* c = X + col;
  for (long i = 0; i < n; ++i) {
    const double v = c[i * f_total];
    // (long)v truncates toward zero exactly like numpy's
    // astype(int64); out-of-range doubles land outside [0, lut_len)
    // on both paths and take the unseen bin
    const long iv = std::isnan(v) ? -1 : (long)v;
    const long b = (iv >= 0 && iv < lut_len) ? lut[iv] : unseen_bin;
    out[i * out_stride] = (unsigned char)b;
  }
}

// EFB bundle column write (reference feature_group.h:128-136): a
// feature inside a multi-feature bundle stores non-default bins at
// [offset, offset+num_bin) — minus the default-at-0 slot removal —
// and leaves default rows alone (they share the group's bin-0 default
// slot, prefilled by the caller).  col_bins is the feature's own
// value->bin result (from ltpu_bin_dense/_cat or the Python mapper).
extern "C" void ltpu_bin_bundle(
    const unsigned char* col_bins, long n, long offset, long default_bin,
    unsigned char* out, long out_stride) {
  const long shift = offset - (default_bin == 0 ? 1 : 0);
  for (long i = 0; i < n; ++i) {
    const unsigned char c = col_bins[i];
    if ((long)c != default_bin)
      out[i * out_stride] = (unsigned char)((long)c + shift);
  }
}

// Feature-major (n_used, n) bin rows -> row-major (n, g_total) packed
// matrix columns.  numpy's out[:, g] = res[j] pays a DRAM-missing
// g_total-strided byte write per value (it dominated wide-matrix prep
// once the binning itself was cache-blocked); transposing through an
// L1-resident row block runs at copy throughput.
extern "C" void ltpu_scatter_cols(
    const unsigned char* res, long n_used, long n,
    const long* col_idx, unsigned char* out, long g_total) {
  constexpr long B = 256;
  for (long i0 = 0; i0 < n; i0 += B) {
    const long m = (n - i0 < B) ? (n - i0) : B;
    unsigned char* ob = out + i0 * g_total;
    for (long j = 0; j < n_used; ++j) {
      const unsigned char* r = res + j * n + i0;
      unsigned char* o = ob + col_idx[j];
      for (long i = 0; i < m; ++i) o[i * g_total] = r[i];
    }
  }
}

// Nibble pack (bin_packing=4bit/auto, packing.py layout): row-major
// (n, g_total) logical bin rows -> (n, out_cols) storage rows where
// the first `packed` groups interleave two-per-byte (group 2j low
// nibble, 2j+1 high) and the rest copy through one byte each.  The
// numpy pack is three strided passes over the chunk; this single
// fused pass runs at copy throughput and keeps the logical row in L1
// while both nibbles are combined.
extern "C" void ltpu_pack_nibbles(
    const unsigned char* logical, long n, long g_total, long packed,
    unsigned char* out, long out_cols) {
  const long pb = (packed + 1) / 2;
  const long pairs = packed / 2;
  const long wide = g_total - packed;
  for (long i = 0; i < n; ++i) {
    const unsigned char* r = logical + i * g_total;
    unsigned char* o = out + i * out_cols;
    for (long j = 0; j < pairs; ++j)
      o[j] = (unsigned char)(r[2 * j] | (r[2 * j + 1] << 4));
    if (packed % 2)                 // odd tail: low nibble only
      o[pb - 1] = r[packed - 1];
    for (long k = 0; k < wide; ++k) o[pb + k] = r[packed + k];
  }
}
