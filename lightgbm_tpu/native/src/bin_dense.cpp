// Dense value->bin binning: the hot half of dataset preparation.
//
// Bit-identical to the Python path's np.searchsorted(bounds, v, 'left')
// (reference ValueToBin binary search, include/LightGBM/bin.h:450-486):
// numpy's searchsorted runs ~20M values/s on this host (per-element
// dtype-dispatched compares); a compiled std::lower_bound over the
// per-feature bound arrays runs ~10x that, which is what keeps the
// 10.5M-row HIGGS prep from being dominated by binning on a 1-core
// host (round-3 verdict weak #4).
#include <algorithm>
#include <cmath>

extern "C" void ltpu_bin_dense(
    const double* X, long n, long f_total,
    const long* feat_idx, long n_used,
    const double* bounds_flat, const long* bounds_off,
    const unsigned char* use_nan, const long* nan_bin,
    unsigned char* out /* (n_used, n) feature-major */) {
  for (long j = 0; j < n_used; ++j) {
    const double* ub = bounds_flat + bounds_off[j];
    const long len = bounds_off[j + 1] - bounds_off[j];
    const long fi = feat_idx[j];
    const bool un = use_nan[j] != 0;
    const unsigned char nb = (unsigned char)nan_bin[j];
    unsigned char* o = out + j * n;
    const double* col = X + fi;
    // branchless compare-count (== lower_bound index for a sorted
    // array), row-blocked so the per-bound loop vectorizes over a
    // contiguous row buffer: the per-value binary search costs ~6
    // dependent mispredicting branches on random data; this form runs
    // at SIMD compare throughput
    constexpr long BK = 512;
    double buf[BK];
    unsigned short cnt[BK];
    unsigned char nanv[BK];
    for (long i0 = 0; i0 < n; i0 += BK) {
      const long m = (n - i0 < BK) ? (n - i0) : BK;
      for (long i = 0; i < m; ++i) {
        double v = col[(i0 + i) * f_total];
        const bool is_nan = std::isnan(v);
        nanv[i] = is_nan ? 1 : 0;
        buf[i] = is_nan ? 0.0 : v;
        cnt[i] = 0;
      }
      for (long b = 0; b < len; ++b) {
        const double ubb = ub[b];
        for (long i = 0; i < m; ++i) cnt[i] += (ubb < buf[i]) ? 1 : 0;
      }
      for (long i = 0; i < m; ++i)
        o[i0 + i] = (nanv[i] && un) ? nb : (unsigned char)cnt[i];
    }
  }
}
