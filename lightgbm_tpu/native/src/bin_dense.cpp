// Dense value->bin binning: the hot half of dataset preparation.
//
// Bit-identical to the Python path's np.searchsorted(bounds, v, 'left')
// (reference ValueToBin binary search, include/LightGBM/bin.h:450-486):
// numpy's searchsorted runs ~20M values/s on this host (per-element
// dtype-dispatched compares); a compiled std::lower_bound over the
// per-feature bound arrays runs ~10x that, which is what keeps the
// 10.5M-row HIGGS prep from being dominated by binning on a 1-core
// host (round-3 verdict weak #4).
#include <algorithm>
#include <cmath>

extern "C" void ltpu_bin_dense(
    const double* X, long n, long f_total,
    const long* feat_idx, long n_used,
    const double* bounds_flat, const long* bounds_off,
    const unsigned char* use_nan, const long* nan_bin,
    unsigned char* out /* (n_used, n) feature-major */) {
  // Loop order: row blocks OUTER, features INNER.  A row-major X
  // column gather strides f_total*8 bytes, so feature-outer order
  // misses DRAM on every value once the matrix is wide (136-feature
  // MS-LTR prep ran 2x slower per value than 28-feature HIGGS).  With
  // the row block held in cache, only the first feature's gather
  // touches DRAM; the rest hit L2.  BK shrinks for very wide rows so
  // the block (BK * f_total * 8B) stays cache-resident.
  constexpr long BKMAX = 512;
  long bk = BKMAX;
  if (f_total > 0) {
    const long fit = (2L << 20) / (8 * f_total);  // ~2 MB of block
    if (fit < bk) bk = fit < 64 ? 64 : (fit / 64) * 64;
  }
  double buf[BKMAX];
  unsigned short cnt[BKMAX];
  unsigned char nanv[BKMAX];
  for (long i0 = 0; i0 < n; i0 += bk) {
    const long m = (n - i0 < bk) ? (n - i0) : bk;
    const double* xb = X + i0 * f_total;
    for (long j = 0; j < n_used; ++j) {
      const double* ub = bounds_flat + bounds_off[j];
      const long len = bounds_off[j + 1] - bounds_off[j];
      const double* col = xb + feat_idx[j];
      const bool un = use_nan[j] != 0;
      const unsigned char nb = (unsigned char)nan_bin[j];
      unsigned char* o = out + j * n + i0;
      // branchless compare-count (== lower_bound index for a sorted
      // array) over a contiguous row buffer: the per-value binary
      // search costs ~6 dependent mispredicting branches on random
      // data; this form runs at SIMD compare throughput
      for (long i = 0; i < m; ++i) {
        double v = col[i * f_total];
        const bool is_nan = std::isnan(v);
        nanv[i] = is_nan ? 1 : 0;
        buf[i] = is_nan ? 0.0 : v;
        cnt[i] = 0;
      }
      for (long b = 0; b < len; ++b) {
        const double ubb = ub[b];
        for (long i = 0; i < m; ++i) cnt[i] += (ubb < buf[i]) ? 1 : 0;
      }
      for (long i = 0; i < m; ++i)
        o[i] = (nanv[i] && un) ? nb : (unsigned char)cnt[i];
    }
  }
}

// Feature-major (n_used, n) bin rows -> row-major (n, g_total) packed
// matrix columns.  numpy's out[:, g] = res[j] pays a DRAM-missing
// g_total-strided byte write per value (it dominated wide-matrix prep
// once the binning itself was cache-blocked); transposing through an
// L1-resident row block runs at copy throughput.
extern "C" void ltpu_scatter_cols(
    const unsigned char* res, long n_used, long n,
    const long* col_idx, unsigned char* out, long g_total) {
  constexpr long B = 256;
  for (long i0 = 0; i0 < n; i0 += B) {
    const long m = (n - i0 < B) ? (n - i0) : B;
    unsigned char* ob = out + i0 * g_total;
    for (long j = 0; j < n_used; ++j) {
      const unsigned char* r = res + j * n + i0;
      unsigned char* o = ob + col_idx[j];
      for (long i = 0; i < m; ++i) o[i * g_total] = r[i];
    }
  }
}
