/*
 * lightgbm_tpu C API — native embedding surface for non-Python hosts.
 *
 * Plays the role of the reference's flat C API
 * (reference: include/LightGBM/c_api.h, src/c_api.cpp) with the same
 * function names, handle discipline and 0/-1 + LGBM_GetLastError error
 * convention (reference c_api.h:765-788).  The stack is inverted
 * relative to the reference: the core is a Python/JAX program, so this
 * library embeds CPython (statically linked against libpython) and
 * forwards each call to lightgbm_tpu.capi.  R's .Call shim or a Java
 * JNI wrapper links against this exactly the way the reference's
 * R-package/src/lightgbm_R.cpp links against lib_lightgbm.
 *
 * Threading: every entry point acquires the GIL; concurrent calls from
 * multiple host threads serialize (the reference serializes Booster
 * mutations with a std::mutex, c_api.cpp:67,311 — same effective
 * discipline).
 *
 * Environment: the embedded interpreter must be able to import
 * `lightgbm_tpu` (set PYTHONPATH, or call LTPU_AddSysPath first).
 */
#ifndef LIGHTGBM_TPU_C_API_H_
#define LIGHTGBM_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* DatasetHandle;
typedef void* BoosterHandle;

/* dtype codes (reference c_api.h:33-41) */
#define C_API_DTYPE_FLOAT32 (0)
#define C_API_DTYPE_FLOAT64 (1)
#define C_API_DTYPE_INT32   (2)
#define C_API_DTYPE_INT64   (3)

/* predict task codes (reference c_api.h:43-47) */
#define C_API_PREDICT_NORMAL     (0)
#define C_API_PREDICT_RAW_SCORE  (1)
#define C_API_PREDICT_LEAF_INDEX (2)
#define C_API_PREDICT_CONTRIB    (3)

/* ---- embedding helpers (no reference analog; interpreter control) */
/* Append a directory to the embedded interpreter's sys.path BEFORE the
 * first API call (so `import lightgbm_tpu` resolves). */
int LTPU_AddSysPath(const char* path);
/* Force interpreter + module initialization now (otherwise lazy). */
int LTPU_EnsureInitialized(void);

/* ---- error handling */
const char* LGBM_GetLastError(void);
/* reference c_api.h:768 — embedders (custom objectives calling back
 * into the host) set the error slot themselves. */
int LGBM_SetLastError(const char* msg);

/* ---- Dataset */
int LGBM_DatasetCreateFromFile(const char* filename, const char* parameters,
                               const DatasetHandle reference,
                               DatasetHandle* out);
int LGBM_DatasetCreateFromMat(const void* data, int data_type, int32_t nrow,
                              int32_t ncol, int is_row_major,
                              const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);
int LGBM_DatasetSetField(DatasetHandle handle, const char* field_name,
                         const void* field_data, int num_element, int type);
/* out_ptr stays valid until the next GetField on the same handle or
 * DatasetFree (the reference returns a pointer into the Dataset too). */
int LGBM_DatasetGetField(DatasetHandle handle, const char* field_name,
                         int* out_len, const void** out_ptr, int* out_type);
int LGBM_DatasetGetNumData(DatasetHandle handle, int32_t* out);
int LGBM_DatasetGetNumFeature(DatasetHandle handle, int32_t* out);
int LGBM_DatasetSaveBinary(DatasetHandle handle, const char* filename);
int LGBM_DatasetFree(DatasetHandle handle);
/* CSR rows (reference c_api.h:147-180).  indptr_type / data_type are
 * C_API_DTYPE_* codes; indices are int32. */
int LGBM_DatasetCreateFromCSR(const void* indptr, int indptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t nindptr, int64_t nelem,
                              int64_t num_col, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);
/* CSC columns (reference c_api.h:183-216). */
int LGBM_DatasetCreateFromCSC(const void* col_ptr, int col_ptr_type,
                              const int32_t* indices, const void* data,
                              int data_type, int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, const char* parameters,
                              const DatasetHandle reference,
                              DatasetHandle* out);
/* Row subset sharing the parent's bin mappers (reference
 * c_api.h:195-210). */
int LGBM_DatasetGetSubset(const DatasetHandle handle,
                          const int32_t* used_row_indices,
                          int32_t num_used_row_indices,
                          const char* parameters, DatasetHandle* out);
/* Feature names (reference c_api.h:212-230).  On Get, each
 * out_strs[i] must point at a caller buffer of >= 256 bytes; pass
 * out_strs == NULL to only query the count. */
int LGBM_DatasetSetFeatureNames(DatasetHandle handle,
                                const char** feature_names,
                                int num_feature_names);
int LGBM_DatasetGetFeatureNames(DatasetHandle handle, char** out_strs,
                                int* out_len);
/* Streaming ingestion (reference c_api.h:68-145): mappers fitted from
 * per-column samples (or copied from an existing dataset), then rows
 * pushed in chunks. */
int LGBM_DatasetCreateByReference(const DatasetHandle reference,
                                  int64_t num_total_row,
                                  DatasetHandle* out);
int LGBM_DatasetPushRows(DatasetHandle handle, const void* data,
                         int data_type, int32_t nrow, int32_t ncol,
                         int32_t start_row);
int LGBM_DatasetPushRowsByCSR(DatasetHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int64_t start_row);

/* ---- Booster */
int LGBM_BoosterCreate(const DatasetHandle train_data,
                       const char* parameters, BoosterHandle* out);
int LGBM_BoosterCreateFromModelfile(const char* filename,
                                    int* out_num_iterations,
                                    BoosterHandle* out);
int LGBM_BoosterLoadModelFromString(const char* model_str,
                                    int* out_num_iterations,
                                    BoosterHandle* out);
int LGBM_BoosterFree(BoosterHandle handle);
int LGBM_BoosterAddValidData(BoosterHandle handle,
                             const DatasetHandle valid_data);
int LGBM_BoosterGetNumClasses(BoosterHandle handle, int* out_len);
int LGBM_BoosterUpdateOneIter(BoosterHandle handle, int* is_finished);
int LGBM_BoosterUpdateOneIterCustom(BoosterHandle handle, const float* grad,
                                    const float* hess, int64_t num_elements,
                                    int* is_finished);
int LGBM_BoosterRollbackOneIter(BoosterHandle handle);
int LGBM_BoosterGetCurrentIteration(BoosterHandle handle,
                                    int* out_iteration);
/* Number of metric values per dataset — size the GetEval buffer with
 * this first (reference c_api.h:430-437). */
int LGBM_BoosterGetEvalCounts(BoosterHandle handle, int* out_len);
int LGBM_BoosterGetEval(BoosterHandle handle, int data_idx, int* out_len,
                        double* out_results);
int LGBM_BoosterPredictForMat(BoosterHandle handle, const void* data,
                              int data_type, int32_t nrow, int32_t ncol,
                              int is_row_major, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result);
int LGBM_BoosterSaveModel(BoosterHandle handle, int num_iteration,
                          const char* filename);
int LGBM_BoosterSaveModelToString(BoosterHandle handle, int num_iteration,
                                  int64_t buffer_len, int64_t* out_len,
                                  char* out_str);
int LGBM_BoosterDumpModel(BoosterHandle handle, int num_iteration,
                          int64_t buffer_len, int64_t* out_len,
                          char* out_str);
int LGBM_BoosterFeatureImportance(BoosterHandle handle, int num_iteration,
                                  int importance_type, double* out_results);
/* Append other's trees onto handle (reference c_api.h:330-338). */
int LGBM_BoosterMerge(BoosterHandle handle, BoosterHandle other_handle);
int LGBM_BoosterNumberOfTotalModel(BoosterHandle handle, int* out_models);
int LGBM_BoosterResetParameter(BoosterHandle handle,
                               const char* parameters);
int LGBM_BoosterResetTrainingData(BoosterHandle handle,
                                  const DatasetHandle train_data);
int LGBM_BoosterGetNumFeature(BoosterHandle handle, int* out_len);
/* Name lists: each out_strs[i] must point at a caller buffer of
 * >= 256 bytes; pass out_strs == NULL to only query the count
 * (reference c_api.h:430-446). */
int LGBM_BoosterGetFeatureNames(BoosterHandle handle, int* out_len,
                                char** out_strs);
int LGBM_BoosterGetEvalNames(BoosterHandle handle, int* out_len,
                             char** out_strs);
/* Converted in-training scores of train (data_idx 0) / valid set
 * data_idx-1 (reference c_api.h:520-548).  Size out_result with
 * GetNumPredict first. */
int LGBM_BoosterGetNumPredict(BoosterHandle handle, int data_idx,
                              int64_t* out_len);
int LGBM_BoosterGetPredict(BoosterHandle handle, int data_idx,
                           int64_t* out_len, double* out_result);
int LGBM_BoosterGetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double* out_val);
int LGBM_BoosterSetLeafValue(BoosterHandle handle, int tree_idx,
                             int leaf_idx, double val);
/* Result-buffer size for a prediction call (reference
 * c_api.h:520-535). */
int LGBM_BoosterCalcNumPredict(BoosterHandle handle, int num_row,
                               int predict_type, int num_iteration,
                               int64_t* out_len);
/* Sparse prediction (reference c_api.h:574-659).  parameter is
 * reserved (the reference parses extra predict params there). */
int LGBM_BoosterPredictForCSR(BoosterHandle handle, const void* indptr,
                              int indptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t nindptr, int64_t nelem,
                              int64_t num_col, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result);
int LGBM_BoosterPredictForCSC(BoosterHandle handle, const void* col_ptr,
                              int col_ptr_type, const int32_t* indices,
                              const void* data, int data_type,
                              int64_t ncol_ptr, int64_t nelem,
                              int64_t num_row, int predict_type,
                              int num_iteration, const char* parameter,
                              int64_t* out_len, double* out_result);
/* Batch file prediction, one row per line (reference
 * c_api.h:495-518). */
int LGBM_BoosterPredictForFile(BoosterHandle handle,
                               const char* data_filename,
                               int data_has_header, int predict_type,
                               int num_iteration, const char* parameter,
                               const char* result_filename);

/* ---- Network (reference c_api.h:749-762; see capi.py for the TPU
 * semantics — rendezvous goes through jax.distributed, these warn) */
int LGBM_NetworkInit(const char* machines, int local_listen_port,
                     int listen_time_out, int num_machines);
int LGBM_NetworkFree(void);

#ifdef __cplusplus
}  /* extern "C" */
#endif

#endif  /* LIGHTGBM_TPU_C_API_H_ */
