"""Native (C++) runtime bindings via ctypes.

Builds lightgbm_tpu/native/src/*.cpp into libltpu.so on first use
(cached beside the sources) — the framework's native IO layer, standing
in for the reference's C++ parser/text-reader stack without a
pybind11 dependency.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

from ..utils.log import Log

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "libltpu.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> Optional[str]:
    srcs = [os.path.join(_SRC_DIR, f) for f in sorted(os.listdir(_SRC_DIR))
            if f.endswith(".cpp")]
    if not srcs:
        return None
    newest_src = max(os.path.getmtime(s) for s in srcs)
    if os.path.exists(_LIB_PATH) and \
            os.path.getmtime(_LIB_PATH) >= newest_src:
        return _LIB_PATH
    cmd = ["g++", "-O3", "-march=native", "-funroll-loops", "-std=c++17",
           "-shared", "-fPIC", "-pthread",
           "-o", _LIB_PATH] + srcs
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except (subprocess.CalledProcessError, FileNotFoundError) as e:
        Log.warning(f"native build failed ({e}); "
                    "falling back to Python IO")
        return None
    return _LIB_PATH


def get_lib() -> Optional[ctypes.CDLL]:
    # fault seam: every native-lib entry resolves the handle through
    # here, so an injected failure models a broken/unloadable .so at
    # exactly one call site (docs/RELIABILITY.md, seam registry)
    from ..reliability.faults import FAULTS
    FAULTS.fault_point("native.entry")
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        path = _build()
        if path is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(path)
        lib.ltpu_load_csv.restype = ctypes.POINTER(ctypes.c_double)
        lib.ltpu_load_csv.argtypes = [
            ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.ltpu_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
        lib.ltpu_count_lines.restype = ctypes.c_long
        lib.ltpu_count_lines.argtypes = [ctypes.c_char_p]
        lib.ltpu_bin_values.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.c_int32,
            ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8)]
        # construction-pipeline entry points (hasattr-guarded: a stale
        # prebuilt libltpu.so without them must still serve the
        # loaders while the callers fall back to the Python path).
        # ONE home for every binner signature — dataset.py must not
        # carry its own copies that could drift from the C side.
        if hasattr(lib, "ltpu_bin_dense"):
            lib.ltpu_bin_dense.restype = None
            lib.ltpu_bin_dense.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_long,
                ctypes.c_long, ctypes.POINTER(ctypes.c_long),
                ctypes.c_long, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_ubyte)]
        if hasattr(lib, "ltpu_scatter_cols"):
            lib.ltpu_scatter_cols.restype = None
            lib.ltpu_scatter_cols.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long,
                ctypes.c_long, ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
        if hasattr(lib, "ltpu_bin_dense_mt"):
            lib.ltpu_bin_dense_mt.restype = None
            lib.ltpu_bin_dense_mt.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_long,
                ctypes.c_long, ctypes.POINTER(ctypes.c_long),
                ctypes.c_long, ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_ubyte),
                ctypes.POINTER(ctypes.c_long),
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
        if hasattr(lib, "ltpu_bin_cat"):
            lib.ltpu_bin_cat.restype = None
            lib.ltpu_bin_cat.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_long,
                ctypes.c_long, ctypes.c_long,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_long,
                ctypes.c_long, ctypes.POINTER(ctypes.c_ubyte),
                ctypes.c_long]
        if hasattr(lib, "ltpu_pack_nibbles"):
            lib.ltpu_pack_nibbles.restype = None
            lib.ltpu_pack_nibbles.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long,
                ctypes.c_long, ctypes.c_long,
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
        if hasattr(lib, "ltpu_bin_bundle"):
            lib.ltpu_bin_bundle.restype = None
            lib.ltpu_bin_bundle.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long,
                ctypes.c_long, ctypes.c_long,
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_long]
        _lib = lib
        return _lib


class text_loader:
    """Namespace used by data_loader.py."""

    @staticmethod
    def load_csv(path: str, sep: str, skip_rows: int) -> np.ndarray:
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        rows = ctypes.c_int64()
        cols = ctypes.c_int64()
        ptr = lib.ltpu_load_csv(path.encode(), sep.encode(), skip_rows,
                                ctypes.byref(rows), ctypes.byref(cols))
        if not ptr:
            raise RuntimeError(f"native parse failed for {path}")
        try:
            n = rows.value * cols.value
            arr = np.ctypeslib.as_array(ptr, shape=(n,)).copy()
        finally:
            lib.ltpu_free(ptr)
        return arr.reshape(rows.value, cols.value)

    @staticmethod
    def count_lines(path: str) -> int:
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        return int(lib.ltpu_count_lines(path.encode()))


def bin_values_native(values: np.ndarray, bounds: np.ndarray,
                      num_bin: int, missing_type: int
                      ) -> Optional[np.ndarray]:
    """Threaded value->bin mapping; None when the native lib is absent."""
    lib = get_lib()
    if lib is None:
        return None
    values = np.ascontiguousarray(values, dtype=np.float64)
    bounds = np.ascontiguousarray(bounds, dtype=np.float64)
    out = np.empty(len(values), dtype=np.uint8)
    lib.ltpu_bin_values(
        values.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        len(values),
        bounds.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        num_bin, missing_type,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out
