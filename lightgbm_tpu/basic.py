"""User-facing Dataset (lazy) and Booster re-export.

Mirrors the reference python package's basic.py: ``Dataset`` wraps raw
data and constructs the binned core dataset lazily when training starts
(reference: python-package/lightgbm/basic.py:572-1263 _lazy_init,
reference alignment for validation data), so bin mappers are fitted with
the final parameter set exactly once.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Union

import numpy as np

from .booster import Booster  # noqa: F401  (re-export)
from .config import Config
from .dataset import Dataset as CoreDataset
from .utils.log import Log


class Dataset:
    """Lazy dataset handle (the lgb.Dataset analog)."""

    def __init__(self, data, label=None, reference: Optional["Dataset"] = None,
                 weight=None, group=None, init_score=None,
                 feature_name: Union[str, Sequence[str]] = "auto",
                 categorical_feature: Union[str, Sequence] = "auto",
                 params: Optional[Dict[str, Any]] = None,
                 free_raw_data: bool = True):
        # free_raw_data defaults True like the reference python package
        # (at bench scale the float64 matrix is 224 MB of dead weight
        # next to the binned copy; 2.4 GB at HIGGS scale).  Continued
        # training (init_model) needs the raw matrix to seed scores —
        # pass free_raw_data=False there, as in the reference.
        self.data = data
        self.label = label
        self.reference = reference
        self.weight = weight
        self.group = group
        self.init_score = init_score
        self.feature_name = feature_name
        self.categorical_feature = categorical_feature
        self.params = dict(params or {})
        self.free_raw_data = free_raw_data
        self._core: Optional[CoreDataset] = None

    # ------------------------------------------------------------------
    def construct(self, config: Optional[Config] = None) -> CoreDataset:
        if self._core is not None:
            return self._core
        if config is None:
            config = Config.from_params(self.params)
        data = self.data
        label = self.label
        if isinstance(data, str):
            # the reference's DatasetLoader sniffs the binary token on
            # EVERY file load (dataset_loader.cpp LoadFromBinFile /
            # CheckCanLoadFromBin) — a saved binary cache must load
            # wherever a text file would
            from .dataset_io import is_binary_file, load_binary
            if is_binary_file(data):
                # the run's bin_packing intent is checked against the
                # cache's recorded storage layout (loud mismatch
                # refusal — see dataset_io._check_packing)
                self._core = load_binary(data, config=config)
                if self.label is not None:
                    self._core.metadata.set_label(self.label)
                if self.weight is not None:
                    self._core.metadata.set_weight(self.weight)
                if self.group is not None:
                    self._core.metadata.set_group(self.group)
                if self.init_score is not None:
                    self._core.metadata.set_init_score(self.init_score)
                if isinstance(self.feature_name, (list, tuple)):
                    self._core.feature_names = list(self.feature_name)
                return self._core
        if config.sharded_shards > 1 and self.reference is None \
                and config.sharded_cache_dir:
            # shard-cache v2 reload (the sharded analog of the binary-
            # token sniff above): a committed manifest short-circuits
            # parsing AND binning; world-size/fingerprint mismatches
            # refuse loudly inside the loader
            from .sharded import has_shard_cache, load_shard_cache
            if has_shard_cache(config.sharded_cache_dir):
                if self.group is not None:
                    # the fresh-construct route refuses query groups
                    # loudly — the cache-reload route must not let
                    # them vanish silently instead
                    Log.fatal("sharded construction does not support "
                              "query groups yet — drop group= or "
                              "sharded_shards")
                self._core = load_shard_cache(
                    config.sharded_cache_dir,
                    expect_world_size=config.sharded_shards,
                    config=config)
                if self.label is not None:
                    self._core.metadata.set_label(self.label)
                if self.weight is not None:
                    self._core.metadata.set_weight(self.weight)
                if self.init_score is not None:
                    self._core.metadata.set_init_score(self.init_score)
                if isinstance(self.feature_name, (list, tuple)):
                    self._core.feature_names = list(self.feature_name)
                self._core.pandas_categorical = None
                return self._core
        sharded_on = config.sharded_shards > 1 and self.reference is None
        streaming_ok = (isinstance(data, str)
                        and config.use_two_round_loading
                        and self.reference is None
                        and not sharded_on
                        and not isinstance(self.categorical_feature,
                                           (list, tuple)))
        if sharded_on and isinstance(data, str) \
                and config.use_two_round_loading:
            Log.warning("two_round loading is bypassed by sharded "
                        "construction: the file parses into one "
                        "in-RAM matrix before row-range splitting "
                        "(per-shard ingest still streams in "
                        "streaming_chunk_rows chunks)")
        if (isinstance(data, str) and config.use_two_round_loading
                and not streaming_ok and not sharded_on):
            Log.warning("two_round loading does not support reference-"
                        "aligned or explicitly-categorical datasets yet; "
                        "falling back to in-RAM loading")
        if streaming_ok:
            # two-round streaming: the float matrix never exists
            import time as _time

            from .data_loader import load_file_streaming
            from .telemetry import TELEMETRY
            t0 = _time.perf_counter()
            with TELEMETRY.span("binning"):
                self._core = load_file_streaming(data, config)
            wall = _time.perf_counter() - t0
            if wall > 0:
                TELEMETRY.gauge("construct_rows_per_s",
                                round(self._core.num_data / wall))
            if isinstance(self.feature_name, (list, tuple)):
                self._core.feature_names = list(self.feature_name)
            if self.label is not None:
                self._core.metadata.set_label(self.label)
            if self.weight is not None:
                self._core.metadata.set_weight(self.weight)
            if self.group is not None:
                self._core.metadata.set_group(self.group)
            if self.init_score is not None:
                self._core.metadata.set_init_score(self.init_score)
            self._core.pandas_categorical = None
            return self._core
        if isinstance(data, str):
            from .data_loader import load_file
            data, label_from_file, extras = load_file(data, config)
            if label is None:
                label = label_from_file
            if self.weight is None and extras.get("weight") is not None:
                self.weight = extras["weight"]
            if self.group is None and extras.get("group") is not None:
                self.group = extras["group"]
            if self.categorical_feature == "auto" \
                    and extras.get("categorical_feature"):
                # CLI categorical_column= spec, resolved by the loader
                # into post-drop feature indices (reference
                # dataset_loader.cpp categorical_feature handling)
                self.categorical_feature = extras["categorical_feature"]
        ref_core = None
        if self.reference is not None:
            # the reference may be a lazy handle or an already
            # constructed core (Booster.add_valid aligns to the core)
            ref_core = self.reference.construct(config) \
                if hasattr(self.reference, "construct") \
                else self.reference
        # validation frames must encode pandas categoricals against the
        # TRAIN-time category lists (the reference aligns valid frames
        # to the train categories and errors on mismatch)
        train_cats = getattr(ref_core, "pandas_categorical", None)
        pandas_cats = (train_cats if train_cats is not None
                       else _pandas_categories(data))
        data = _to_matrix(data, train_cats)
        if _is_sparse(data) and not config.is_enable_sparse:
            # reference is_enable_sparse=false: bypass the sparse-aware
            # construction and bin the dense matrix
            data = np.ascontiguousarray(
                np.asarray(data.todense(), dtype=np.float64))
        feature_names, cat_indices = self._resolve_columns(data)

        import time as _time

        from .telemetry import TELEMETRY
        if sharded_on and ref_core is None:
            # mesh-sharded construction (lightgbm_tpu/sharded/,
            # docs/Parallel-Learning-Guide.md "Sharded construction"):
            # distributed bin finding + per-shard streaming ingest;
            # reference-aligned (validation) datasets never shard —
            # they bin whole against the training mappers
            if _is_sparse(data):
                Log.warning("sharded_shards ignored for sparse input; "
                            "using the single-matrix sparse path")
            else:
                from .sharded import ShardedDataset, save_shard_cache
                t0 = _time.perf_counter()
                with TELEMETRY.span("binning", rows=int(data.shape[0])):
                    self._core = ShardedDataset.construct_sharded(
                        data, label=label, weight=self.weight,
                        group=self.group, init_score=self.init_score,
                        config=config,
                        categorical_features=cat_indices,
                        feature_names=feature_names)
                wall = _time.perf_counter() - t0
                if wall > 0:
                    TELEMETRY.gauge("construct_rows_per_s",
                                    round(int(data.shape[0]) / wall))
                if config.sharded_cache_dir:
                    save_shard_cache(self._core,
                                     config.sharded_cache_dir)
                self._core._raw_data = None if self.free_raw_data \
                    else data
                self._core.pandas_categorical = pandas_cats
                if self.free_raw_data:
                    self.data = None
                return self._core
        t0 = _time.perf_counter()
        with TELEMETRY.span("binning", rows=int(data.shape[0])):
            # host-side bin-mapper fit + matrix binning — the one
            # pre-device phase of training, decomposed into the
            # fit_mappers/bin/pack sub-spans (docs/OBSERVABILITY.md)
            self._core = CoreDataset.from_matrix(
                data, label=label, weight=self.weight, group=self.group,
                init_score=self.init_score, config=config,
                categorical_features=cat_indices,
                feature_names=feature_names, reference=ref_core)
        wall = _time.perf_counter() - t0
        if wall > 0:
            TELEMETRY.gauge("construct_rows_per_s",
                            round(int(data.shape[0]) / wall))
        self._core._raw_data = None if self.free_raw_data else data
        if self.free_raw_data and not _is_sparse(data) \
                and str(getattr(config, "quality", "off")).lower() \
                == "on":
            # quality=on + free_raw_data: the profile's leaf-occupancy
            # pass (pred_leaf) needs raw feature rows AFTER training,
            # but the float matrix dies right here — retain a
            # deterministic strided sample (quality_profile_rows cap)
            # instead of the whole matrix (docs/MODEL_MONITORING.md)
            from .quality.profile import strided_rows
            self._core._quality_row_sample = strided_rows(
                data, int(config.quality_profile_rows))
        self._core._categorical_features = cat_indices
        self._core.pandas_categorical = pandas_cats
        if self.free_raw_data:
            # drop the lazy handle's copy too (reference sets
            # Dataset.data = None after construction) — the binned
            # matrix is the training representation from here on
            self.data = None
        return self._core

    # ------------------------------------------------------------------
    def _resolve_columns(self, data: np.ndarray):
        n_cols = data.shape[1]
        feature_names = None
        if isinstance(self.feature_name, (list, tuple)):
            feature_names = list(self.feature_name)
        elif _is_pandas(self.data):
            feature_names = [str(c) for c in self.data.columns]
        cat_indices = []
        cf = self.categorical_feature
        if isinstance(cf, (list, tuple)):
            for c in cf:
                if isinstance(c, str):
                    if feature_names and c in feature_names:
                        cat_indices.append(feature_names.index(c))
                    else:
                        Log.warning(f"Unknown categorical column {c}")
                else:
                    cat_indices.append(int(c))
        elif cf == "auto" and _is_pandas(self.data):
            for i, dtype in enumerate(self.data.dtypes):
                if str(dtype) == "category":
                    cat_indices.append(i)
        return feature_names, cat_indices

    # ------------------------------------------------------------------
    def set_label(self, label):
        self.label = label
        if self._core is not None:
            self._core.metadata.set_label(label)
        return self

    def set_weight(self, weight):
        self.weight = weight
        if self._core is not None:
            self._core.metadata.set_weight(weight)
        return self

    def set_group(self, group):
        self.group = group
        if self._core is not None:
            self._core.metadata.set_group(group)
        return self

    def set_init_score(self, init_score):
        self.init_score = init_score
        if self._core is not None:
            self._core.metadata.set_init_score(init_score)
        return self

    def set_field(self, name, data):
        if name == "label":
            return self.set_label(data)
        if name == "weight":
            return self.set_weight(data)
        if name in ("group", "query"):
            return self.set_group(data)
        if name == "init_score":
            return self.set_init_score(data)
        Log.fatal(f"Unknown field {name}")

    def get_field(self, name):
        if self._core is not None:
            return self._core.metadata.get_field(name)
        return {"label": self.label, "weight": self.weight,
                "group": self.group, "init_score": self.init_score}.get(name)

    def get_label(self):
        return self.get_field("label")

    def get_weight(self):
        return self.get_field("weight")

    def get_group(self):
        return self.get_field("group")

    def get_init_score(self):
        return self.get_field("init_score")

    def num_data(self) -> int:
        if self._core is not None:
            return self._core.num_data
        d = self.data
        if isinstance(d, str):
            Log.fatal("Cannot get num_data before construction of a "
                      "file-backed Dataset")
        if _is_sparse(d):
            return d.shape[0]
        return _to_matrix(d).shape[0]

    def num_feature(self) -> int:
        if self._core is not None:
            return self._core.num_total_features
        if _is_sparse(self.data):
            return self.data.shape[1]
        return _to_matrix(self.data).shape[1]

    def set_reference(self, reference: "Dataset") -> "Dataset":
        """reference basic.py Dataset.set_reference: align this
        dataset's bin mappers to another's.  Must precede construct."""
        if self._core is not None and reference is not self.reference:
            Log.fatal("Cannot set reference after the Dataset has "
                      "been constructed; create a new Dataset")
        self.reference = reference
        return self

    def set_feature_name(self, feature_name) -> "Dataset":
        """reference basic.py Dataset.set_feature_name."""
        if isinstance(feature_name, (list, tuple)):
            nf = None
            if self._core is not None:
                nf = self._core.num_total_features
            elif getattr(self.data, "ndim", 0) == 2:
                nf = self.data.shape[1]
            if nf is not None and len(feature_name) != nf:
                Log.fatal(f"Length of feature_name "
                          f"({len(feature_name)}) does not match the "
                          f"number of features ({nf})")
            if self._core is not None:
                self._core.feature_names = list(feature_name)
        self.feature_name = feature_name
        return self

    def set_categorical_feature(self, categorical_feature) -> "Dataset":
        """reference basic.py Dataset.set_categorical_feature — the
        categorical set shapes the bin mappers, so it cannot change
        after construction."""
        if self._core is not None and \
                categorical_feature != self.categorical_feature:
            Log.fatal("Cannot set categorical feature after the "
                      "Dataset has been constructed; create a new "
                      "Dataset")
        self.categorical_feature = categorical_feature
        return self

    def construct_aligned(self, ref_core, config) -> CoreDataset:
        """Construct with bins aligned to ``ref_core`` when nothing
        pinned the mappers yet — the reference package's
        train()/add_valid set_reference behavior.  Already-constructed
        or explicitly-referenced datasets are left alone (the
        bin-alignment gate in gbdt.add_valid rejects mismatches)."""
        if self._core is None and self.reference is None:
            self.reference = ref_core
        return self.construct(config)

    def get_ref_chain(self, ref_limit: int = 100) -> set:
        """reference basic.py Dataset.get_ref_chain: the set of
        datasets reachable through .reference links."""
        head = self
        chain = set()
        count = 0
        while count < ref_limit:
            chain.add(head)
            if head.reference is not None and head.reference not in chain:
                head = head.reference
                count += 1
            else:
                break
        return chain

    def subset(self, used_indices, params=None) -> "Dataset":
        if self.data is None:
            Log.fatal("Cannot subset: raw data was freed — construct "
                      "the Dataset with free_raw_data=False")
        if _is_sparse(self.data):
            data = self.data.tocsr()[used_indices]
        else:
            data = _to_matrix(self.data)[used_indices]
        label = (None if self.label is None
                 else np.asarray(self.label)[used_indices])
        weight = (None if self.weight is None
                  else np.asarray(self.weight)[used_indices])
        return Dataset(data, label=label, weight=weight,
                       feature_name=self.feature_name,
                       categorical_feature=self.categorical_feature,
                       params=params or self.params, reference=self)

    def create_valid(self, data, label=None, weight=None, group=None,
                     init_score=None, params=None) -> "Dataset":
        return Dataset(data, label=label, reference=self, weight=weight,
                       group=group, init_score=init_score,
                       feature_name=self.feature_name,
                       categorical_feature=self.categorical_feature,
                       params=params or self.params)

    def save_binary(self, filename: str) -> "Dataset":
        from .dataset_io import save_binary
        save_binary(self.construct(), filename)
        return self


def _is_pandas(obj) -> bool:
    return type(obj).__module__.startswith("pandas") and \
        hasattr(obj, "dtypes")


def _to_matrix(data, pandas_categorical=None) -> np.ndarray:
    """Raw input -> float64 matrix.  Pandas category-dtype columns
    encode as their category codes; when ``pandas_categorical`` (the
    train-time category lists, in categorical-column order) is given,
    codes are computed AGAINST THOSE categories so a predict-time frame
    with reordered or fewer observed categories maps identically
    (reference basic.py pandas_categorical handling); unseen categories
    become NaN."""
    if isinstance(data, np.ndarray):
        return np.ascontiguousarray(data.astype(np.float64, copy=False))
    if _is_pandas(data) and not hasattr(data, "columns"):
        # a Series: single row of raw features
        return np.ascontiguousarray(np.asarray(data, dtype=np.float64))
    if _is_pandas(data):
        import pandas as pd
        n_cat = sum(1 for c in data.columns
                    if str(data[c].dtype) == "category")
        if pandas_categorical is not None \
                and n_cat != len(pandas_categorical):
            raise ValueError(
                "train and valid/predict dataset categorical_feature do "
                f"not match: trained with {len(pandas_categorical)} "
                f"categorical columns, got {n_cat}")
        cols = []
        i_cat = 0
        for c in data.columns:
            col = data[c]
            if str(col.dtype) == "category":
                if pandas_categorical is not None:
                    cats = pandas_categorical[i_cat]
                    codes = pd.Categorical(
                        col, categories=cats).codes.astype(np.float64)
                    codes[codes < 0] = np.nan
                else:
                    codes = col.cat.codes.to_numpy().astype(np.float64)
                cols.append(codes)
                i_cat += 1
            else:
                cols.append(col.to_numpy().astype(np.float64))
        return np.ascontiguousarray(np.stack(cols, axis=1))
    if _is_sparse(data):
        # sparse stays sparse: Dataset construction bins CSC columns
        # directly and prediction densifies in bounded row chunks —
        # the whole-matrix float64 densify of a 100k x 10k 99%-sparse
        # input would be 8 GB for 80 MB of payload
        return data.tocsc()
    return np.ascontiguousarray(np.asarray(data, dtype=np.float64))


def _is_sparse(obj) -> bool:
    return hasattr(obj, "tocsc") and hasattr(obj, "nnz")


def _pandas_categories(data):
    """Category lists of category-dtype columns, in column order (the
    reference's pandas_categorical model attribute)."""
    if not _is_pandas(data):
        return None
    cats = [list(data[c].cat.categories) for c in data.columns
            if str(data[c].dtype) == "category"]
    return cats or None
