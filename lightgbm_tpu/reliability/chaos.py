"""Seeded chaos scheduler: randomized multi-fault plans, replayable
from one integer.

The r12 harness proves the system survives one SCRIPTED fault at one
seam; production failures are compound and unscripted — a slow
collective plus a mid-ingest kill, an OOM during a publish, a
participant that hangs instead of dying.  This module extends the
fault-plan grammar (``reliability/faults.py``) with randomized plans
drawn from the registered seam table by a DETERMINISTIC PRNG, so the
space of compound failures gets explored without sacrificing the
harness's core property: any failing run replays exactly from its
printed seed.

Grammar extension (``LTPU_FAULT_PLAN`` / ``Config.fault_plan``)::

    chaos:<seed>:<n_faults>[:<seam_glob>]

``chaos:7:3`` draws three (seam, nth-call, action) tuples over every
registered seam; ``chaos:7:3:gbdt.*`` restricts the draw to seams
matching the glob (comma-separated patterns compose:
``gbdt.*,checkpoint.io``).  A chaos entry expands at parse time into
ordinary plan entries — the expansion is logged, and
:func:`chaos_spec` renders the same draw as a plain
``seam:nth:action`` plan for replay or bisection.  Chaos entries
compose with scripted ones: ``chaos:7:2;predict.dispatch:1:oom``.

Actions drawn (weighted uniformly): ``kill``, ``oom``, the transient
builtin exceptions (ConnectionError / TimeoutError / OSError), and
the two stall shapes the deadline watchdog exists for — ``hang:<ms>``
(blocks past any sane deadline, then errors: the op never completed)
and ``slow:<ms>`` (delays, then proceeds — must stay UNDER deadlines).
In-process callers (``scripts/chaos_probe.py`` serve/continuous
workloads) restrict the action set via :func:`chaos_entries`'s
``actions=`` so a drawn ``kill`` cannot take the probe down with the
workload.
"""
from __future__ import annotations

import fnmatch
import random
from typing import List, Sequence, Tuple

# the full drawable action set; "hang"/"slow" get a drawn duration
DEFAULT_ACTIONS = ("kill", "oom", "ConnectionError", "TimeoutError",
                   "OSError", "hang", "slow")
# transport seams additionally draw the network-shaped faults: a
# reset socket (peer_drop -> TransportPeerLost -> epoch-boundary
# reform), a laggy-but-live peer (peer_slow:<ms>, must stay under any
# armed watchdog_collective_s deadline), a bit-flipped frame (corrupt
# -> the CRC must catch it), a replayed frame (dup -> the seq
# dup-discard must drop it) and a severed-then-healed link
# (partition:<ms> -> the in-epoch reconnect must resync bit-exact)
TRANSPORT_ACTIONS = DEFAULT_ACTIONS + (
    "peer_drop", "peer_slow", "corrupt", "dup", "partition")
# hang durations default WELL past any test deadline (the watchdog is
# supposed to fire first); slow durations stay small (tolerated);
# partitions heal inside the reconnect budget
DEFAULT_HANG_MS = (2000, 8000)
DEFAULT_SLOW_MS = (5, 50)
DEFAULT_PARTITION_MS = (20, 120)


def chaos_seams(seam_glob: str = "*") -> List[str]:
    """Registered seams matching ``seam_glob`` (comma-separated
    fnmatch patterns).  An empty match is a hard error — a typo'd
    glob must not silently draw zero faults and turn a chaos run into
    a vacuous pass (the same contract as unknown seam names)."""
    from .faults import SEAMS
    pats = [p.strip() for p in str(seam_glob or "*").split(",")
            if p.strip()]
    out = [s for s in SEAMS
           if any(fnmatch.fnmatchcase(s, p) for p in pats)]
    if not out:
        raise ValueError(
            f"chaos seam glob {seam_glob!r} matches no registered "
            f"seam (registered: {', '.join(SEAMS)})")
    return out


def chaos_entries(seed: int, n_faults: int, seam_glob: str = "*",
                  actions: Sequence[str] = DEFAULT_ACTIONS,
                  max_nth: int = 4,
                  hang_ms: Tuple[int, int] = DEFAULT_HANG_MS,
                  slow_ms: Tuple[int, int] = DEFAULT_SLOW_MS,
                  partition_ms: Tuple[int, int] = DEFAULT_PARTITION_MS
                  ) -> List[Tuple[str, int, str]]:
    """Draw ``n_faults`` deterministic (seam, nth, action) tuples.
    Same arguments -> byte-identical plan, always (``random.Random``
    is a stable, versioned PRNG) — that determinism IS the replay
    guarantee.  ``(seam, nth)`` pairs are deduplicated so two draws
    cannot shadow each other at the same call."""
    if n_faults < 1:
        raise ValueError(f"chaos plan needs n_faults >= 1, got "
                         f"{n_faults}")
    rng = random.Random(int(seed))
    seams = chaos_seams(seam_glob)
    actions = tuple(actions)
    if int(n_faults) > len(seams) * max(1, int(max_nth)):
        # fault_point fires only the FIRST matching entry, so a
        # duplicate (seam, nth) draw would silently shadow another —
        # an overdrawn plan must error loudly, not quietly inject
        # fewer faults than it claims
        raise ValueError(
            f"chaos plan asks for {n_faults} faults but only "
            f"{len(seams) * max(1, int(max_nth))} distinct "
            f"(seam, nth) pairs exist for glob {seam_glob!r} with "
            f"max_nth={max_nth}")
    entries: List[Tuple[str, int, str]] = []
    used = set()
    for _ in range(int(n_faults)):
        seam, nth = None, None
        while True:
            seam = rng.choice(seams)
            nth = rng.randint(1, max(1, int(max_nth)))
            if (seam, nth) not in used:
                break
        used.add((seam, nth))
        pool = actions
        if seam.startswith("transport.") and actions == DEFAULT_ACTIONS:
            # only the DEFAULT pool widens — in-process probes that
            # restricted the action set keep their restriction
            pool = TRANSPORT_ACTIONS
        action = rng.choice(pool)
        if action == "hang":
            action = f"hang:{rng.randint(*hang_ms)}"
        elif action in ("slow", "peer_slow"):
            action = f"{action}:{rng.randint(*slow_ms)}"
        elif action == "partition":
            action = f"partition:{rng.randint(*partition_ms)}"
        entries.append((seam, nth, action))
    return entries


def chaos_spec(seed: int, n_faults: int, seam_glob: str = "*",
               **kwargs) -> str:
    """The drawn plan rendered in the PLAIN grammar
    (``seam:nth:action;...``) — what a failing chaos run prints for
    replay/bisection, and what in-process probes feed
    ``FAULTS.configure`` directly."""
    return ";".join(f"{seam}:{nth}:{action}" for seam, nth, action
                    in chaos_entries(seed, n_faults, seam_glob,
                                     **kwargs))


def parse_chaos_entry(parts: List[str]):
    """Expand one ``chaos:<seed>:<n>[:<glob>]`` plan entry (already
    colon-split) into concrete ``faults._Entry`` objects.  Called by
    ``faults.parse_plan``; malformed specs raise ValueError like every
    other grammar violation."""
    from ..utils.log import Log
    from .faults import _Entry
    if len(parts) not in (3, 4):
        raise ValueError(
            "chaos plan entry must be chaos:<seed>:<n_faults>"
            f"[:<seam_glob>], got {':'.join(parts)!r}")
    seed_s, n_s = parts[1].strip(), parts[2].strip()
    try:
        seed = int(seed_s)
    except ValueError:
        raise ValueError(f"chaos seed {seed_s!r} must be an integer") \
            from None
    if not n_s.isdigit() or int(n_s) < 1:
        raise ValueError(f"chaos fault count {n_s!r} must be a "
                         "positive integer")
    glob = parts[3].strip() if len(parts) == 4 else "*"
    drawn = chaos_entries(seed, int(n_s), glob)
    Log.info(
        f"chaos plan seed={seed} n={n_s} glob={glob!r} expanded to: "
        + "; ".join(f"{s}:{n}:{a}" for s, n, a in drawn)
        + f" — replay with chaos:{seed}:{n_s}"
        + (f":{glob}" if glob != "*" else ""))
    return [_Entry(seam, nth, action.split(":")[0], 1,
                   duration_ms=int(action.split(":")[1])
                   if ":" in action else 0)
            for seam, nth, action in drawn]
