"""Fault-tolerance subsystem (docs/RELIABILITY.md).

Four pieces, one package:

- ``checkpoint``  — crash-safe full-training-state checkpoints
  (versioned container, atomic writes, rolling retention,
  fingerprinted resume; ``engine.train(resume=...)``).
- ``faults``      — deterministic fault-injection harness: registered
  seams + the ``LTPU_FAULT_PLAN`` plan grammar; every recovery test
  drives its failure through this, never through sleeps or races.
- ``retry``       — bounded exponential backoff + jitter around
  transient-classified errors (dispatch + distributed-init seams).
- OOM degradation lives at the call sites (``booster.py`` serving
  ladder, ``engine.py`` chunk downshift) keyed on ``retry.is_oom``.
"""
from .checkpoint import (CheckpointError, atomic_write_text,  # noqa: F401
                         find_resume, list_checkpoints, prune_snapshots,
                         read_checkpoint, save_checkpoint, save_rolling,
                         training_fingerprint)
from .faults import FAULTS, FaultInjected, parse_plan  # noqa: F401
from .retry import (RetryPolicy, is_oom, is_transient,  # noqa: F401
                    retry_call)
