"""Fault-tolerance subsystem (docs/RELIABILITY.md).

Seven pieces, one package:

- ``checkpoint``  — crash-safe full-training-state checkpoints
  (versioned container, atomic writes, rolling retention,
  fingerprinted resume; ``engine.train(resume=...)``).
- ``faults``      — deterministic fault-injection harness: registered
  seams + the ``LTPU_FAULT_PLAN`` plan grammar (kill/oom/exception
  plus the ``hang:<ms>``/``slow:<ms>`` stall shapes); every recovery
  test drives its failure through this, never through sleeps or races.
- ``chaos``       — seeded chaos scheduler: ``chaos:<seed>:<n>`` plan
  entries draw randomized multi-fault combinations from the seam
  table with a deterministic PRNG, replayable from the seed.
- ``watchdog``    — per-phase deadline watchdog: bounded stalls
  (all-thread stack flight dumps + classified ``StallError`` through
  the retry machinery) instead of silent hangs.
- ``invariants``  — machine-checked postconditions evaluated after
  every chaos run (byte-identical resume, no partial artifacts,
  ledger convergence, serving parity, loud failure).
- ``retry``       — bounded exponential backoff + jitter around
  transient-classified errors (dispatch + distributed-init seams).
- OOM degradation lives at the call sites (``booster.py`` serving
  ladder, ``engine.py`` chunk downshift) keyed on ``retry.is_oom``.
"""
from .chaos import chaos_entries, chaos_spec  # noqa: F401
from .checkpoint import (CheckpointError, atomic_write_text,  # noqa: F401
                         find_resume, list_checkpoints, prune_snapshots,
                         read_checkpoint, save_checkpoint, save_rolling,
                         training_fingerprint)
from .faults import FAULTS, FaultInjected, parse_plan  # noqa: F401
from .invariants import (ChaosContext, run_invariants,  # noqa: F401
                         violations)
from .retry import (RetryPolicy, is_oom, is_transient,  # noqa: F401
                    retry_call)
from .watchdog import (WATCHDOG, StallError,  # noqa: F401
                       run_with_deadline)
