"""Crash-safe training checkpoints: versioned container, atomic
writes, rolling retention, fingerprinted resume.

A SIGKILL mid-train used to lose everything except the lossy
``snapshot_iter_N`` model text — model weights without the score
cache, RNG streams or early-stopping bookkeeping, so a "resume" from
one silently trains a DIFFERENT model.  A checkpoint here captures
FULL training state (``GBDT.capture_state``): interrupted-then-resumed
training produces byte-identical trees to an uninterrupted run, and
``tests/test_reliability.py`` pins that equivalence with a real
SIGKILL injected through the fault harness.

Container layout (``docs/RELIABILITY.md``)::

    offset  size  field
    0       10    magic  b"LTPUCKPT1\\n"
    10      4     schema version (u32 LE)
    14      4     fingerprint length F (u32 LE)
    18      F     fingerprint (ascii sha256 hexdigest of the config +
                  dataset identity — resume refuses state from a
                  different run setup)
    18+F    8     payload length P (u64 LE)
    26+F    P     payload (pickled state dict)
    26+F+P  32    sha256 over bytes [0, 26+F+P)

Every read validates magic, schema, both length fields and the
trailing digest before unpickling; ANY violation raises
``CheckpointError`` — a torn, truncated or bit-flipped file is
rejected loudly and the resume scan falls back to the previous valid
checkpoint.  Writes are atomic: tmp file in the same directory,
flush + fsync, ``os.replace``, best-effort directory fsync — a crash
at any instant leaves either the old file or the new one, never a
hybrid.
"""
from __future__ import annotations

import glob
import hashlib
import os
import pickle
import re
import struct
from typing import Dict, List, Optional, Tuple

from ..utils.log import Log
from . import watchdog as _watchdog
from .faults import FAULTS

MAGIC = b"LTPUCKPT1\n"
SCHEMA_VERSION = 1
# hard sanity bound on the pickled-state length field: a value past
# this is a corrupted (or hostile) file, not a real training state
_MAX_PAYLOAD_BYTES = 1 << 40


class CheckpointError(Exception):
    """A checkpoint file failed validation (magic/schema/length/
    checksum/fingerprint) — the caller falls back or starts cold."""


# ---------------------------------------------------------------------------
# atomic writes (shared by checkpoints AND model snapshots)
# ---------------------------------------------------------------------------
def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                     os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - fs-dependent (e.g. NFS)
        pass


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp-write -> flush -> fsync -> rename: a crash leaves either
    the old file or the new file, never a torn hybrid.  With
    ``watchdog_checkpoint_s`` armed the whole write is deadline-
    bounded: a wedged filesystem (hung NFS, dead disk) surfaces as a
    classified ``StallError`` with all-thread stacks dumped instead
    of freezing training silently.  The tmp name carries the WRITER
    THREAD's id beside the pid: a deadline-abandoned writer may still
    be mid-write when the caller retries the same path on a fresh
    worker, and a shared tmp name would let the two interleave into a
    torn file that one of them renames into place.  (A slow-but-alive
    abandoned writer can still late-rename its own COMPLETE, stale
    bytes over a newer write — each renamed file stays internally
    consistent, and the checkpoint/ledger machinery already tolerates
    falling back to an older consistent state by replay.)"""
    def _write():
        import threading
        FAULTS.fault_point("checkpoint.io")
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        _fsync_dir(path)

    _watchdog.run_with_deadline(_write, _watchdog.deadline("checkpoint"),
                                phase="checkpoint_io",
                                seam="checkpoint.io")


def atomic_write_text(path: str, text: str) -> None:
    """The atomic writer for model text (snapshots, final saves that
    opt in) — ``save_model`` used to bare-``open`` and a kill mid-write
    left a torn, unparseable model file."""
    atomic_write_bytes(path, text.encode("utf-8"))


# ---------------------------------------------------------------------------
# container read/write
# ---------------------------------------------------------------------------
def save_checkpoint(path: str, state: dict, fingerprint: str) -> int:
    """Serialize ``state`` into the versioned container at ``path``
    (atomically).  Returns bytes written."""
    fp = fingerprint.encode("ascii")
    payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
    blob = b"".join([
        MAGIC,
        struct.pack("<I", SCHEMA_VERSION),
        struct.pack("<I", len(fp)), fp,
        struct.pack("<Q", len(payload)), payload,
    ])
    blob += hashlib.sha256(blob).digest()
    atomic_write_bytes(path, blob)
    return len(blob)


def read_checkpoint(path: str,
                    expect_fingerprint: Optional[str] = None
                    ) -> Tuple[str, dict]:
    """Validate and load one checkpoint file.  Raises
    ``CheckpointError`` naming the first violated invariant."""
    def _read() -> bytes:
        FAULTS.fault_point("checkpoint.io")
        with open(path, "rb") as f:
            return f.read()

    try:
        # deadline-bounded like the writes: a read that hangs raises
        # StallError (NOT CheckpointError — a stalled filesystem is an
        # environment failure, not a corrupt file, so the resume scan
        # must not silently "fall back" past a checkpoint it never read)
        blob = _watchdog.run_with_deadline(
            _read, _watchdog.deadline("checkpoint"),
            phase="checkpoint_io", seam="checkpoint.io")
    except _watchdog.StallError:
        # re-raise BEFORE the OSError arm: StallError subclasses
        # TimeoutError (hence OSError), and letting it convert to
        # CheckpointError would hand find_resume license to silently
        # skip a valid newer checkpoint it never actually read
        raise
    except OSError as e:
        raise CheckpointError(f"cannot read checkpoint {path}: {e}") \
            from e
    if len(blob) < len(MAGIC) + 4 + 4 + 8 + 32:
        raise CheckpointError(f"{path}: truncated (only {len(blob)} "
                              "bytes)")
    if not blob.startswith(MAGIC):
        raise CheckpointError(f"{path}: bad magic (not a checkpoint "
                              "file)")
    body, digest = blob[:-32], blob[-32:]
    if hashlib.sha256(body).digest() != digest:
        raise CheckpointError(f"{path}: checksum mismatch (torn or "
                              "bit-flipped file)")
    off = len(MAGIC)
    (schema,) = struct.unpack_from("<I", body, off)
    off += 4
    if schema != SCHEMA_VERSION:
        raise CheckpointError(f"{path}: schema version {schema} "
                              f"(this build reads {SCHEMA_VERSION})")
    (fp_len,) = struct.unpack_from("<I", body, off)
    off += 4
    if off + fp_len > len(body):
        raise CheckpointError(f"{path}: fingerprint length {fp_len} "
                              "exceeds file")
    fingerprint = body[off:off + fp_len].decode("ascii", "replace")
    off += fp_len
    (p_len,) = struct.unpack_from("<Q", body, off)
    off += 8
    if p_len > _MAX_PAYLOAD_BYTES or off + p_len != len(body):
        raise CheckpointError(f"{path}: payload length {p_len} does "
                              "not match file size")
    if expect_fingerprint is not None and \
            fingerprint != expect_fingerprint:
        raise CheckpointError(
            f"{path}: fingerprint mismatch — checkpoint was written "
            "by a different config/dataset (expected "
            f"{expect_fingerprint[:12]}..., found "
            f"{fingerprint[:12]}...)")
    try:
        state = pickle.loads(body[off:off + p_len])
    except Exception as e:
        raise CheckpointError(f"{path}: payload unpickle failed "
                              f"({type(e).__name__}: {e})") from e
    return fingerprint, state


# ---------------------------------------------------------------------------
# rolling files + resume scan
# ---------------------------------------------------------------------------
def checkpoint_file(prefix: str, iteration: int) -> str:
    return f"{prefix}_iter_{int(iteration)}"


def _iter_files(base: str, sep: str) -> List[Tuple[int, str]]:
    """[(iteration, path)] newest-first for ``<base><sep><N>`` files
    (ignores tmp files) — the one file-listing used by checkpoint
    retention, snapshot retention and the resume scan."""
    out = []
    pat = re.compile(re.escape(os.path.basename(base))
                     + re.escape(sep) + r"(\d+)$")
    for path in glob.glob(glob.escape(base) + sep + "*"):
        m = pat.match(os.path.basename(path))
        if m:
            out.append((int(m.group(1)), path))
    out.sort(reverse=True)
    return out


def _prune(files: List[Tuple[int, str]], keep: int) -> None:
    """Delete everything past the newest ``keep`` (keep<=0 keeps
    all)."""
    if keep <= 0:
        return
    for _it, old in files[keep:]:
        try:
            os.unlink(old)
        except OSError:
            pass


def list_checkpoints(prefix: str) -> List[Tuple[int, str]]:
    """[(iteration, path)] newest-first; ignores tmp files."""
    return _iter_files(prefix, "_iter_")


def save_rolling(prefix: str, iteration: int, state: dict,
                 fingerprint: str, keep: int = 2) -> str:
    """Write the iteration's checkpoint, then prune to the newest
    ``keep`` files.  The new file is fully durable (fsync'd) BEFORE
    any old one is deleted, so a crash inside this function always
    leaves at least one valid checkpoint behind."""
    path = checkpoint_file(prefix, iteration)
    save_checkpoint(path, state, fingerprint)
    _prune(list_checkpoints(prefix), keep)
    return path


def find_resume(prefix: str, fingerprint: str,
                max_iteration: Optional[int] = None
                ) -> Optional[Tuple[int, dict, str]]:
    """Scan ``<prefix>_iter_*`` newest-first for the first VALID
    checkpoint matching ``fingerprint``.  Corrupt/truncated/mismatched
    files are rejected loudly (Log.warning) and the scan falls back to
    the next-older candidate; returns None when nothing valid exists
    (the caller starts cold).  ``max_iteration`` skips checkpoints
    PAST the requested training target (a previous longer run) —
    auto-resuming one would return more trees than asked for."""
    for iteration, path in list_checkpoints(prefix):
        if max_iteration is not None and iteration > max_iteration:
            Log.warning(
                f"skipping checkpoint {path}: iteration {iteration} is "
                f"past the requested target {max_iteration} (resume "
                "from it explicitly to keep the longer model)")
            continue
        try:
            _fp, state = read_checkpoint(path, fingerprint)
        except CheckpointError as e:
            Log.warning(f"rejecting checkpoint: {e}; falling back to "
                        "an older one")
            continue
        return iteration, state, path
    return None


# ---------------------------------------------------------------------------
# fingerprint + snapshot retention
# ---------------------------------------------------------------------------
# config fields that do NOT change what gets trained: IO paths, task
# routing, serving/telemetry/reliability knobs, and the dispatch
# chunking (chunk length is byte-parity pinned by test_packed_carry).
# num_iterations is excluded deliberately so a run can be RESUMED WITH
# A LARGER TARGET (extend training) from an existing checkpoint.
_FP_EXCLUDE_EXACT = frozenset({
    "task", "data", "valid_data", "input_model", "output_model",
    "output_result", "convert_model", "convert_model_language",
    "num_iterations", "verbose", "output_freq", "extra",
    "machines", "machine_list_file", "local_listen_port", "time_out",
    "compile_cache_dir", "dispatch_chunk", "force_pallas_interpret",
    "num_iteration_predict", "num_threads", "construct_threads",
    "is_save_binary_file", "binary_cache_v2", "native_binning",
})
_FP_EXCLUDE_PREFIX = ("telemetry", "predict_", "is_predict_",
                      "pred_early_stop", "snapshot_", "checkpoint_",
                      "resume", "fault_plan", "dispatch_retries",
                      "retry_backoff", "oom_downshift", "serve_",
                      "flight_recorder", "continuous_", "watchdog_",
                      "sharded_allow_degraded")


def training_fingerprint(config, dataset, num_valid: int = 0,
                         init_model: str = "") -> str:
    """sha256 identity of (training-relevant config) + (dataset
    shape/binning/labels) + valid-set count + init-model identity.
    Two runs with equal fingerprints train the same trees at every
    iteration, so a checkpoint from one is resumable by the other.
    ``init_model`` is the engine-level continued-training seed (path
    string, or a marker for an in-memory booster): a run continued
    FROM a previous model must never adopt a fresh run's checkpoint,
    or vice versa — its scores and tree list start differently."""
    import dataclasses as _dc
    import zlib

    import numpy as np
    parts = []
    for f in sorted(_dc.fields(config), key=lambda f: f.name):
        name = f.name
        if name in _FP_EXCLUDE_EXACT or \
                any(name.startswith(p) for p in _FP_EXCLUDE_PREFIX):
            continue
        parts.append(f"{name}={getattr(config, name)!r}")
    parts.append(f"num_data={dataset.num_data}")
    parts.append(f"num_features={dataset.num_total_features}")
    parts.append("feature_infos=" + " ".join(dataset.feature_infos()))
    md = dataset.metadata
    for field in ("label", "weight", "init_score"):
        arr = getattr(md, field, None)
        crc = 0 if arr is None else zlib.crc32(
            np.ascontiguousarray(arr).tobytes())
        parts.append(f"{field}_crc={crc:#x}")
    qb = getattr(md, "query_boundaries", None)
    parts.append("group_crc=%#x" % (0 if qb is None else zlib.crc32(
        np.ascontiguousarray(qb).tobytes())))
    parts.append(f"num_valid={num_valid}")
    parts.append(f"init_model={init_model!r}")
    return hashlib.sha256("\n".join(parts).encode()).hexdigest()


def prune_snapshots(output_model: str, keep: int) -> None:
    """Rolling retention for ``<output_model>.snapshot_iter_N`` model
    snapshots (``snapshot_keep``; 0 keeps everything)."""
    _prune(_iter_files(output_model, ".snapshot_iter_"), keep)
