"""System-wide invariant registry: machine-checked postconditions for
chaos runs.

A chaos run is only evidence if something CHECKS the wreckage.  Every
invariant here is a named predicate over a :class:`ChaosContext` —
the artifacts a faulted run left behind (model files, work
directories, ledgers, served responses, exit codes, flight dumps) —
returning a list of human-readable violations (empty = holds).  The
chaos probe (``scripts/chaos_probe.py``) and ``tests/test_chaos.py``
evaluate the full registry after every run; a violation fails the run
with the seed printed, so the exact fault combination replays.

The catalog (docs/RELIABILITY.md, "Chaos testing"):

``resume_byte_identical``
    A killed-then-resumed run's final model is byte-identical to an
    uninterrupted reference (the r12 checkpoint contract, now held
    under RANDOMIZED fault combinations).
``no_partial_artifacts``
    No orphaned tmp/partial files anywhere in the work directory —
    the atomic writers (tmp + fsync + rename) must never leak a torn
    hybrid, no matter where the fault landed.
``ledger_converges``
    The continuous lane's ledger parses, carries a known schema and a
    replayable phase — a crash replays FROM the ledger, so a ledger
    the state machine cannot re-enter is lost work.
``serving_parity``
    Every successful serving response is byte-identical to a direct
    ``Booster.predict`` of the same rows — degraded or faulted
    serving must never be SILENTLY wrong (mixed-version or corrupted
    slices).
``loud_failure``
    Whenever work was lost, the process exited nonzero AND a flight
    dump names the seam that fired — no silent partial success.
``transport_no_silent_misdata``
    A chaos ``corrupt`` (bit-flipped frame in flight) is ALWAYS
    caught: the CRC counter fired or the run failed loudly, and any
    completed run's collective results are bit-identical to the
    fault-free expectation — never silent wrong bytes.
``partition_heals``
    A healed ``partition:<ms>`` leaves the world byte-identical and
    UNDEGRADED: results match the fault-free expectation, the world
    size is unchanged, and the in-epoch reconnect path (not the
    degrade path) did the healing (``collective_tcp_reconnects`` > 0).
``coordinator_failover``
    A coordinator killed mid-run does not kill the run: the lowest
    surviving rank takes over (a ``coordinator_change`` journal event
    exists), the run completes, and the results are byte-identical to
    the uninterrupted reference.

Invariants skip (return no violations) when their inputs are absent
from the context, so one registry serves train, serve, continuous and
transport workloads.
"""
from __future__ import annotations

import glob as _glob
import json
import os
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

# patterns an atomic writer's crash could conceivably leak — the
# checkpoint/ledger/model writers use ``<name>.tmp-<pid>``, the
# flight recorder ``<name>.tmp``
PARTIAL_PATTERNS = ("*.tmp", "*.tmp-*")

INVARIANTS: Dict[str, Callable] = {}


def invariant(name: str):
    def _wrap(fn):
        INVARIANTS[name] = fn
        return fn
    return _wrap


class ChaosContext:
    """The artifacts one chaos run left behind.  Every field is
    optional; an invariant whose inputs are missing skips.

    Fields: ``workdir`` (scanned for partial artifacts),
    ``reference_model`` / ``final_model`` (paths compared byte-wise),
    ``ledger_path``, ``served`` / ``expected`` (prediction arrays),
    ``exit_code`` + ``work_lost`` + ``flight_dumps`` (loud-failure
    evidence), ``seed`` + ``plan`` (replay identity, echoed into
    violations).

    Transport-chaos fields (all optional, set by the transport
    workload): ``transport_result`` / ``transport_expected`` (lists of
    collective result arrays, compared bit-wise),
    ``transport_counters`` (telemetry counter snapshot),
    ``transport_events`` (journal event kinds seen),
    ``transport_corrupt_fired`` / ``transport_partition_fired`` /
    ``coordinator_killed`` (which faults the plan injected),
    ``transport_failed`` (the run raised — loud, acceptable for
    corrupt; fatal for partition/failover), ``transport_world_start``
    / ``transport_world_end`` (degradation evidence)."""

    def __init__(self, workdir: Optional[str] = None,
                 reference_model: Optional[str] = None,
                 final_model: Optional[str] = None,
                 ledger_path: Optional[str] = None,
                 served=None, expected=None,
                 exit_code: Optional[int] = None,
                 work_lost: bool = False,
                 flight_dumps: Optional[Sequence[str]] = None,
                 seed: Optional[int] = None, plan: str = "",
                 transport_result=None, transport_expected=None,
                 transport_counters: Optional[Dict[str, float]] = None,
                 transport_events: Optional[Sequence[str]] = None,
                 transport_corrupt_fired: bool = False,
                 transport_partition_fired: bool = False,
                 coordinator_killed: bool = False,
                 transport_failed: bool = False,
                 transport_world_start: Optional[int] = None,
                 transport_world_end: Optional[int] = None):
        self.workdir = workdir
        self.reference_model = reference_model
        self.final_model = final_model
        self.ledger_path = ledger_path
        self.served = served
        self.expected = expected
        self.exit_code = exit_code
        self.work_lost = bool(work_lost)
        self.flight_dumps = list(flight_dumps or [])
        self.seed = seed
        self.plan = plan
        self.transport_result = transport_result
        self.transport_expected = transport_expected
        self.transport_counters = dict(transport_counters or {})
        self.transport_events = list(transport_events or [])
        self.transport_corrupt_fired = bool(transport_corrupt_fired)
        self.transport_partition_fired = bool(
            transport_partition_fired)
        self.coordinator_killed = bool(coordinator_killed)
        self.transport_failed = bool(transport_failed)
        self.transport_world_start = transport_world_start
        self.transport_world_end = transport_world_end


@invariant("resume_byte_identical")
def _resume_byte_identical(ctx: ChaosContext) -> List[str]:
    if not ctx.reference_model or not ctx.final_model:
        return []
    if not os.path.exists(ctx.final_model):
        return [f"final model {ctx.final_model} missing after "
                "resume"]
    with open(ctx.reference_model, "rb") as a, \
            open(ctx.final_model, "rb") as b:
        ra, rb = a.read(), b.read()
    if ra != rb:
        return [f"resumed model {ctx.final_model} differs from the "
                f"uninterrupted reference {ctx.reference_model} "
                f"({len(rb)} vs {len(ra)} bytes)"]
    return []


@invariant("no_partial_artifacts")
def _no_partial_artifacts(ctx: ChaosContext) -> List[str]:
    if not ctx.workdir or not os.path.isdir(ctx.workdir):
        return []
    leaked: List[str] = []
    for pat in PARTIAL_PATTERNS:
        leaked.extend(_glob.glob(os.path.join(
            _glob.escape(ctx.workdir), "**", pat), recursive=True))
    return [f"orphaned partial artifact: {p}"
            for p in sorted(set(leaked))]


@invariant("ledger_converges")
def _ledger_converges(ctx: ChaosContext) -> List[str]:
    if not ctx.ledger_path:
        return []
    if not os.path.exists(ctx.ledger_path):
        return [f"ledger {ctx.ledger_path} missing"]
    try:
        with open(ctx.ledger_path) as f:
            led = json.load(f)
    except ValueError as e:
        return [f"ledger {ctx.ledger_path} does not parse: {e} — a "
                "crash cannot replay from it"]
    out: List[str] = []
    from ..continuous.lane import LEDGER_SCHEMA, PHASES
    if led.get("schema") != LEDGER_SCHEMA:
        out.append(f"ledger schema {led.get('schema')!r} is not "
                   f"{LEDGER_SCHEMA}")
    if led.get("phase") not in PHASES + ("idle",):
        out.append(f"ledger phase {led.get('phase')!r} is not "
                   "re-enterable by the cycle state machine")
    for field in ("cycle", "processed", "published", "quarantined",
                  "last_good"):
        if field not in led:
            out.append(f"ledger lacks the {field!r} field a replay "
                       "reads")
    return out


@invariant("serving_parity")
def _serving_parity(ctx: ChaosContext) -> List[str]:
    if ctx.served is None or ctx.expected is None:
        return []
    served = np.asarray(ctx.served)
    expected = np.asarray(ctx.expected)
    if served.shape != expected.shape:
        return [f"served shape {served.shape} != direct-predict "
                f"shape {expected.shape}"]
    if not np.array_equal(served, expected):
        bad = int(np.sum(served != expected))
        return [f"{bad} served value(s) differ from direct predict — "
                "serving went silently wrong under faults"]
    return []


@invariant("loud_failure")
def _loud_failure(ctx: ChaosContext) -> List[str]:
    if not ctx.work_lost:
        return []
    out: List[str] = []
    if ctx.exit_code == 0:
        out.append("work was lost but the process exited 0 — a "
                   "silent partial success")
    seams = set()
    for path in ctx.flight_dumps:
        try:
            with open(path) as f:
                seams.add(json.load(f).get("seam", ""))
        except (OSError, ValueError):
            continue
    if not any(seams - {""}):
        out.append("work was lost but no flight dump names the seam "
                   f"that fired (dumps scanned: {len(ctx.flight_dumps)})")
    return out


def _transport_mismatches(ctx: ChaosContext) -> List[str]:
    """Bit-compare the transport workload's collective results
    against the fault-free expectation (both lists of arrays)."""
    if ctx.transport_result is None or ctx.transport_expected is None:
        return []
    got = [np.asarray(a) for a in ctx.transport_result]
    want = [np.asarray(a) for a in ctx.transport_expected]
    if len(got) != len(want):
        return [f"{len(got)} collective result(s) vs "
                f"{len(want)} expected"]
    return [f"collective round {i} result differs from the "
            "fault-free expectation — bytes went silently wrong"
            for i, (g, w) in enumerate(zip(got, want))
            if g.shape != w.shape or not np.array_equal(g, w)]


@invariant("transport_no_silent_misdata")
def _transport_no_silent_misdata(ctx: ChaosContext) -> List[str]:
    if not ctx.transport_corrupt_fired:
        return []
    out: List[str] = []
    crc = ctx.transport_counters.get("collective_tcp_crc_errors", 0)
    if crc <= 0 and not ctx.transport_failed:
        out.append("a corrupt frame was injected but the CRC never "
                   "fired and the run did not fail loudly")
    if not ctx.transport_failed:
        out.extend(_transport_mismatches(ctx))
    return out


@invariant("partition_heals")
def _partition_heals(ctx: ChaosContext) -> List[str]:
    if not ctx.transport_partition_fired:
        return []
    if ctx.transport_failed:
        return ["a healed partition must not fail the run — the "
                "in-epoch reconnect should have resynced the round"]
    out = _transport_mismatches(ctx)
    if ctx.transport_counters.get("collective_tcp_reconnects", 0) <= 0:
        out.append("partition healed without a counted reconnect — "
                   "the degrade path, not the reconnect path, ran")
    if (ctx.transport_world_start is not None
            and ctx.transport_world_end is not None
            and ctx.transport_world_end != ctx.transport_world_start):
        out.append(f"world degraded {ctx.transport_world_start} -> "
                   f"{ctx.transport_world_end} across a TRANSIENT "
                   "partition")
    return out


@invariant("coordinator_failover")
def _coordinator_failover(ctx: ChaosContext) -> List[str]:
    if not ctx.coordinator_killed:
        return []
    if ctx.transport_failed:
        return ["coordinator death killed the run — the lowest "
                "surviving rank never took over"]
    out = _transport_mismatches(ctx)
    if "coordinator_change" not in ctx.transport_events:
        out.append("no coordinator_change journal event — the "
                   "successor never announced the takeover")
    return out


def run_invariants(ctx: ChaosContext,
                   names: Optional[Sequence[str]] = None
                   ) -> Dict[str, List[str]]:
    """Evaluate the registry (or the named subset) against ``ctx``;
    returns {invariant: violations} with every registered name
    present (empty list = holds/skipped)."""
    todo = list(names) if names is not None else list(INVARIANTS)
    unknown = [n for n in todo if n not in INVARIANTS]
    if unknown:
        raise ValueError(f"unknown invariant(s): {unknown} "
                         f"(registered: {sorted(INVARIANTS)})")
    return {name: INVARIANTS[name](ctx) for name in todo}


def violations(ctx: ChaosContext,
               names: Optional[Sequence[str]] = None) -> List[str]:
    """Flattened violation list, each prefixed with its invariant
    name (and the replay seed when the context carries one)."""
    tag = f"[seed {ctx.seed}] " if ctx.seed is not None else ""
    return [f"{tag}{name}: {v}"
            for name, vs in run_invariants(ctx, names).items()
            for v in vs]
