"""Retry with bounded exponential backoff + jitter, and the
transient / OOM error classification the recovery paths share.

Scope discipline: retries wrap only TRANSIENT-classified errors at
host seams that are safe to re-enter (the dispatch enqueue before any
state mutation, the distributed rendezvous, host collective calls).
An error that is not transient — a real bug, a shape mismatch, an OOM
— propagates immediately: OOM is handled by the degradation ladders
(``docs/RELIABILITY.md``), never by blind re-dispatch of the exact
allocation that just failed.
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional

from ..telemetry import TELEMETRY
from ..utils.log import Log

# connection-/scheduling-shaped builtin exceptions are transient by
# type; everything else is classified by message marker (jax surfaces
# backend RPC errors as XlaRuntimeError with the grpc status text)
TRANSIENT_TYPES = (ConnectionError, TimeoutError, InterruptedError)
TRANSIENT_MARKERS = (
    "unavailable", "deadline exceeded", "deadline_exceeded",
    "connection reset", "connection refused", "broken pipe",
    "temporarily unavailable", "socket closed", "transient",
    "try again",
)
OOM_MARKERS = (
    "resource_exhausted", "resource exhausted", "out of memory",
    "failed to allocate", "allocation failure", "oom killed",
)


def is_oom(exc: BaseException) -> bool:
    """Whether ``exc`` is a device/host memory-exhaustion error (the
    degradation ladders key on this; jax raises XlaRuntimeError with a
    RESOURCE_EXHAUSTED status on device OOM)."""
    msg = str(exc).lower()
    return any(m in msg for m in OOM_MARKERS)


def is_transient(exc: BaseException) -> bool:
    """Whether ``exc`` is worth retrying: connection/timeout shaped,
    or carrying an RPC-unavailability marker — and NOT an OOM (the
    same allocation would fail again; degrade instead)."""
    if is_oom(exc):
        return False
    if isinstance(exc, TRANSIENT_TYPES):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in TRANSIENT_MARKERS)


@dataclasses.dataclass
class RetryPolicy:
    """Bounded exponential backoff: attempt k (0-based) sleeps
    ``min(max_delay_s, base_delay_s * 2**k)`` scaled by a uniform
    jitter in [1, 1+jitter] (decorrelates a fleet of workers retrying
    the same dead endpoint).

    The bound is ``max_retries`` attempts — UNLESS ``budget_s`` is
    set, in which case the TIME budget governs instead: retries
    continue (with the backoff still growing toward ``max_delay_s``)
    until the next sleep would exceed ``budget_s`` cumulative.  That
    is the reference ``time_out`` semantic at the rendezvous seam: a
    coordinator that needs two minutes to come up is waited out for
    the configured minutes, not for three fixed attempts."""

    max_retries: int = 2
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    jitter: float = 0.25
    budget_s: Optional[float] = None

    @classmethod
    def from_config(cls, config) -> "RetryPolicy":
        return cls(
            max_retries=max(0, int(getattr(config, "dispatch_retries",
                                           2))),
            base_delay_s=max(0.0, float(getattr(config,
                                                "retry_backoff_s",
                                                0.5))))

    def delay(self, attempt: int, rng: random.Random) -> float:
        d = min(self.max_delay_s, self.base_delay_s * (2 ** attempt))
        return d * (1.0 + self.jitter * rng.random())


def retry_call(fn: Callable, *args, policy: Optional[RetryPolicy] = None,
               seam: str = "", classify: Callable = is_transient,
               sleep: Callable = time.sleep, **kwargs):
    """Call ``fn`` retrying transient-classified failures under
    ``policy``.  Retries count into the ``retries`` telemetry counter
    and warn with the seam name; exhaustion (or a non-transient error)
    re-raises the LAST error unchanged so callers and tests see the
    original failure, not a wrapper."""
    policy = policy or RetryPolicy()
    rng = random.Random()
    spent = 0.0
    attempt = 0
    while True:
        try:
            return fn(*args, **kwargs)
        except Exception as e:  # noqa: BLE001 - classification decides
            if not classify(e):
                raise
            if policy.budget_s is None:
                if attempt >= policy.max_retries:
                    # retry budget exhausted: count it (Prometheus
                    # ltpu_retry_exhausted_total — exhaustion used to
                    # re-raise with no metric trail) and flight-dump
                    # the last-N telemetry events naming the seam
                    # before re-raising the original error (the dump
                    # is a no-op unless the recorder is armed)
                    TELEMETRY.add("retry_exhausted_total", 1)
                    TELEMETRY.flight.dump("retry_exhausted", seam=seam,
                                          attempts=attempt + 1,
                                          budget="max_retries="
                                          f"{policy.max_retries}",
                                          error=repr(e)[:300])
                    raise
                d = policy.delay(attempt, rng)
            else:
                # time-budget mode: the count bound is the budget, not
                # max_retries; floor the delay so a zero base backoff
                # cannot hot-spin the budget away
                d = max(policy.delay(attempt, rng), 0.05)
                if spent + d > policy.budget_s:
                    TELEMETRY.add("retry_exhausted_total", 1)
                    TELEMETRY.flight.dump("retry_exhausted", seam=seam,
                                          attempts=attempt + 1,
                                          budget=f"{policy.budget_s:g}s"
                                          f" (spent {spent:.2f}s)",
                                          error=repr(e)[:300])
                    raise
            TELEMETRY.add("retries", 1)
            bound = (f"{policy.budget_s:.0f}s budget"
                     if policy.budget_s is not None
                     else f"of {policy.max_retries}")
            Log.warning(
                f"transient error at {seam or 'call'} (attempt "
                f"{attempt + 1} {bound}): {e!r}; retrying in {d:.2f}s")
            sleep(d)
            spent += d
            attempt += 1
