"""Deterministic fault-injection harness.

A production jax_graft stack must survive preempted workers, killed
jobs, transient dispatch/RPC errors and memory pressure — and none of
that machinery is trustworthy unless every recovery path can be
EXERCISED on demand.  This module is the one mechanism all recovery
tests share: named *seams* wrap the process's failure-prone
boundaries (device dispatch, collectives, cache file IO, native-lib
entry), and a declarative *fault plan* says exactly which call at
which seam fails and how.  No sleeps, no signal races, no flaky
timing — the Nth call at seam S fails, every time.

Plan grammar (``LTPU_FAULT_PLAN`` env var or ``Config.fault_plan``)::

    plan   := entry (';' entry)*
    entry  := seam ':' nth ':' action [':x' count]
            | 'chaos' ':' seed ':' n_faults [':' seam_glob]
    seam   := registered seam name (see SEAMS below)
    nth    := 1-based call index at that seam
    action := 'kill'            -- SIGKILL the process (no cleanup,
                                   no atexit: the crash-consistency
                                   ground truth for checkpoint tests)
            | 'oom'             -- raise FaultInjected with a
                                   RESOURCE_EXHAUSTED message (what
                                   the OOM-degradation ladders key on)
            | 'hang' ':' ms     -- the seam BLOCKS for ms milliseconds
                                   and then errors (the op never
                                   completed) — the deadline watchdog
                                   (reliability/watchdog.py) is
                                   supposed to fire first and surface
                                   a classified StallError
            | 'slow' ':' ms     -- the seam DELAYS ms milliseconds and
                                   then proceeds normally (must stay
                                   under any armed deadline)
            | 'peer_drop'       -- raise ConnectionResetError: the
                                   remote end of a transport round
                                   died (classified TransportPeerLost
                                   by parallel/transport.py; the epoch
                                   protocol is the recovery path)
            | 'peer_slow' ':' ms -- a laggy-but-live peer: the round
                                   DELAYS ms milliseconds then
                                   proceeds (must stay under any armed
                                   watchdog_collective_s deadline)
            | 'corrupt'         -- flip one payload bit in a frame in
                                   flight (transport seams: the frame
                                   CRC must catch it — loud error or
                                   clean retried round, never silent
                                   misdata)
            | 'dup'             -- replay the last transport frame
                                   (the receiver's sequence-number
                                   dup-discard must drop it)
            | 'partition' ':' ms -- sever one peer link BOTH ways for
                                   ms milliseconds, then heal (the
                                   in-epoch reconnect must resync and
                                   finish bit-exact with zero
                                   degradation)
            | ExceptionName     -- a builtin exception class, e.g.
                                   ConnectionError, TimeoutError,
                                   OSError, RuntimeError
    count  := consecutive calls that fire, starting at nth (default 1;
              'x3' at nth=2 fails calls 2, 3 and 4 — how
              retry-exhaustion tests outlast the retry budget)

Example: ``gbdt.train_chunk:3:kill`` SIGKILLs the process the third
time a fused training chunk is about to be dispatched;
``predict.dispatch:1:oom;dataset.cache_io:2:OSError`` injects an OOM
into the first serving dispatch and an OSError into the second
binary-cache file open; ``collectives.allgather:1:hang:5000`` wedges
the first host collective for five seconds.

The ``chaos:<seed>:<n_faults>[:<seam_glob>]`` form draws n randomized
(seam, nth, action) tuples from the registered seam table with a
DETERMINISTIC PRNG (``reliability/chaos.py``) — compound, unscripted
failure combinations, yet any failing run replays exactly from its
seed.  The expansion is logged at parse time.

Call counting starts when a plan is configured and is per-process;
``FAULTS.reset()`` clears both plan and counters (tests).  With no
plan configured every ``fault_point`` is a single attribute check —
the production cost of the harness is one ``if``.
"""
from __future__ import annotations

import builtins
import os
import signal
import threading
import time
from typing import Dict, List, Optional

from ..utils.log import Log

# the STATIC seam registry: every fault_point call site names one of
# these.  parse_plan hard-errors on any other name — the registry is
# fixed at import, so an unknown seam is always a typo, and a typo'd
# seam never fires (turning the recovery test it was written for into
# a vacuous pass).  Adding a seam means adding it here AND at its
# fault_point call site.
SEAMS = (
    "gbdt.train_chunk",      # fused multi-iteration dispatch enqueue
    "gbdt.train_one_iter",   # per-iteration fused dispatch enqueue
    "predict.dispatch",      # serving predictor device dispatch
    "serving.request",       # HTTP serving request handler entry
                             # (serving/server.py — the socket-facing
                             # seam: an injected fault exercises the
                             # 500 + flight-dump path, never tears
                             # down the listener)
    "continuous.cycle",      # continuous-training lane phase entry
                             # (continuous/lane.py — fires once per
                             # cycle PHASE: ingest, train, eval,
                             # publish.  A kill here proves the cycle
                             # state machine resumes from its ledger;
                             # the byte-identity of the resumed
                             # published model is pinned by
                             # tests/test_continuous.py)
    "distributed.init",      # multi-machine rendezvous / network init
    "transport.connect",     # TCP transport socket connect attempt
                             # (parallel/transport.py — rendezvous and
                             # peer-mesh connects; retried under the
                             # bounded policy exactly like
                             # distributed.init)
    "transport.round",       # TCP transport communication round entry
                             # (parallel/transport.py _round and
                             # epoch_tick — fires BEFORE any frame of
                             # the round moves, so a killed round
                             # leaves no half-gathered buffer; the
                             # peer_drop/peer_slow chaos actions land
                             # here, and a hung peer past an armed
                             # watchdog_collective_s surfaces as a
                             # retryable StallError)
    "transport.failover",    # coordinator-failover walk entry
                             # (parallel/transport.py
                             # _coordinator_failover — fires when a
                             # member's tick finds the coordinator
                             # unreachable, BEFORE any successor is
                             # dialed; an injected fault here proves
                             # the walk's own failure path converts to
                             # TransportPeerLost, never a hang)
    "collectives.allgather", # host-side collective backend calls
    "collectives.hist_exchange",  # host-side compressed histogram
                             # exchange (parallel/collectives.py
                             # host_exchange_histograms — fires BEFORE
                             # any shard's histogram is coded or
                             # summed, so a killed exchange leaves no
                             # partially-reconstructed histogram; the
                             # q16/q8 codec and its byte counters ride
                             # the same entry)
    "sharded.binfind",       # sharded-construct boundary-candidate
                             # collection, once per participant
                             # (sharded/binfind.py — fires BEFORE the
                             # candidate allgather, so a killed
                             # participant leaves no merged mappers
                             # behind)
    "sharded.ingest",        # sharded-construct per-shard ingest entry
                             # (sharded/dataset.py — fires BEFORE a
                             # shard's rows are binned; a kill here
                             # must leave any shard-cache manifest
                             # untouched, pinned by tests/
                             # test_sharded.py)
    "dataset.cache_io",      # binary dataset cache file open (r/w)
    "native.entry",          # native libltpu.so entry (load/build)
    "checkpoint.io",         # checkpoint file open (r/w)
)


class FaultInjected(Exception):
    """Raised by an injected fault whose action is not a builtin
    exception name ('oom' and future synthetic actions)."""


class TransportChaos(FaultInjected):
    """The network-shaped chaos actions — ``corrupt`` (bit-flip a
    payload in flight), ``dup`` (replay the last frame) and
    ``partition:<ms>`` (sever the link both directions, then heal).
    ``parallel/transport.py`` catches this at its seams and applies
    the action to REAL frames; anywhere else it propagates as a loud
    FaultInjected."""

    def __init__(self, action: str, seam: str, call: int,
                 duration_ms: int = 0):
        self.action = action
        self.duration_ms = int(duration_ms)
        super().__init__(
            f"{action} (injected at seam {seam}, call {call})")


class _Entry:
    __slots__ = ("seam", "nth", "action", "count", "exc_type",
                 "duration_ms")

    def __init__(self, seam: str, nth: int, action: str, count: int,
                 duration_ms: int = 0):
        self.seam = seam
        self.nth = nth
        self.action = action
        self.count = count
        self.exc_type = None
        self.duration_ms = int(duration_ms)
        if action in ("hang", "slow", "peer_slow", "partition"):
            if self.duration_ms < 1:
                raise ValueError(
                    f"fault plan action {action!r} needs a positive "
                    "millisecond duration (hang:<ms> / slow:<ms> / "
                    "peer_slow:<ms> / partition:<ms>)")
        elif action not in ("kill", "oom", "peer_drop", "corrupt",
                            "dup"):
            exc = getattr(builtins, action, None)
            if not (isinstance(exc, type)
                    and issubclass(exc, BaseException)):
                raise ValueError(
                    f"fault plan action {action!r} is not 'kill', "
                    "'oom', 'hang:<ms>', 'slow:<ms>', 'peer_drop', "
                    "'peer_slow:<ms>', 'corrupt', 'dup', "
                    "'partition:<ms>' or a builtin exception name")
            self.exc_type = exc

    def matches(self, n: int) -> bool:
        return self.nth <= n < self.nth + self.count


def parse_plan(spec: str) -> List[_Entry]:
    """Parse the plan grammar; raises ValueError on malformed specs
    (a silently-dropped fault plan would turn an injection test into
    a vacuous pass).  ``chaos:*`` entries expand through
    ``reliability/chaos.py`` at parse time."""
    entries: List[_Entry] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        parts = raw.split(":")
        if parts[0].strip().lower() == "chaos":
            # seeded randomized plan: deterministic expansion, logged
            # for replay (lazy import — chaos.py reads SEAMS here)
            from .chaos import parse_chaos_entry
            entries.extend(parse_chaos_entry([p.strip()
                                              for p in parts]))
            continue
        if len(parts) < 3:
            raise ValueError(
                f"fault plan entry {raw!r} must be "
                "seam:nth:action[:xCount]")
        seam, nth_s, action = parts[0].strip(), parts[1].strip(), \
            parts[2].strip()
        idx = 3
        duration_ms = 0
        if action in ("hang", "slow", "peer_slow", "partition"):
            if len(parts) < 4 or not parts[3].strip().isdigit():
                raise ValueError(
                    f"fault plan entry {raw!r}: {action} needs a "
                    "millisecond duration (seam:nth:"
                    f"{action}:<ms>[:xCount])")
            duration_ms = int(parts[3].strip())
            idx = 4
        count = 1
        if len(parts) == idx + 1:
            rep = parts[idx].strip().lower()
            if not rep.startswith("x") or not rep[1:].isdigit():
                raise ValueError(
                    f"fault plan repeat {parts[idx]!r} must be xN")
            count = int(rep[1:])
        elif len(parts) > idx + 1:
            raise ValueError(
                f"fault plan entry {raw!r} has trailing fields "
                "(expected seam:nth:action[:<ms>][:xCount])")
        if not nth_s.isdigit() or int(nth_s) < 1:
            raise ValueError(
                f"fault plan call index {nth_s!r} must be a 1-based "
                "integer")
        if seam not in SEAMS:
            # hard error, not a warning: the seam registry is static,
            # so an unknown name is always a typo — and a typo'd seam
            # never fires, turning the recovery test it was written
            # for into a vacuous pass
            raise ValueError(
                f"fault plan names unknown seam {seam!r} (registered: "
                f"{', '.join(SEAMS)})")
        entries.append(_Entry(seam, int(nth_s), action, max(1, count),
                              duration_ms=duration_ms))
    return entries


class FaultInjector:
    """Process-global injector (module singleton ``FAULTS``).  With no
    plan configured, ``fault_point`` is one attribute check."""

    def __init__(self):
        self._lock = threading.Lock()
        self._plan: List[_Entry] = []
        self._counts: Dict[str, int] = {}
        self.spec = ""
        self.fired: List[dict] = []

    @property
    def active(self) -> bool:
        return bool(self._plan)

    def configure(self, spec: str) -> "FaultInjector":
        """Arm ``spec``, restarting the per-seam call counters."""
        with self._lock:
            self._plan = parse_plan(spec)
            self._counts = {}
            self.spec = spec
            self.fired = []
        if self._plan:
            Log.debug(f"fault plan armed: {spec}")
        return self

    def reset(self) -> None:
        with self._lock:
            self._plan = []
            self._counts = {}
            self.spec = ""
            self.fired = []

    def call_count(self, seam: str) -> int:
        with self._lock:
            return self._counts.get(seam, 0)

    def fault_point(self, seam: str) -> None:
        """Mark one call at ``seam``; acts if the armed plan says this
        call fails.  Call BEFORE the seam's side effects so an injected
        failure (or kill) leaves the state as if the call never
        happened — that is what makes injected-crash tests a faithful
        model of a real mid-call crash."""
        if not self._plan:
            return
        with self._lock:
            n = self._counts.get(seam, 0) + 1
            self._counts[seam] = n
            entry: Optional[_Entry] = None
            for e in self._plan:
                if e.seam == seam and e.matches(n):
                    entry = e
                    break
            if entry is not None:
                self.fired.append({"seam": seam, "call": n,
                                   "action": entry.action})
        if entry is None:
            return
        from ..telemetry import TELEMETRY
        TELEMETRY.add("faults_injected", 1)
        # fleet event journal: EVERY registered seam firing journals —
        # the seam-coverage lint (scripts/check_seam_coverage.py)
        # statically pins this call in the shared fire path, so no
        # seam can fire without a journal event.  A chaos-drawn fault
        # carries its replay seed.
        seed = None
        for part in self.spec.split(";"):
            bits = part.strip().split(":")
            if bits and bits[0].strip().lower() == "chaos" \
                    and len(bits) > 1 and bits[1].strip().isdigit():
                seed = int(bits[1])
                break
        TELEMETRY.journal.emit(
            "fault_fired", seam=seam, action=entry.action, call=n,
            **({"chaos_seed": seed} if seed is not None else {}))
        # crash flight recorder (docs/OBSERVABILITY.md): every fired
        # fault dumps the last-N telemetry/log events tagged with THIS
        # seam — for 'kill' the dump lands BEFORE the SIGKILL, which is
        # the whole point: the only trace a kill leaves behind
        TELEMETRY.flight.dump(f"fault:{entry.action}", seam=seam,
                              call=n)
        if entry.action == "kill":
            Log.debug(f"fault plan: SIGKILL at seam {seam} call {n}")
            os.kill(os.getpid(), signal.SIGKILL)
        if entry.action == "oom":
            raise FaultInjected(
                f"RESOURCE_EXHAUSTED: out of memory (injected at seam "
                f"{seam}, call {n})")
        if entry.action in ("corrupt", "dup", "partition"):
            # network-shaped actions: the transport applies them to
            # real frames in flight (bit-flip / replay / sever+heal);
            # outside a transport seam this propagates loud
            raise TransportChaos(entry.action, seam, n,
                                 entry.duration_ms)
        if entry.action == "peer_drop":
            # the remote end of a transport round died: surface the
            # exact exception a reset TCP socket raises, so the
            # transport's dead-peer classification (TransportPeerLost
            # -> epoch-boundary reform) is exercised, not simulated
            raise ConnectionResetError(
                f"peer dropped (injected at seam {seam}, call {n})")
        if entry.action in ("slow", "peer_slow"):
            # delay, then PROCEED: models a slow-but-healthy op — an
            # armed deadline must tolerate it (the watchdog fires only
            # past the deadline, so slow durations are drawn under it)
            Log.debug(f"fault plan: {entry.action} "
                      f"{entry.duration_ms} ms at seam {seam} "
                      f"call {n}")
            time.sleep(entry.duration_ms / 1e3)
            return
        if entry.action == "hang":
            # block, then ERROR: the op never completed.  With a
            # deadline armed the watchdog fires FIRST (the caller
            # already holds a StallError and abandoned this thread);
            # without one, the release error is the loud evidence a
            # hang-shaped failure went unwatched.
            Log.debug(f"fault plan: hang {entry.duration_ms} ms at "
                      f"seam {seam} call {n}")
            time.sleep(entry.duration_ms / 1e3)
            raise FaultInjected(
                f"hang released after {entry.duration_ms} ms at seam "
                f"{seam}, call {n} (fault plan; a deadline watchdog "
                "should have fired before this)")
        raise entry.exc_type(
            f"injected at seam {seam}, call {n} (fault plan)")


FAULTS = FaultInjector()

_env_plan = os.environ.get("LTPU_FAULT_PLAN", "")
if _env_plan:
    FAULTS.configure(_env_plan)


def apply_config(cfg) -> None:
    """Arm ``Config.fault_plan`` (the config-file form of
    LTPU_FAULT_PLAN).  An empty value leaves the env-armed plan alone
    — internally-built default Configs must not disarm a test's
    injection mid-run — and an UNCHANGED value is a no-op: the library
    builds several Configs from one params dict (train + lazy dataset
    construction), and re-arming would zero the per-seam call counters
    mid-run, shifting the plan's Nth-call targeting.  Re-arm the same
    spec freshly via ``FAULTS.configure`` directly."""
    plan = str(getattr(cfg, "fault_plan", "") or "")
    if plan and plan != FAULTS.spec:
        FAULTS.configure(plan)
