"""Deadline watchdog: bounded stalls instead of silent hangs.

The r12 fault harness could only make a seam *crash* — every
hang-shaped failure mode (a wedged collective, a dispatch RPC that
never returns, an NFS checkpoint write that blocks forever) was
untested and, in production, unbounded.  The reference LightGBM guards
every socket operation with ``Network`` ``time_out`` semantics; this
module is that guarantee for the jax_graft stack: per-phase deadlines
(``watchdog_*_s`` knobs, default 0 = off so the hot path is untouched)
that, on expiry, dump ALL-thread stacks into the crash flight recorder
(docs/OBSERVABILITY.md) and surface the stall as a classified
:class:`StallError` — a ``TimeoutError`` subclass, so the existing
retry machinery (``reliability/retry.py``) treats it as transient and
re-enters safe seams, while exhaustion fails loudly with the seam
named.

Two mechanisms, one stall path:

- :func:`run_with_deadline` — bound a BLOCKING host call (dispatch
  enqueue, host collective, checkpoint IO, serve dispatch): the call
  runs on a daemon worker; if it has not returned within the deadline
  the caller gets a :class:`StallError` (stacks dumped, ``stalls_total``
  counted) and the wedged worker is abandoned.  This is what turns the
  fault harness's ``hang`` action from a test-killer into tested
  behavior.
- :class:`Watchdog` (singleton ``WATCHDOG``) — a monitor thread for
  phases that cannot be wrapped (a whole continuous-lane cycle phase):
  ``watch(phase, deadline_s, seam)`` arms a one-shot token; expiry
  dumps stacks + counts + warns (it cannot interrupt the stalled
  thread, but it makes the stall observable within the deadline);
  ``cancel(token)`` disarms on phase completion.

Deadline knobs (``Config``): ``watchdog_dispatch_s`` (fused-chunk /
per-iteration dispatch enqueue), ``watchdog_collective_s`` (host
collectives + sharded binfind participants), ``watchdog_checkpoint_s``
(checkpoint/ledger file IO), ``watchdog_serve_s`` (coalesced serving
dispatch), ``watchdog_continuous_s`` (continuous-lane cycle phases).
Callers with a Config in hand read it directly; the config-less seams
(``distributed._allgather``, ``HostCollectives``, ``checkpoint.io``)
read the process-global registry :func:`deadline`, armed by
``apply_config`` from any Config carrying a non-zero knob (a zero
leaves the armed value alone — internally-built default Configs must
not disarm a run's deadlines mid-flight; tests reset via
:func:`set_deadline`).
"""
from __future__ import annotations

import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

from ..utils.log import Log

# phases with a process-global deadline slot (watchdog_<phase>_s knob)
PHASES = ("dispatch", "collective", "checkpoint", "serve", "continuous")

_DEADLINES: Dict[str, float] = {p: 0.0 for p in PHASES}
_STACK_FRAMES = 24   # frames kept per thread in a stall dump


class StallError(TimeoutError):
    """A watched operation exceeded its deadline.  Subclasses
    ``TimeoutError`` ON PURPOSE: ``retry.is_transient`` classifies it
    retryable by type, so a stall at a safe re-entry seam (the
    dispatch enqueue) rides the existing bounded-retry machinery, and
    retry exhaustion re-raises it with the seam named."""

    def __init__(self, phase: str = "", seam: str = "",
                 deadline_s: float = 0.0,
                 elapsed_s: Optional[float] = None):
        what = phase or seam or "operation"
        msg = (f"{what} stalled: deadline exceeded after "
               f"{deadline_s:g}s")
        if elapsed_s is not None:
            msg += f" ({elapsed_s:.2f}s elapsed)"
        if seam:
            msg += f" [seam {seam}]"
        super().__init__(msg)
        self.phase = phase
        self.seam = seam
        self.deadline_s = float(deadline_s)
        self.elapsed_s = elapsed_s


def set_deadline(phase: str, seconds: float) -> None:
    """Set one phase deadline directly (0 disarms) — the test seam;
    production code arms via the Config knobs."""
    if phase not in _DEADLINES:
        raise ValueError(f"unknown watchdog phase {phase!r} "
                         f"(registered: {', '.join(PHASES)})")
    _DEADLINES[phase] = max(0.0, float(seconds))


def deadline(phase: str) -> float:
    """The armed deadline for ``phase`` (0 = unbounded)."""
    return _DEADLINES.get(phase, 0.0)


def apply_config(cfg) -> None:
    """Arm the process-global deadlines from a Config's
    ``watchdog_*_s`` knobs.  Non-zero values arm; zero (the default)
    leaves the current value alone, so internally-built default
    Configs cannot disarm a run's deadlines mid-flight (the
    ``faults.apply_config`` contract)."""
    knobs = {
        "dispatch": getattr(cfg, "watchdog_dispatch_s", 0.0),
        "collective": getattr(cfg, "watchdog_collective_s", 0.0),
        "checkpoint": getattr(cfg, "watchdog_checkpoint_s", 0.0),
        "serve": getattr(cfg, "watchdog_serve_s", 0.0),
        "continuous": getattr(cfg, "watchdog_continuous_s", 0.0),
    }
    for phase, raw in knobs.items():
        v = float(raw or 0.0)
        if v > 0:
            _DEADLINES[phase] = v


def all_thread_stacks(limit: int = _STACK_FRAMES) -> Dict[str, list]:
    """{thread name: [formatted frames]} for every live thread — the
    stall dump's payload.  Pure introspection (``sys._current_frames``),
    safe to call from the monitor thread while the stalled thread is
    still blocked."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, list] = {}
    for tid, frame in sys._current_frames().items():
        key = f"{names.get(tid, 'unknown')}-{tid}"
        out[key] = [ln.rstrip("\n") for ln in
                    traceback.format_stack(frame)[-limit:]]
    return out


def _record_stall(phase: str, seam: str, deadline_s: float,
                  elapsed_s: float) -> None:
    """The one stall path both mechanisms share: count
    ``stalls_total`` (Prometheus ``ltpu_stalls_total``), dump the
    flight recorder with the seam, the blown deadline and ALL-thread
    stacks, and warn loudly."""
    from ..telemetry import TELEMETRY
    TELEMETRY.add("stalls_total", 1)
    # fleet event journal: the stall names its seam and carries the
    # active trace context (a stalled serve dispatch journals with the
    # coalesced request's trace)
    TELEMETRY.journal.emit(
        "stall", seam=seam, phase=phase,
        deadline_s=round(float(deadline_s), 6),
        elapsed_s=round(float(elapsed_s), 6))
    TELEMETRY.flight.dump(
        "stall", seam=seam, phase=phase,
        deadline_s=round(float(deadline_s), 6),
        elapsed_s=round(float(elapsed_s), 6),
        stacks=all_thread_stacks())
    Log.warning(
        f"watchdog: {phase or seam or 'operation'} exceeded its "
        f"{deadline_s:g}s deadline ({elapsed_s:.2f}s elapsed"
        + (f", seam {seam}" if seam else "")
        + ") — all-thread stacks dumped to the flight recorder")


def run_with_deadline(fn: Callable, deadline_s: float,
                      phase: str = "", seam: str = "",
                      *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` bounded by ``deadline_s`` seconds.
    ``deadline_s <= 0`` calls inline (zero overhead when disarmed).
    Otherwise the call runs on a daemon worker thread; a call that
    has not finished within the deadline raises :class:`StallError`
    in the CALLER (stacks dumped, ``stalls_total`` counted) and the
    wedged worker is abandoned — its eventual result or exception is
    discarded, exactly like a socket op timed out by the reference's
    ``Network`` ``time_out``.  A worker exception inside the deadline
    re-raises unchanged in the caller."""
    if deadline_s is None or deadline_s <= 0:
        return fn(*args, **kwargs)
    box: dict = {}
    done = threading.Event()

    def _work():
        try:
            box["result"] = fn(*args, **kwargs)
        except BaseException as e:  # noqa: BLE001 - relayed to caller
            box["error"] = e
        finally:
            done.set()

    t0 = time.perf_counter()
    worker = threading.Thread(
        target=_work, daemon=True,
        name=f"ltpu-deadline-{phase or seam or 'op'}")
    worker.start()
    if not done.wait(deadline_s):
        elapsed = time.perf_counter() - t0
        _record_stall(phase, seam, deadline_s, elapsed)
        raise StallError(phase, seam, deadline_s, elapsed)
    if "error" in box:
        raise box["error"]
    return box.get("result")


class Watchdog:
    """Monitor-thread deadline watching for phases that cannot be
    wrapped in :func:`run_with_deadline` (the work runs on the
    caller's own thread across many calls — a continuous-lane cycle
    phase).  ``watch`` arms a one-shot token; on expiry the monitor
    dumps stacks + counts the stall + warns (it cannot interrupt the
    stalled thread); ``cancel`` disarms when the phase completes."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tokens: Dict[int, tuple] = {}
        self._next = 1
        self._thread: Optional[threading.Thread] = None
        self.fired: int = 0     # tokens that expired (tests)

    def watch(self, phase: str, deadline_s: float,
              seam: str = "") -> Optional[int]:
        """Arm a one-shot deadline on ``phase``; returns the token to
        :meth:`cancel` on completion (None when ``deadline_s <= 0``)."""
        if deadline_s is None or deadline_s <= 0:
            return None
        with self._cond:
            token = self._next
            self._next += 1
            now = time.monotonic()
            self._tokens[token] = (now + deadline_s, phase, seam,
                                   now, deadline_s)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="ltpu-watchdog")
                self._thread.start()
            self._cond.notify_all()
        return token

    def cancel(self, token: Optional[int]) -> None:
        if token is None:
            return
        with self._cond:
            self._tokens.pop(token, None)
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            expired = []
            with self._cond:
                now = time.monotonic()
                for token, rec in list(self._tokens.items()):
                    if rec[0] <= now:
                        expired.append(rec)
                        del self._tokens[token]
                if not expired:
                    nxt = min((rec[0] for rec in
                               self._tokens.values()), default=None)
                    self._cond.wait(None if nxt is None
                                    else max(nxt - now, 0.01))
                    continue
                self.fired += len(expired)
            # fire OUTSIDE the lock: the dump walks every thread's
            # stack and writes a file — new watch()/cancel() calls
            # must not block behind it
            for _abs, phase, seam, t0, dl in expired:
                _record_stall(phase, seam, dl, time.monotonic() - t0)


WATCHDOG = Watchdog()
