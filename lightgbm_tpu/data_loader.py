"""Text data loading: CSV / TSV / LibSVM with column-role resolution.

Re-design of the reference's Parser + DatasetLoader text pipeline
(reference: src/io/parser.cpp:67-162 format auto-detection,
src/io/dataset_loader.cpp:23-158 header/column-role resolution,
src/io/metadata.cpp:23-26 side files <data>.weight / <data>.query).
A NumPy-vectorized path parses the common case; the optional C++
native loader (lightgbm_tpu/native) accelerates large files.
"""
from __future__ import annotations

import os
import time as _time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .config import Config
from .utils.log import Log


def detect_format(sample_lines: List[str]) -> str:
    """Auto-detect csv/tsv/libsvm (reference parser.cpp:67-162): count
    parseable columns under each dialect on sample lines and pick the
    consistent one; ':' inside tokens marks libsvm."""
    def is_libsvm(line):
        toks = line.split()
        if not toks:
            return False
        rest = toks[1:] if ":" not in toks[0] else toks
        return len(rest) > 0 and all(":" in t for t in rest)

    votes = {"csv": 0, "tsv": 0, "libsvm": 0}
    for line in sample_lines:
        line = line.strip()
        if not line:
            continue
        if is_libsvm(line):
            votes["libsvm"] += 1
        elif "\t" in line:
            votes["tsv"] += 1
        elif "," in line:
            votes["csv"] += 1
    fmt = max(votes, key=votes.get)
    if votes[fmt] == 0:
        Log.fatal("Cannot detect data format (csv/tsv/libsvm)")
    return fmt


def _sniff_text_file(path: str, config: Config):
    """Shared format/header sniffing for both loaders: returns
    (fmt, sep, names) from the file's first lines."""
    with open(path) as f:
        first_lines = [f.readline() for _ in range(20)]
    has_header = config.has_header
    header_line = first_lines[0] if has_header else None
    data_sample = first_lines[1:] if has_header else first_lines
    fmt = detect_format([ln for ln in data_sample if ln])
    sep = "\t" if fmt == "tsv" else ","
    names = None
    if header_line is not None:
        names = [c.strip() for c in header_line.strip().split(sep)]
    return fmt, sep, names


def qid_to_group_sizes(qid: np.ndarray) -> np.ndarray:
    """Per-row query ids -> per-query sizes in APPEARANCE order (rows
    of one query must be contiguous, the reference contract;
    np.unique's sorted order would misassign boundaries for descending
    qids)."""
    qid = np.asarray(qid)
    if len(qid) == 0:
        return np.zeros(0, dtype=np.int64)
    change = np.flatnonzero(qid[1:] != qid[:-1])
    bounds = np.concatenate([[0], change + 1, [len(qid)]])
    sizes = np.diff(bounds)
    starts = qid[bounds[:-1]]
    if len(np.unique(starts)) != len(starts):
        Log.fatal("query/group column is not contiguous: the same qid "
                  "appears in non-adjacent row blocks")
    return sizes


def _resolve_file_columns(config: Config, names: Optional[List[str]],
                          ncol: int):
    """Shared label/weight/group/ignore/categorical column-role
    resolution (reference dataset_loader.cpp:23-158).  Returns
    categorical indices REMAPPED into the post-drop feature space so
    they line up with the returned matrix's columns."""
    label_col = _resolve_single(config.label_column, names, default=0)
    weight_cols = _parse_column_spec(config.weight_column, names)
    group_cols = _parse_column_spec(config.group_column, names)
    ignore_cols = set(_parse_column_spec(config.ignore_column, names))
    used = [i for i in range(ncol)
            if i != label_col and i not in weight_cols
            and i not in group_cols and i not in ignore_cols]
    raw_cat = set(_parse_column_spec(config.categorical_column, names))
    cat_feats = [f for f, i in enumerate(used) if i in raw_cat]
    return label_col, weight_cols, group_cols, used, cat_feats


def _load_side_files(path: str, extras: Dict) -> Dict:
    """Side files <data>.weight / .query / .init
    (reference metadata.cpp:23-26); existing keys win."""
    wf = path + ".weight"
    if os.path.exists(wf) and extras.get("weight") is None:
        extras["weight"] = np.loadtxt(wf, dtype=np.float32).reshape(-1)
    qf = path + ".query"
    if os.path.exists(qf) and extras.get("group") is None:
        extras["group"] = np.loadtxt(qf, dtype=np.int64).reshape(-1)
    inf = path + ".init"
    if os.path.exists(inf):
        extras["init_score"] = np.loadtxt(inf,
                                          dtype=np.float64).reshape(-1)
    return extras


def split_sample_columns(sample: np.ndarray):
    """Per-column non-zero/NaN values + their row indices — the shared
    sampling contract (zeros implicit; reference bin.cpp:207)."""
    vals, rows = [], []
    for j in range(sample.shape[1]):
        col = sample[:, j]
        keep = np.isnan(col) | (np.abs(col) > 1e-35)
        vals.append(col[keep])
        rows.append(np.nonzero(keep)[0].astype(np.int64))
    return vals, rows


def _parse_column_spec(spec: str, names: Optional[List[str]]) -> List[int]:
    """Resolve 'name:' or index column specs (reference
    dataset_loader.cpp:23-158)."""
    if not spec:
        return []
    out = []
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.startswith("name:"):
            nm = tok[5:]
            if names and nm in names:
                out.append(names.index(nm))
            else:
                Log.warning(f"Column name {nm} not found in header")
        else:
            out.append(int(tok))
    return out


def load_file(path: str, config: Config
              ) -> Tuple[np.ndarray, Optional[np.ndarray], Dict]:
    """Load a training/prediction text file.

    Returns (feature_matrix, label, extras) where extras may hold
    weight / group arrays from columns or side files.
    """
    # native fast path for csv/tsv when the C++ loader is built
    has_header = config.has_header
    fmt, sep, names = _sniff_text_file(path, config)

    if fmt in ("csv", "tsv"):
        from .telemetry import TELEMETRY
        with TELEMETRY.span("parse"):
            try:
                from .native import text_loader
                raw = text_loader.load_csv(path, sep,
                                           1 if has_header else 0)
            except Exception:
                raw = np.loadtxt(path, delimiter=sep,
                                 skiprows=1 if has_header else 0,
                                 ndmin=2, dtype=np.float64,
                                 converters=None, encoding=None)
        label_col, weight_cols, group_cols, used, cat_feats = \
            _resolve_file_columns(config, names, raw.shape[1])
        X = raw[:, used]
        label = raw[:, label_col] if label_col is not None else None
        extras: Dict = {}
        if cat_feats:
            extras["categorical_feature"] = cat_feats
        if weight_cols:
            extras["weight"] = raw[:, weight_cols[0]].astype(np.float32)
        if group_cols:
            # group column holds per-row query ids -> convert to sizes
            qid = raw[:, group_cols[0]].astype(np.int64)
            extras["group"] = qid_to_group_sizes(qid)
    else:
        X, label = _load_libsvm(path)
        extras = {}

    return X, label, _load_side_files(path, extras)


def load_file_streaming(path: str, config: Config):
    """Two-round streaming construction: the float matrix never exists
    (reference two_round_loading, src/io/dataset_loader.cpp:180-265).

    Round 1 reservoir-samples up to ``bin_construct_sample_cnt`` parsed
    rows while counting lines; bin mappers and EFB bundles are fitted
    from the samples.  Round 2 re-reads the file in chunks, pushing
    binned rows straight into the packed (N, G) uint8 matrix — parse
    and bin OVERLAPPED: a producer thread parses ahead while the main
    thread bins, a bounded two-chunk queue in between (the native
    binner and numpy both release the GIL, so the stages genuinely run
    concurrently).  Peak host memory = samples + at most FOUR parsed
    chunks (two queued, one in the producer's hand, one being binned)
    + the uint8 matrix.

    Returns a constructed CoreDataset (metadata from label/weight/group
    columns and side files already applied).
    """
    import queue
    import threading

    from .dataset import Dataset as CoreDataset
    from .telemetry import TELEMETRY

    has_header = config.has_header
    fmt, sep, names = _sniff_text_file(path, config)
    if fmt == "libsvm":
        # libsvm files are sparse — route through the sparse in-RAM
        # path (bounded by nnz) rather than two-round
        X, label, extras = load_file(path, config)
        ds = CoreDataset.from_matrix(X, label=label,
                                     weight=extras.get("weight"),
                                     group=extras.get("group"),
                                     init_score=extras.get("init_score"),
                                     config=config)
        return ds

    def parse_lines(lines):
        return np.loadtxt(lines, delimiter=sep, ndmin=2, dtype=np.float64)

    # ---- round 1: count + reservoir sample ----
    sample_cnt = config.bin_construct_sample_cnt
    rng = np.random.RandomState(config.data_random_seed)
    reservoir: List[str] = []
    n_rows = 0
    with open(path) as f:
        if has_header:
            f.readline()
        for line in f:
            if not line.strip():
                continue
            if n_rows < sample_cnt:
                reservoir.append(line)
            else:
                j = rng.randint(0, n_rows + 1)
                if j < sample_cnt:
                    reservoir[j] = line
            n_rows += 1
    with TELEMETRY.span("parse", rows=len(reservoir)):
        sample_raw = parse_lines(reservoir)
    label_col, weight_cols, group_cols, used, cat_feats = \
        _resolve_file_columns(config, names, sample_raw.shape[1])
    sample_X = sample_raw[:, used]
    sample_vals, sample_rows = split_sample_columns(sample_X)

    ds = CoreDataset.from_sampled_columns(
        sample_vals, sample_rows, sample_X.shape[0], n_rows,
        config=config,
        feature_names=[names[i] for i in used] if names else None,
        categorical_features=cat_feats or None)

    # ---- round 2: stream chunks into the bin matrix, parse || bin ----
    # A bounded two-chunk queue: the producer thread reads + parses
    # ahead while the consumer bins the current chunk.  Chunk
    # boundaries and parse order are identical to the old serial loop,
    # so the packed matrix is byte-identical.  Worst-case resident
    # parsed chunks: two queued + one in the producer's hand + one
    # being binned (see streaming_chunk_rows in Parameters.md).  The
    # `stop` event keeps a consumer-side failure from stranding the
    # producer in a blocking put() forever (thread + chunk leak).
    chunk_rows = max(1, int(config.streaming_chunk_rows))
    label = np.zeros(n_rows, dtype=np.float64)
    weight = np.zeros(n_rows, dtype=np.float32) if weight_cols else None
    qid = np.zeros(n_rows, dtype=np.int64) if group_cols else None
    chunk_q: "queue.Queue" = queue.Queue(maxsize=2)
    stop = threading.Event()

    def _put(item) -> bool:
        """Bounded put that gives up once the consumer has aborted."""
        while not stop.is_set():
            try:
                chunk_q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _produce():
        try:
            with open(path) as f:
                if has_header:
                    f.readline()
                buf: List[str] = []
                for line in f:
                    if not line.strip():
                        continue
                    buf.append(line)
                    if len(buf) >= chunk_rows:
                        with TELEMETRY.span("parse", rows=len(buf)):
                            arr = parse_lines(buf)
                        if not _put(("chunk", arr)):
                            return
                        buf = []
                if buf:
                    with TELEMETRY.span("parse", rows=len(buf)):
                        arr = parse_lines(buf)
                    if not _put(("chunk", arr)):
                        return
            _put(("done", None))
        except BaseException as e:  # re-raised on the consumer side
            _put(("error", e))

    t0 = _time.perf_counter()
    producer = threading.Thread(target=_produce, name="ltpu-parse",
                                daemon=True)
    producer.start()
    row = 0
    try:
        while True:
            kind, payload = chunk_q.get()
            if kind == "done":
                break
            if kind == "error":
                raise payload
            row = _push_text_chunk(ds, payload, used, label_col,
                                   weight_cols, group_cols, label,
                                   weight, qid, row)
    finally:
        stop.set()
        while True:  # unblock a producer waiting on a full queue
            try:
                chunk_q.get_nowait()
            except queue.Empty:
                break
        producer.join()
    wall = _time.perf_counter() - t0
    if wall > 0:
        TELEMETRY.gauge("construct_stream_rows_per_s", round(row / wall))
    ds.finish_load()
    ds.metadata.set_label(label)
    extras = _load_side_files(path, {
        "weight": weight,
        "group": qid_to_group_sizes(qid) if qid is not None else None,
    })
    if extras.get("weight") is not None:
        ds.metadata.set_weight(extras["weight"])
    if extras.get("group") is not None:
        ds.metadata.set_group(extras["group"])
    if extras.get("init_score") is not None:
        ds.metadata.set_init_score(extras["init_score"])
    return ds


def _push_text_chunk(ds, raw, used, label_col, weight_cols, group_cols,
                     label, weight, qid, row):
    n = raw.shape[0]
    ds.push_rows(raw[:, used], row)
    if label_col is not None:
        label[row:row + n] = raw[:, label_col]
    if weight_cols:
        weight[row:row + n] = raw[:, weight_cols[0]]
    if group_cols:
        qid[row:row + n] = raw[:, group_cols[0]].astype(np.int64)
    return row + n


def _resolve_single(spec: str, names: Optional[List[str]],
                    default: Optional[int]) -> Optional[int]:
    cols = _parse_column_spec(spec, names)
    if cols:
        return cols[0]
    return default


def _load_libsvm(path: str):
    """Parse a libsvm file to CSR (reference src/io/parser.hpp:87-126
    LibSVMParser).  Memory is bounded by nnz — the dense (N, max_feat)
    matrix is never materialized, so a wide 99%-sparse file (news20:
    15k x 1.3M) parses in ~nnz floats instead of OOMing; downstream
    Dataset construction walks the CSC columns (dataset.py
    _bin_data_sparse) without densifying either."""
    from array import array

    from scipy import sparse as sp

    labels = array("d")
    indptr = array("q", [0])
    indices = array("q")
    values = array("d")
    max_feat = -1
    with open(path) as f:
        for line in f:
            toks = line.split()
            if not toks:
                continue
            start = 0
            if ":" not in toks[0]:
                labels.append(float(toks[0]))
                start = 1
            else:
                labels.append(0.0)
            for t in toks[start:]:
                k, v = t.split(":", 1)
                idx = int(k)
                indices.append(idx)
                values.append(float(v))
                if idx > max_feat:
                    max_feat = idx
            indptr.append(len(indices))
    X = sp.csr_matrix(
        (np.frombuffer(values, dtype=np.float64),
         np.frombuffer(indices, dtype=np.int64),
         np.frombuffer(indptr, dtype=np.int64)),
        shape=(len(labels), max_feat + 1))
    return X, np.frombuffer(labels, dtype=np.float64)
