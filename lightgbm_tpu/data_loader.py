"""Text data loading: CSV / TSV / LibSVM with column-role resolution.

Re-design of the reference's Parser + DatasetLoader text pipeline
(reference: src/io/parser.cpp:67-162 format auto-detection,
src/io/dataset_loader.cpp:23-158 header/column-role resolution,
src/io/metadata.cpp:23-26 side files <data>.weight / <data>.query).
A NumPy-vectorized path parses the common case; the optional C++
native loader (lightgbm_tpu/native) accelerates large files.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from .config import Config
from .utils.log import Log


def detect_format(sample_lines: List[str]) -> str:
    """Auto-detect csv/tsv/libsvm (reference parser.cpp:67-162): count
    parseable columns under each dialect on sample lines and pick the
    consistent one; ':' inside tokens marks libsvm."""
    def is_libsvm(line):
        toks = line.split()
        if not toks:
            return False
        rest = toks[1:] if ":" not in toks[0] else toks
        return len(rest) > 0 and all(":" in t for t in rest)

    votes = {"csv": 0, "tsv": 0, "libsvm": 0}
    for line in sample_lines:
        line = line.strip()
        if not line:
            continue
        if is_libsvm(line):
            votes["libsvm"] += 1
        elif "\t" in line:
            votes["tsv"] += 1
        elif "," in line:
            votes["csv"] += 1
    fmt = max(votes, key=votes.get)
    if votes[fmt] == 0:
        Log.fatal("Cannot detect data format (csv/tsv/libsvm)")
    return fmt


def _parse_column_spec(spec: str, names: Optional[List[str]]) -> List[int]:
    """Resolve 'name:' or index column specs (reference
    dataset_loader.cpp:23-158)."""
    if not spec:
        return []
    out = []
    for tok in str(spec).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.startswith("name:"):
            nm = tok[5:]
            if names and nm in names:
                out.append(names.index(nm))
            else:
                Log.warning(f"Column name {nm} not found in header")
        else:
            out.append(int(tok))
    return out


def load_file(path: str, config: Config
              ) -> Tuple[np.ndarray, Optional[np.ndarray], Dict]:
    """Load a training/prediction text file.

    Returns (feature_matrix, label, extras) where extras may hold
    weight / group arrays from columns or side files.
    """
    # native fast path for csv/tsv when the C++ loader is built
    with open(path) as f:
        first_lines = [f.readline() for _ in range(20)]
    has_header = config.has_header
    header_line = first_lines[0] if has_header else None
    data_sample = first_lines[1:] if has_header else first_lines
    fmt = detect_format([l for l in data_sample if l])

    names = None
    if header_line is not None:
        sep = "\t" if fmt == "tsv" else ","
        names = [c.strip() for c in header_line.strip().split(sep)]

    if fmt in ("csv", "tsv"):
        sep = "\t" if fmt == "tsv" else ","
        try:
            from .native import text_loader
            raw = text_loader.load_csv(path, sep, 1 if has_header else 0)
        except Exception:
            raw = np.loadtxt(path, delimiter=sep,
                             skiprows=1 if has_header else 0,
                             ndmin=2, dtype=np.float64,
                             converters=None, encoding=None)
        label_col = _resolve_single(config.label_column, names, default=0)
        weight_cols = _parse_column_spec(config.weight_column, names)
        group_cols = _parse_column_spec(config.group_column, names)
        ignore_cols = set(_parse_column_spec(config.ignore_column, names))

        ncol = raw.shape[1]
        used = [i for i in range(ncol)
                if i != label_col and i not in weight_cols
                and i not in group_cols and i not in ignore_cols]
        X = raw[:, used]
        label = raw[:, label_col] if label_col is not None else None
        extras: Dict = {}
        if weight_cols:
            extras["weight"] = raw[:, weight_cols[0]].astype(np.float32)
        if group_cols:
            # group column holds per-row query ids -> convert to sizes
            qid = raw[:, group_cols[0]].astype(np.int64)
            _, counts = np.unique(qid, return_counts=True)
            extras["group"] = counts
    else:
        X, label = _load_libsvm(path)
        extras = {}

    # side files (reference metadata.cpp:23-26)
    wf = path + ".weight"
    if os.path.exists(wf) and "weight" not in extras:
        extras["weight"] = np.loadtxt(wf, dtype=np.float32).reshape(-1)
    qf = path + ".query"
    if os.path.exists(qf) and "group" not in extras:
        extras["group"] = np.loadtxt(qf, dtype=np.int64).reshape(-1)
    inf = path + ".init"
    if os.path.exists(inf):
        extras["init_score"] = np.loadtxt(inf, dtype=np.float64).reshape(-1)
    return X, label, extras


def _resolve_single(spec: str, names: Optional[List[str]],
                    default: Optional[int]) -> Optional[int]:
    cols = _parse_column_spec(spec, names)
    if cols:
        return cols[0]
    return default


def _load_libsvm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    labels = []
    rows: List[Dict[int, float]] = []
    max_feat = -1
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            toks = line.split()
            start = 0
            if ":" not in toks[0]:
                labels.append(float(toks[0]))
                start = 1
            else:
                labels.append(0.0)
            row = {}
            for t in toks[start:]:
                k, v = t.split(":", 1)
                idx = int(k)
                row[idx] = float(v)
                max_feat = max(max_feat, idx)
            rows.append(row)
    X = np.zeros((len(rows), max_feat + 1), dtype=np.float64)
    for i, row in enumerate(rows):
        for k, v in row.items():
            X[i, k] = v
    return X, np.asarray(labels, dtype=np.float64)
