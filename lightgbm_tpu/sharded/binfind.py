"""Distributed bin-mapper finding for sharded dataset construction.

The redesign of the reference ``DatasetLoader``'s networked bin-
boundary sync (reference: src/io/dataset_loader.cpp:523-605 local
find_bin + :828-886 serialized-mapper allgather, docs/Parallel-
Learning-Guide.md): before ANY participant bins a row, every
participant collects *boundary candidates* — the per-feature sampled
non-zero/NaN values of its own disjoint row range (the same sampling
contract the single-host fit uses, bin.cpp:207) — the candidates are
ALLGATHERED through the instrumented, fault-injectable host-collective
seam, merged DETERMINISTICALLY (participant-rank order, sample-row
offsets rebased into the merged sample space), and the merged sample
feeds the ONE threaded ``Dataset._fit_mappers`` path.  Every shard
therefore bins against IDENTICAL mappers, and — whenever the per-shard
quotas cover the full shards (small/medium datasets, every test) — the
merged fit is BYTE-EQUAL to a single-host fit on the concatenated
data, EFB bundles included (pinned by ``tests/test_sharded.py``).

The collective is the :class:`HostCollectives` backend for simulated
(in-process) participants — calls and payload bytes land in the
``collective_allgather_*`` telemetry counters exactly like every other
explicit collective — and callers with a real multi-host transport
inject their own gather (the ``LGBM_NetworkInitWithFunctions``
pattern).  The ``sharded.binfind`` fault seam fires once per
participant BEFORE its candidates enter the gather, so an injected
kill leaves no merged mappers behind.
"""
from __future__ import annotations

import hashlib
import pickle
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..binning import BIN_CATEGORICAL, BinMapper
from ..config import Config
from ..data_loader import split_sample_columns
from ..parallel.collectives import HostCollectives
from ..reliability.faults import FAULTS
from ..utils.log import Log


class BoundaryCandidates:
    """One participant's contribution to distributed bin finding:
    per-feature sampled values + their row indices WITHIN the
    participant's sample, plus the sample/row counts the merge needs
    to rebase rows into the merged sample space."""

    __slots__ = ("rank", "num_rows", "sample_cnt", "vals", "rows")

    def __init__(self, rank: int, num_rows: int, sample_cnt: int,
                 vals: List[np.ndarray], rows: List[np.ndarray]):
        self.rank = rank
        self.num_rows = num_rows
        self.sample_cnt = sample_cnt
        self.vals = vals
        self.rows = rows


def shard_sample_quota(config: Optional[Config], world: int) -> int:
    """Per-participant sample budget: an explicit
    ``sharded_sample_per_shard``, else the single-host
    ``bin_construct_sample_cnt`` split evenly so the merged sample
    stays within the same budget."""
    cfg = config or Config()
    per = int(getattr(cfg, "sharded_sample_per_shard", 0) or 0)
    if per > 0:
        return per
    return max(1, int(cfg.bin_construct_sample_cnt) // max(1, world))


def collect_candidates(shard: np.ndarray, config: Optional[Config],
                       rank: int, world: int) -> BoundaryCandidates:
    """Sample this participant's row range and split it into
    per-feature boundary candidates (``split_sample_columns`` — the
    shared zeros-implicit sampling contract).  Shards at or under the
    quota contribute EVERY row (no RNG), which is what makes the
    merged fit byte-equal to the single-host fit; larger shards draw a
    sorted random subset under a rank-derived seed (the
    ``distributed.sample_local_rows`` idiom)."""
    FAULTS.fault_point("sharded.binfind")
    cfg = config or Config()
    shard = np.asarray(shard, dtype=np.float64)
    n = shard.shape[0]
    quota = shard_sample_quota(cfg, world)
    if n > quota:
        rng = np.random.RandomState(cfg.data_random_seed + 7919 * rank)
        idx = rng.choice(n, size=quota, replace=False)
        idx.sort()
        sample = shard[idx]
    else:
        sample = shard
    vals, rows = split_sample_columns(sample)
    return BoundaryCandidates(rank, n, sample.shape[0], vals, rows)


def merge_candidates(cands: Sequence[BoundaryCandidates],
                     collective: Optional[HostCollectives] = None
                     ) -> Tuple[List[np.ndarray], List[np.ndarray], int]:
    """Allgather + deterministic merge: every per-feature candidate
    array crosses the collective seam (bytes counted per call, the
    reference's per-feature boundary sync), candidates concatenate in
    participant-RANK order, and sample-row indices rebase by the
    cumulative sample counts — so the merged (vals, rows, total) is
    exactly what a single host would have sampled from the
    concatenated row ranges.  Returns the merged per-feature values,
    rows and total sample count for ``Dataset._fit_mappers`` + the EFB
    bundler."""
    if not cands:
        raise ValueError("merge_candidates needs at least one "
                         "participant")
    cands = sorted(cands, key=lambda c: c.rank)
    hc = collective or HostCollectives(shards=len(cands))
    counts = hc.simulate_allgather(
        [np.asarray([c.sample_cnt], dtype=np.int64) for c in cands]
    ).ravel()
    offsets = np.concatenate([[0], np.cumsum(counts)])
    n_feat = len(cands[0].vals)
    for c in cands:
        if len(c.vals) != n_feat:
            raise ValueError(
                f"participant {c.rank} contributed {len(c.vals)} "
                f"feature columns, expected {n_feat} — shards must "
                "share one schema")
    vals: List[np.ndarray] = []
    rows: List[np.ndarray] = []
    for f in range(n_feat):
        vals.append(hc.simulate_allgather(
            [np.asarray(c.vals[f], dtype=np.float64) for c in cands]))
        rows.append(hc.simulate_allgather(
            [np.asarray(c.rows[f], dtype=np.int64) + offsets[i]
             for i, c in enumerate(cands)]))
    return vals, rows, int(counts.sum())


def gather_merge_remote(local_cand: BoundaryCandidates, transport
                        ) -> Tuple[List[np.ndarray], List[np.ndarray],
                                   int]:
    """Cross-PROCESS candidate gather: this participant's boundary
    candidates cross the TCP transport (one Bruck allgather of the
    pickled :class:`BoundaryCandidates` — wire bytes land in the
    ``collective_tcp_*`` counters), then the full set merges through
    the same deterministic rank-order :func:`merge_candidates` path
    the in-process participants use — so the merged (vals, rows,
    total) is byte-equal whether the shards live in one process or
    N (the ``LGBM_NetworkInitWithFunctions`` injected-gather pattern,
    finally over a real wire)."""
    cands = transport.allgather_obj(local_cand)
    return merge_candidates(cands)


def mapper_fingerprint(mappers: Sequence[BinMapper],
                       bundles: Optional[Sequence[Sequence[int]]] = None,
                       max_bin: int = 0) -> str:
    """sha256 identity of a fitted mapper set (+ EFB bundle layout):
    the byte-level contract two shards (or a shard cache and its
    loader) must agree on before their bin matrices are comparable.
    Canonicalized field-by-field so lazily-built caches (the
    categorical LUT) never perturb the digest."""
    h = hashlib.sha256()
    h.update(f"max_bin={int(max_bin)};".encode())
    for m in mappers:
        h.update(f"{m.bin_type}|{m.num_bin}|{m.missing_type}|"
                 f"{m.default_bin}|{int(m.is_trivial)}|"
                 f"{m.min_val!r}|{m.max_val!r};".encode())
        bub = getattr(m, "bin_upper_bound", None)
        if bub is not None:
            h.update(np.ascontiguousarray(
                np.asarray(bub, dtype=np.float64)).tobytes())
        cat = getattr(m, "categorical_2_bin", None)
        if cat:
            h.update(pickle.dumps(sorted(cat.items()), protocol=4))
        h.update(b"\x00")
    if bundles is not None:
        h.update(pickle.dumps([list(b) for b in bundles], protocol=4))
    return h.hexdigest()


def warn_if_quota_truncated(cands: Sequence[BoundaryCandidates]) -> bool:
    """True (with one loud warning) when any participant subsampled —
    merged mappers are then still identical on every shard, but no
    longer byte-equal to a whole-data single-host fit (same caveat as
    the reference's sampled GreedyFindBin)."""
    truncated = [c.rank for c in cands if c.sample_cnt < c.num_rows]
    if truncated:
        Log.warning(
            "sharded bin finding subsampled participant(s) "
            f"{truncated}: merged mappers are deterministic and "
            "identical on every shard, but reflect the sample, not "
            "the full rows — byte-equality with a whole-data "
            "single-host fit does not hold at this scale "
            "(bin_construct_sample_cnt / sharded_sample_per_shard)")
    return bool(truncated)
