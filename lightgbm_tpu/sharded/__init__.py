"""Mesh-sharded dataset subsystem (ROADMAP item 1, round 16).

The end-to-end sharded data plane joining the r11 construction
pipeline to the r13-instrumented collectives layer:

* :mod:`binfind` — distributed bin-mapper finding: per-participant
  boundary candidates, instrumented/fault-injectable allgather,
  deterministic merge, byte-equal to a single-host fit;
* :mod:`dataset` — :class:`ShardedDataset`: disjoint row ranges
  stream-ingested into per-shard bin matrices, placed per-device over
  the mesh row axis by the grower;
* :mod:`cache` — shard-cache v2: per-shard v2 binary-cache files + a
  manifest (world size, row ranges, mapper fingerprint), zero-copy
  reload, loud mismatch refusal.

See docs/Parallel-Learning-Guide.md, "Sharded construction".
"""
from .binfind import (BoundaryCandidates, collect_candidates,
                      mapper_fingerprint, merge_candidates,
                      shard_sample_quota)
from .cache import (ShardCacheError, has_shard_cache, load_shard_cache,
                    save_shard_cache)
from .dataset import ShardedDataset, shard_row_ranges

__all__ = ["ShardedDataset", "shard_row_ranges", "BoundaryCandidates",
           "collect_candidates", "merge_candidates",
           "mapper_fingerprint", "shard_sample_quota",
           "save_shard_cache", "load_shard_cache", "has_shard_cache",
           "ShardCacheError"]
