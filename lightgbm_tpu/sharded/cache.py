"""Shard-cache v2: the sharded extension of the binary dataset cache.

Layout: one DIRECTORY holding one standard v2 binary-cache file per
shard (``shard_<i>.bin`` — the r11 format ``dataset_io`` writes, so
reload memmaps each shard's bin section zero-copy) plus a
``manifest.json`` carrying the construction identity: schema, world
size, global row count, per-shard row ranges and file sizes, and the
merged-mapper fingerprint (``binfind.mapper_fingerprint``).

Crash safety: shard files write FIRST, the manifest LAST (atomic
tmp+fsync+rename — the r12 writer).  A kill during shard ingest or
save leaves either the previous complete manifest or none at all, so
a loader can never assemble a half-written cache (pinned through the
``sharded.ingest`` fault seam, tests/test_sharded.py).

Loading REFUSES loudly on: a missing/alien manifest, a world-size
mismatch against the caller's expectation, a per-shard mapper
fingerprint that disagrees with the manifest (stale shards next to a
new manifest or vice versa), truncated/corrupted shard files (size
check here + the v2 reader's own header/section checks), and row
ranges that do not tile the global row count.
"""
from __future__ import annotations

import json
import os
import zlib
from typing import Optional

import numpy as np

from ..dataset import Dataset as CoreDataset
from ..dataset import Metadata
from ..dataset_io import load_binary, save_binary
from ..reliability.checkpoint import atomic_write_text
from ..utils.log import Log
from . import binfind
from .dataset import ShardedDataset

MANIFEST_NAME = "manifest.json"
SHARD_CACHE_SCHEMA = 1


class ShardCacheError(ValueError):
    """Loud shard-cache rejection (mismatched or damaged cache)."""


def _shard_file(i: int) -> str:
    return f"shard_{i}.bin"


def _manifest_crc(man: dict) -> int:
    """Manifest self-digest: crc32 over the CANONICAL JSON (sorted
    keys, compact separators — independent of on-disk pretty-printing)
    of every field except the digest itself.  The atomic tmp+rename
    writer rules out torn COMMITS, but not a flipped page or a partial
    overwrite by an outside tool — this catches field-level corruption
    that still parses as valid JSON."""
    body = {k: v for k, v in man.items() if k != "manifest_crc"}
    blob = json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode()
    return zlib.crc32(blob) & 0xFFFFFFFF


def _shard_core(sds: ShardedDataset, i: int) -> CoreDataset:
    """A per-shard CoreDataset view (shared mappers/groups, this
    shard's bins + metadata slice) for the v2 writer."""
    a, b = sds.shard_ranges[i]
    sd = CoreDataset.from_reference_for_push(sds, b - a)
    sd.group_bins = sds.shard_bins[i]
    sd._pushed_rows = b - a
    md = sds.metadata
    sd.metadata.set_label(md.label[a:b])
    if md.weight is not None:
        sd.metadata.set_weight(md.weight[a:b])
    return sd


def save_shard_cache(sds: ShardedDataset, cache_dir: str) -> str:
    """Persist every shard as its own v2 binary-cache file, then
    commit the manifest.  Returns the manifest path."""
    os.makedirs(cache_dir, exist_ok=True)
    shards = []
    for i in range(sds.world_size):
        path = os.path.join(cache_dir, _shard_file(i))
        save_binary(_shard_core(sds, i), path)
        a, b = sds.shard_ranges[i]
        shards.append({"file": _shard_file(i), "rows": int(b - a),
                       "bytes": int(os.path.getsize(path))})
    lay = getattr(sds, "bin_layout", None)
    manifest = {
        "schema": SHARD_CACHE_SCHEMA,
        "world_size": int(sds.world_size),
        "num_data": int(sds.num_data),
        "num_total_features": int(sds.num_total_features),
        "max_bin": int(sds.max_bin),
        "row_ranges": [[int(a), int(b)] for a, b in sds.shard_ranges],
        "mapper_fingerprint": sds.bin_fingerprint,
        # storage layout of every shard's bin matrix (packing.py);
        # absent/None = 8-bit.  Recorded here so a loader can refuse a
        # width mismatch BEFORE interpreting any shard's bytes
        "bin_packing": lay.to_state() if lay is not None else None,
        "shards": shards,
    }
    manifest["manifest_crc"] = _manifest_crc(manifest)
    mpath = os.path.join(cache_dir, MANIFEST_NAME)
    atomic_write_text(mpath, json.dumps(manifest, indent=1,
                                        sort_keys=True))
    Log.info(f"Saved sharded dataset cache to {cache_dir} "
             f"({sds.world_size} shard(s), {sds.num_data} rows)")
    return mpath


def has_shard_cache(cache_dir: str) -> bool:
    return bool(cache_dir) and os.path.isfile(
        os.path.join(cache_dir, MANIFEST_NAME))


def load_shard_cache(cache_dir: str,
                     expect_world_size: Optional[int] = None,
                     config=None) -> ShardedDataset:
    """Reload a shard cache into a ShardedDataset.  Each shard's bin
    section comes back as a read-only memmap (the v2 zero-copy
    reload); every mismatch listed in the module docstring raises
    :class:`ShardCacheError` instead of training silently wrong."""
    mpath = os.path.join(cache_dir, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        raise ShardCacheError(
            f"{cache_dir}: no shard-cache manifest ({MANIFEST_NAME}) "
            "— not a shard cache, or an interrupted save that never "
            "committed (reconstruct to repair)")
    try:
        with open(mpath) as f:
            man = json.load(f)
    except Exception as e:
        raise ShardCacheError(
            f"{mpath}: corrupted shard-cache manifest "
            f"({type(e).__name__}: {e})") from e
    if "manifest_crc" in man:
        want = int(man["manifest_crc"])
        got = _manifest_crc(man)
        if got != want:
            raise ShardCacheError(
                f"{mpath}: manifest self-digest mismatch (recorded "
                f"{want:#010x}, computed {got:#010x}) — torn or "
                "corrupted manifest; reconstruct the cache")
    else:
        Log.warning(f"{mpath}: manifest carries no self-digest "
                    "(pre-digest cache) — loading unverified; "
                    "re-save to add it")
    if man.get("schema") != SHARD_CACHE_SCHEMA:
        raise ShardCacheError(
            f"{mpath}: shard-cache schema {man.get('schema')!r} "
            f"(this build reads {SHARD_CACHE_SCHEMA})")
    world = int(man["world_size"])
    if expect_world_size is not None and world != int(expect_world_size):
        raise ShardCacheError(
            f"{cache_dir}: shard cache was built for world size "
            f"{world}, this run asked for {int(expect_world_size)} — "
            "re-shard the cache (reconstruct with the new "
            "sharded_shards) instead of silently re-splitting rows")
    ranges = [(int(a), int(b)) for a, b in man["row_ranges"]]
    if len(ranges) != world or len(man["shards"]) != world:
        raise ShardCacheError(
            f"{mpath}: manifest lists {len(man['shards'])} shard(s) / "
            f"{len(ranges)} range(s) for world size {world}")
    pos = 0
    for a, b in ranges:
        if a != pos or b < a:
            raise ShardCacheError(
                f"{mpath}: row ranges do not tile [0, "
                f"{man['num_data']}) contiguously (at [{a}, {b}))")
        pos = b
    if pos != int(man["num_data"]):
        raise ShardCacheError(
            f"{mpath}: row ranges cover {pos} rows, manifest says "
            f"{man['num_data']}")

    from ..packing import BinLayout, resolve_bin_packing
    man_lay = BinLayout.from_state(man.get("bin_packing"))
    if config is not None:
        want = resolve_bin_packing(config)
        if want == "8bit" and man_lay is not None:
            # "8bit" is also the DEFAULT, so this cannot refuse — a
            # default-params run must be able to reload the packed
            # cache it just built.  The recorded layout is kept (every
            # consumer reads through bin_layout; no mis-bin path)
            Log.warning(
                f"{cache_dir}: shard cache holds nibble-packed bin "
                f"matrices ({man_lay!r}); bin_packing=8bit applies "
                "to new constructions — the cached layout is kept "
                "(reconstruct the cache for unpacked shards)")
        elif want == "4bit" and man_lay is None:
            raise ShardCacheError(
                f"{cache_dir}: shard cache holds 8-bit bin matrices "
                "but this run asked for bin_packing=4bit — "
                "reconstruct the cache under bin_packing=4bit")
        elif want == "2bit" and (man_lay is None
                                 or man_lay.crumb_groups == 0):
            raise ShardCacheError(
                f"{cache_dir}: shard cache holds "
                + ("8-bit" if man_lay is None else "crumb-free packed")
                + " bin matrices but this run asked for "
                "bin_packing=2bit — reconstruct the cache under "
                "bin_packing=2bit")

    cores = []
    for i, rec in enumerate(man["shards"]):
        path = os.path.join(cache_dir, rec["file"])
        if not os.path.isfile(path):
            raise ShardCacheError(f"{cache_dir}: shard file "
                                  f"{rec['file']} is missing")
        size = os.path.getsize(path)
        if size < int(rec["bytes"]):
            raise ShardCacheError(
                f"{path}: truncated shard file ({size} bytes, "
                f"manifest recorded {rec['bytes']})")
        core = load_binary(path)
        if core.num_data != int(rec["rows"]):
            raise ShardCacheError(
                f"{path}: shard holds {core.num_data} rows, manifest "
                f"recorded {rec['rows']}")
        shard_lay = getattr(core, "bin_layout", None)
        if (shard_lay is None) != (man_lay is None) or (
                shard_lay is not None
                and shard_lay.to_state() != man_lay.to_state()):
            raise ShardCacheError(
                f"{path}: shard storage layout "
                f"({shard_lay!r}) disagrees with the manifest "
                f"({man_lay!r}) — stale shard next to a newer "
                "manifest (or vice versa); reconstruct the cache")
        fp = binfind.mapper_fingerprint(core.mappers, core._bundles,
                                        core.max_bin)
        if fp != man["mapper_fingerprint"]:
            raise ShardCacheError(
                f"{path}: shard mapper fingerprint {fp[:12]}... does "
                f"not match the manifest "
                f"({man['mapper_fingerprint'][:12]}...) — stale shard "
                "next to a newer manifest (or vice versa); "
                "reconstruct the cache")
        cores.append(core)

    sds = ShardedDataset()
    tpl = cores[0]
    sds.config = config if config is not None else tpl.config
    sds.num_data = int(man["num_data"])
    sds.num_total_features = tpl.num_total_features
    sds.max_bin = tpl.max_bin
    sds.mappers = tpl.mappers
    sds.used_features = tpl.used_features
    sds.features = tpl.features
    sds.group_num_bin = tpl.group_num_bin
    sds.group_is_multi = tpl.group_is_multi
    sds.bin_layout = man_lay
    sds._bundles = tpl._bundles
    sds.feature_names = tpl.feature_names
    sds._categorical_features = tpl._categorical_features
    sds.monotone_constraints = tpl.monotone_constraints
    sds.world_size = world
    sds.shard_ranges = ranges
    sds.shard_bins = [c.group_bins for c in cores]
    sds.bin_fingerprint = man["mapper_fingerprint"]
    md = Metadata(sds.num_data)
    md.label = np.concatenate(
        [np.asarray(c.metadata.label, dtype=np.float32)
         for c in cores]) if cores else md.label
    if all(c.metadata.weight is not None for c in cores) and cores:
        md.weight = np.concatenate(
            [np.asarray(c.metadata.weight, dtype=np.float32)
             for c in cores])
    sds.metadata = md
    Log.info(f"Loaded sharded dataset cache from {cache_dir} "
             f"({world} shard(s), {sds.num_data} rows, zero-copy "
             "shard maps)")
    return sds
