"""ShardedDataset: the mesh-sharded training matrix.

End-to-end sharded data plane (ROADMAP item 1): instead of ONE
host-resident packed ``(N, G)`` uint8 matrix (``dataset.py``), the
training rows are split into disjoint contiguous participant ranges,
bin mappers are fitted DISTRIBUTED (``binfind.py`` — per-range
boundary candidates allgathered and deterministically merged, the
reference ``DatasetLoader`` bin-boundary sync), and each range is
stream-ingested through the r11 two-round push protocol
(``Dataset.from_reference_for_push`` + chunked ``push_rows``) into its
OWN per-shard bin matrix.  The grower places the shards straight onto
their mesh devices (``ShardingPolicy.place_row_shards`` — the host
never materializes the concatenated matrix on the mesh path) and the
data-parallel histogram allreduce rides the same collective seams the
single-matrix route compiles to, so trees are BYTE-IDENTICAL across
the two routes (tests/test_sharded.py, the ``sharded_construct``
MULTICHIP gate).

Host peak memory is samples + one streaming chunk + the per-shard
uint8 matrices (the LiteMORT rows-per-chip argument, PAPERS.md arxiv
2001.09419): sharding buys capacity per participant, not just per
fleet.  The shard-cache v2 (``cache.py``) persists the shards +
manifest for zero-copy reload.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..dataset import Dataset as CoreDataset
from ..dataset import Metadata
from ..reliability.faults import FAULTS
from ..reliability.watchdog import run_with_deadline
from ..telemetry import TELEMETRY
from ..utils.log import Log
from . import binfind


def shard_row_ranges(num_data: int, num_shards: int
                     ) -> List[Tuple[int, int]]:
    """Disjoint contiguous [start, stop) participant ranges covering
    ``num_data`` rows — ``np.array_split`` semantics (first
    ``num_data % num_shards`` shards one row longer), deterministic."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    bounds = np.linspace(0, num_data, num_shards + 1).astype(np.int64)
    return [(int(bounds[i]), int(bounds[i + 1]))
            for i in range(num_shards)]


class ShardedDataset(CoreDataset):
    """A constructed dataset whose packed bin matrix lives as
    per-participant row shards (``shard_bins``) instead of one
    ``group_bins`` array.  All mapper/feature/group metadata is the
    merged-fit result shared by every shard; ``metadata`` is the
    GLOBAL view (labels/weights in original row order)."""

    def __init__(self):
        super().__init__()
        self.shard_bins: List[np.ndarray] = []
        self.shard_ranges: List[Tuple[int, int]] = []
        self.world_size = 0
        self.bin_fingerprint = ""

    # engine.train / Booster accept lazy datasets and call construct()
    # — a ShardedDataset is already constructed
    def construct(self, config: Optional[Config] = None
                  ) -> "ShardedDataset":
        return self

    def construct_aligned(self, ref_core, config) -> "ShardedDataset":
        return self

    def assembled_group_bins(self) -> np.ndarray:
        """The concatenated (N, G) matrix — parity checks and the
        no-mesh fallback only; the mesh training path never calls
        this (shards go to devices individually)."""
        return np.concatenate(self.shard_bins, axis=0)

    # ------------------------------------------------------------------
    @classmethod
    def construct_sharded(cls, data, label=None, weight=None,
                          group=None, init_score=None,
                          config: Optional[Config] = None,
                          num_shards: Optional[int] = None,
                          categorical_features: Optional[Sequence[int]]
                          = None,
                          feature_names: Optional[Sequence[str]] = None,
                          collective=None) -> "ShardedDataset":
        """Build the sharded dataset from an in-memory float matrix
        (or a text file path, parsed through the standard loader).

        1. rows split into ``num_shards`` (default
           ``config.sharded_shards``) disjoint contiguous ranges;
        2. distributed bin finding: per-range boundary candidates ->
           instrumented allgather -> deterministic merge -> the ONE
           threaded ``_fit_mappers`` path (+ EFB bundling) — identical
           mappers on every shard, byte-equal to a single-host fit
           whenever the quotas cover the shards;
        3. per-shard streaming ingest (``from_reference_for_push`` +
           ``streaming_chunk_rows`` chunked pushes) into per-shard bin
           matrices, behind the ``sharded.ingest`` fault seam.
        """
        config = config or Config()
        if isinstance(data, str):
            from ..data_loader import load_file
            data, label_from_file, extras = load_file(data, config)
            if label is None:
                label = label_from_file
            if weight is None:
                weight = extras.get("weight")
            if group is None:
                group = extras.get("group")
            if categorical_features is None \
                    and extras.get("categorical_feature"):
                categorical_features = extras["categorical_feature"]
        if hasattr(data, "tocsc") and hasattr(data, "nnz"):
            Log.fatal("sharded construction does not take sparse "
                      "input yet — densify, or use the single-matrix "
                      "sparse path (sharded_shards=0)")
        if group is not None:
            Log.fatal("sharded construction does not support query "
                      "groups yet — queries must not span shards "
                      "(same bound as multi-host ranking)")
        X = np.asarray(data, dtype=np.float64)
        if X.ndim != 2:
            raise ValueError("data must be 2-dimensional")
        num_data, num_features = X.shape
        world = int(num_shards if num_shards is not None
                    else getattr(config, "sharded_shards", 0) or 0)
        if world < 1:
            raise ValueError(
                "construct_sharded needs num_shards >= 1 (or "
                "sharded_shards set in the config)")
        if world > max(1, num_data):
            # a hard error, not a silent clamp: a clamped world size
            # would commit a shard cache whose manifest disagrees with
            # the UNCHANGED config on the very next run
            Log.fatal(f"sharded_shards={world} exceeds the {num_data} "
                      "data rows — lower sharded_shards (every "
                      "participant needs at least one row)")
        ranges = shard_row_ranges(num_data, world)

        self = cls()
        self.config = config
        self.num_data = num_data
        self.num_total_features = num_features
        self.max_bin = config.max_bin
        self.world_size = world
        self.shard_ranges = ranges
        self.feature_names = list(feature_names) if feature_names else [
            f"Column_{i}" for i in range(num_features)]
        cat_set = set(categorical_features or [])

        # degraded-mode continuation (docs/RELIABILITY.md): with
        # sharded_allow_degraded on, a participant whose binfind or
        # ingest seam dies — or hangs past watchdog_collective_s —
        # is EXCLUDED and construction restarts on the surviving
        # participants' rows with quota-rebalanced shards (byte-
        # identical to a from-scratch run on the surviving world,
        # because it IS one).  Default off = today's fail-fast.  The
        # per-participant deadline only arms in degraded mode: in
        # fail-fast mode a long ingest must not spuriously stall-error
        # under a deadline sized for collective ops.
        allow_degraded = bool(getattr(config, "sharded_allow_degraded",
                                      False))
        part_deadline = float(getattr(config, "watchdog_collective_s",
                                      0.0) or 0.0) \
            if allow_degraded else 0.0

        # ---- distributed bin finding (binfind.py) ----
        with TELEMETRY.span("shard_binfind", shards=world,
                            rows=num_data):
            cands = []
            dead: List[int] = []
            for i, (a, b) in enumerate(ranges):
                try:
                    cands.append(run_with_deadline(
                        binfind.collect_candidates, part_deadline,
                        "shard_binfind", "sharded.binfind",
                        X[a:b], config, rank=i, world=world))
                except Exception as e:  # noqa: BLE001 - mode decides
                    if not allow_degraded:
                        raise
                    Log.warning(
                        f"sharded participant {i} FAILED during bin "
                        f"finding ({type(e).__name__}: {e}) — "
                        "excluding it (sharded_allow_degraded=true)")
                    dead.append(i)
            if dead:
                return cls._construct_degraded(
                    X, label, weight, init_score, config, ranges,
                    dead, categorical_features, feature_names,
                    collective)
            binfind.warn_if_quota_truncated(cands)
            sample_vals, sample_rows, total_sample = \
                binfind.merge_candidates(cands, collective)
            self.mappers = self._fit_mappers(sample_vals, total_sample,
                                             config, cat_set)
        self.used_features = [i for i, m in enumerate(self.mappers)
                              if not m.is_trivial]
        if not self.used_features:
            Log.warning("There are no meaningful features; "
                        "all features are constant or filtered")
        self._build_groups(reference=None, sample_nonzero=sample_rows,
                           sample_cnt=total_sample)
        self._categorical_features = list(categorical_features or [])
        self._resolve_monotone(config)
        self.bin_fingerprint = binfind.mapper_fingerprint(
            self.mappers, self._bundles, self.max_bin)

        # ---- per-shard streaming ingest ----
        chunk_rows = max(1, int(config.streaming_chunk_rows))
        for i, (a, b) in enumerate(ranges):
            def _ingest(a=a, b=b):
                FAULTS.fault_point("sharded.ingest")
                sd = CoreDataset.from_reference_for_push(self, b - a)
                for start in range(0, b - a, chunk_rows):
                    stop = min(b - a, start + chunk_rows)
                    sd.push_rows(X[a + start:a + stop], start)
                sd.finish_load()
                return sd
            try:
                with TELEMETRY.span("shard_ingest", shard=i,
                                    rows=b - a):
                    sd = run_with_deadline(
                        _ingest, part_deadline, "shard_ingest",
                        "sharded.ingest")
            except Exception as e:  # noqa: BLE001 - mode decides
                if not allow_degraded:
                    raise
                Log.warning(
                    f"sharded participant {i} FAILED during ingest "
                    f"({type(e).__name__}: {e}) — excluding it "
                    "(sharded_allow_degraded=true)")
                return cls._construct_degraded(
                    X, label, weight, init_score, config, ranges,
                    [i], categorical_features, feature_names,
                    collective, seam="sharded.ingest")
            self.shard_bins.append(sd.group_bins)
            if TELEMETRY.on:
                TELEMETRY.add("sharded_rows_ingested", int(b - a))
        if TELEMETRY.on:
            TELEMETRY.gauge("sharded_world_size", world)

        self.metadata = Metadata(num_data)
        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weight(weight)
        self.metadata.set_init_score(init_score)
        return self

    # ------------------------------------------------------------------
    @classmethod
    def _construct_degraded(cls, X, label, weight, init_score, config,
                            ranges, dead: List[int],
                            categorical_features, feature_names,
                            collective,
                            seam: str = "sharded.binfind"
                            ) -> "ShardedDataset":
        """Degraded-mode restart (``sharded_allow_degraded``): drop
        the dead participants' row ranges and rebuild FROM SCRATCH on
        the surviving rows with a quota-rebalanced world — the
        degraded dataset is literally a from-scratch construction on
        the surviving world, which is what makes its trees
        byte-identical to one (pinned by ``tests/test_chaos.py``).
        The excluded rows are LOST — logged loudly per participant
        and counted (``sharded_degraded_exclusions``) so the loss is
        never silent."""
        dead_set = set(dead)
        survivors = [i for i in range(len(ranges))
                     if i not in dead_set]
        if not survivors:
            Log.fatal(
                "sharded degraded mode: every participant failed — "
                "nothing left to continue on (replay the fault plan "
                "seed to reproduce)")
        lost_rows = sum(b - a for i, (a, b) in enumerate(ranges)
                        if i in dead_set)
        keep = np.concatenate([np.arange(a, b, dtype=np.int64)
                               for i, (a, b) in enumerate(ranges)
                               if i not in dead_set])

        def _slice(arr, what: str):
            if arr is None:
                return None
            arr = np.asarray(arr)
            if arr.ndim >= 1 and arr.shape[0] == X.shape[0]:
                return arr[keep]
            Log.fatal(
                f"sharded degraded mode cannot re-slice {what} of "
                f"shape {arr.shape} to the surviving "
                f"{len(keep)}-row world — disable "
                "sharded_allow_degraded or drop the metadata")

        if TELEMETRY.on:
            TELEMETRY.add("sharded_degraded_exclusions", len(dead))
            TELEMETRY.gauge("sharded_degraded_world", len(survivors))
        TELEMETRY.flight.dump(
            "sharded_degraded", seam=seam,
            excluded=sorted(dead_set), surviving=len(survivors),
            lost_rows=int(lost_rows))
        Log.warning(
            f"sharded DEGRADED continuation: excluded participant(s) "
            f"{sorted(dead_set)} ({lost_rows} rows lost), continuing "
            f"on the surviving {len(survivors)}-participant world "
            "with rebalanced sample quotas "
            "(sharded_allow_degraded=true; trees are byte-identical "
            "to a from-scratch run on the survivors)")
        return cls.construct_sharded(
            X[keep], label=_slice(label, "label"),
            weight=_slice(weight, "weight"),
            init_score=_slice(init_score, "init_score"),
            config=config, num_shards=len(survivors),
            categorical_features=categorical_features,
            feature_names=feature_names, collective=collective)
