"""ShardedDataset: the mesh-sharded training matrix.

End-to-end sharded data plane (ROADMAP item 1): instead of ONE
host-resident packed ``(N, G)`` uint8 matrix (``dataset.py``), the
training rows are split into disjoint contiguous participant ranges,
bin mappers are fitted DISTRIBUTED (``binfind.py`` — per-range
boundary candidates allgathered and deterministically merged, the
reference ``DatasetLoader`` bin-boundary sync), and each range is
stream-ingested through the r11 two-round push protocol
(``Dataset.from_reference_for_push`` + chunked ``push_rows``) into its
OWN per-shard bin matrix.  The grower places the shards straight onto
their mesh devices (``ShardingPolicy.place_row_shards`` — the host
never materializes the concatenated matrix on the mesh path) and the
data-parallel histogram allreduce rides the same collective seams the
single-matrix route compiles to, so trees are BYTE-IDENTICAL across
the two routes (tests/test_sharded.py, the ``sharded_construct``
MULTICHIP gate).

Host peak memory is samples + one streaming chunk + the per-shard
uint8 matrices (the LiteMORT rows-per-chip argument, PAPERS.md arxiv
2001.09419): sharding buys capacity per participant, not just per
fleet.  The shard-cache v2 (``cache.py``) persists the shards +
manifest for zero-copy reload.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..dataset import Dataset as CoreDataset
from ..dataset import Metadata
from ..reliability.faults import FAULTS
from ..telemetry import TELEMETRY
from ..utils.log import Log
from . import binfind


def shard_row_ranges(num_data: int, num_shards: int
                     ) -> List[Tuple[int, int]]:
    """Disjoint contiguous [start, stop) participant ranges covering
    ``num_data`` rows — ``np.array_split`` semantics (first
    ``num_data % num_shards`` shards one row longer), deterministic."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    bounds = np.linspace(0, num_data, num_shards + 1).astype(np.int64)
    return [(int(bounds[i]), int(bounds[i + 1]))
            for i in range(num_shards)]


class ShardedDataset(CoreDataset):
    """A constructed dataset whose packed bin matrix lives as
    per-participant row shards (``shard_bins``) instead of one
    ``group_bins`` array.  All mapper/feature/group metadata is the
    merged-fit result shared by every shard; ``metadata`` is the
    GLOBAL view (labels/weights in original row order)."""

    def __init__(self):
        super().__init__()
        self.shard_bins: List[np.ndarray] = []
        self.shard_ranges: List[Tuple[int, int]] = []
        self.world_size = 0
        self.bin_fingerprint = ""

    # engine.train / Booster accept lazy datasets and call construct()
    # — a ShardedDataset is already constructed
    def construct(self, config: Optional[Config] = None
                  ) -> "ShardedDataset":
        return self

    def construct_aligned(self, ref_core, config) -> "ShardedDataset":
        return self

    def assembled_group_bins(self) -> np.ndarray:
        """The concatenated (N, G) matrix — parity checks and the
        no-mesh fallback only; the mesh training path never calls
        this (shards go to devices individually)."""
        return np.concatenate(self.shard_bins, axis=0)

    # ------------------------------------------------------------------
    @classmethod
    def construct_sharded(cls, data, label=None, weight=None,
                          group=None, init_score=None,
                          config: Optional[Config] = None,
                          num_shards: Optional[int] = None,
                          categorical_features: Optional[Sequence[int]]
                          = None,
                          feature_names: Optional[Sequence[str]] = None,
                          collective=None) -> "ShardedDataset":
        """Build the sharded dataset from an in-memory float matrix
        (or a text file path, parsed through the standard loader).

        1. rows split into ``num_shards`` (default
           ``config.sharded_shards``) disjoint contiguous ranges;
        2. distributed bin finding: per-range boundary candidates ->
           instrumented allgather -> deterministic merge -> the ONE
           threaded ``_fit_mappers`` path (+ EFB bundling) — identical
           mappers on every shard, byte-equal to a single-host fit
           whenever the quotas cover the shards;
        3. per-shard streaming ingest (``from_reference_for_push`` +
           ``streaming_chunk_rows`` chunked pushes) into per-shard bin
           matrices, behind the ``sharded.ingest`` fault seam.
        """
        config = config or Config()
        if isinstance(data, str):
            from ..data_loader import load_file
            data, label_from_file, extras = load_file(data, config)
            if label is None:
                label = label_from_file
            if weight is None:
                weight = extras.get("weight")
            if group is None:
                group = extras.get("group")
            if categorical_features is None \
                    and extras.get("categorical_feature"):
                categorical_features = extras["categorical_feature"]
        if hasattr(data, "tocsc") and hasattr(data, "nnz"):
            Log.fatal("sharded construction does not take sparse "
                      "input yet — densify, or use the single-matrix "
                      "sparse path (sharded_shards=0)")
        if group is not None:
            Log.fatal("sharded construction does not support query "
                      "groups yet — queries must not span shards "
                      "(same bound as multi-host ranking)")
        X = np.asarray(data, dtype=np.float64)
        if X.ndim != 2:
            raise ValueError("data must be 2-dimensional")
        num_data, num_features = X.shape
        world = int(num_shards if num_shards is not None
                    else getattr(config, "sharded_shards", 0) or 0)
        if world < 1:
            raise ValueError(
                "construct_sharded needs num_shards >= 1 (or "
                "sharded_shards set in the config)")
        if world > max(1, num_data):
            # a hard error, not a silent clamp: a clamped world size
            # would commit a shard cache whose manifest disagrees with
            # the UNCHANGED config on the very next run
            Log.fatal(f"sharded_shards={world} exceeds the {num_data} "
                      "data rows — lower sharded_shards (every "
                      "participant needs at least one row)")
        ranges = shard_row_ranges(num_data, world)

        self = cls()
        self.config = config
        self.num_data = num_data
        self.num_total_features = num_features
        self.max_bin = config.max_bin
        self.world_size = world
        self.shard_ranges = ranges
        self.feature_names = list(feature_names) if feature_names else [
            f"Column_{i}" for i in range(num_features)]
        cat_set = set(categorical_features or [])

        # ---- distributed bin finding (binfind.py) ----
        with TELEMETRY.span("shard_binfind", shards=world,
                            rows=num_data):
            cands = [binfind.collect_candidates(X[a:b], config,
                                                rank=i, world=world)
                     for i, (a, b) in enumerate(ranges)]
            binfind.warn_if_quota_truncated(cands)
            sample_vals, sample_rows, total_sample = \
                binfind.merge_candidates(cands, collective)
            self.mappers = self._fit_mappers(sample_vals, total_sample,
                                             config, cat_set)
        self.used_features = [i for i, m in enumerate(self.mappers)
                              if not m.is_trivial]
        if not self.used_features:
            Log.warning("There are no meaningful features; "
                        "all features are constant or filtered")
        self._build_groups(reference=None, sample_nonzero=sample_rows,
                           sample_cnt=total_sample)
        self._categorical_features = list(categorical_features or [])
        self._resolve_monotone(config)
        self.bin_fingerprint = binfind.mapper_fingerprint(
            self.mappers, self._bundles, self.max_bin)

        # ---- per-shard streaming ingest ----
        chunk_rows = max(1, int(config.streaming_chunk_rows))
        for i, (a, b) in enumerate(ranges):
            FAULTS.fault_point("sharded.ingest")
            with TELEMETRY.span("shard_ingest", shard=i, rows=b - a):
                sd = CoreDataset.from_reference_for_push(self, b - a)
                for start in range(0, b - a, chunk_rows):
                    stop = min(b - a, start + chunk_rows)
                    sd.push_rows(X[a + start:a + stop], start)
                sd.finish_load()
            self.shard_bins.append(sd.group_bins)
            if TELEMETRY.on:
                TELEMETRY.add("sharded_rows_ingested", int(b - a))
        if TELEMETRY.on:
            TELEMETRY.gauge("sharded_world_size", world)

        self.metadata = Metadata(num_data)
        if label is not None:
            self.metadata.set_label(label)
        self.metadata.set_weight(weight)
        self.metadata.set_init_score(init_score)
        return self
