"""RF: random forest mode.

Re-design of the reference RF (src/boosting/rf.hpp:18-180): gradients
computed once from zero scores, mandatory bagging + feature_fraction,
no shrinkage, leaf outputs converted through the objective's output
transform, and the tracked score is the running AVERAGE of tree
outputs (the MultiplyScore dance becomes an explicit running mean).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..utils.log import Log
from .gbdt import GBDT
from ..tree import Tree
from ..learner.grower import TreeArrays


class RF(GBDT):
    def __init__(self, config: Config, train_set: Dataset, **kwargs):
        super().__init__(config, train_set, **kwargs)
        if config.num_class > 1:
            Log.fatal("cannot use RF for multi-class")
        if not (config.bagging_freq > 0 and 0 < config.bagging_fraction < 1):
            Log.fatal("RF requires bagging "
                      "(bagging_freq > 0, bagging_fraction in (0,1))")
        if not (0 < config.feature_fraction < 1):
            Log.fatal("RF requires feature_fraction in (0, 1)")
        self.shrinkage_rate = 1.0
        self.average_output = True
        self.init_score = 0.0
        # fixed gradients from zero score (reference rf.hpp:82-88)
        zero = jnp.zeros_like(self.scores)
        self._fixed_g, self._fixed_h = self._grad_fn(zero)

    def train_one_iter(self, grad=None, hess=None) -> bool:
        if grad is None or hess is None:
            g, h = self._fixed_g, self._fixed_h
        else:
            return super().train_one_iter(grad, hess)

        counts, _ = self._bagging_counts(self.iter_)
        g, h = self._mask_gradients(g, h, counts)

        for k in range(self.num_class):
            feature_mask = self._feature_mask()
            tree_arrays, leaf_id, _ = self.grower.train_tree(
                g[k], h[k], counts, feature_mask,
                qkey=self._host_qkey(k))
            tree_arrays = self._finalize_tree(tree_arrays, leaf_id, k,
                                              self.scores, counts)
            # convert leaf outputs (reference rf.hpp ConvertTreeOutput)
            conv = self.objective.convert_output(tree_arrays.leaf_value)
            tree_arrays = tree_arrays._replace(leaf_value=conv)
            self.device_trees.append(tree_arrays)
            # running average: score = (score*t + tree_out) / (t+1)
            t = float(self.iter_)
            delta = self._update_train_fn(
                self.scores * t, leaf_id, tree_arrays.leaf_value, k, 1.0)
            self.scores = delta / (t + 1.0)
            for vs in self.valid_sets:
                pv = self._predict_valid_fn(tree_arrays, vs.bins)
                vs.scores = (vs.scores * t).at[k].add(pv) / (t + 1.0)
            self._pending.append(("tree", tree_arrays, 1.0, 0.0))
            self._tree_scale.append(1.0)
            self._tree_shrink.append(1.0)
        self.iter_ += 1
        return False

    def eval_metrics(self, which: str = "all"):
        """Scores are already in output space (averaged converted
        outputs) — metrics must not re-apply the objective transform."""
        saved = self.objective
        self.objective = None
        try:
            return super().eval_metrics(which)
        finally:
            self.objective = saved
