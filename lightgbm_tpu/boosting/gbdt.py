"""GBDT: the boosting orchestrator.

TPU-native re-design of the reference GBDT
(reference: src/boosting/gbdt.{h,cpp}; TrainOneIter hot path
gbdt.cpp:386-481, bagging :234-316, boost_from_average :362-384,
early stopping :582-639, score updating :528-580).  Scores, gradients
and the binned matrix live on device for the whole run; one boosting
iteration is ONE jitted call (gradients -> bagging mask -> tree growth
-> score update -> validation-score update) with no host sync.  Host
work per iteration is O(1) dispatch only; finished trees stay on device
and are pulled to host models in a single batched transfer when the
model is actually needed (flush_models) — on a remote-attached TPU
every host pull costs a full RPC round trip, so the loop never blocks
on one.
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..dataset import Dataset
from ..learner.grower import TreeGrower, TreeArrays
from ..metrics import Metric, create_metrics
from ..objectives import Objective, create_objective
from ..ops.histogram import leaf_value_broadcast
from ..ops.predict import predict_binned
from ..reliability.checkpoint import CheckpointError
from ..reliability.faults import FAULTS
from ..reliability.retry import RetryPolicy, retry_call
from ..telemetry import TELEMETRY
from ..tree import Tree
from ..utils.log import Log, PhaseTimer


def fit_chunk_slope(times: Dict[int, float]) -> Tuple[float, float]:
    """Least-squares fit of the per-iteration chunk cost model
    ``per_tree(c) = base + slope * c`` from {chunk_len: per_tree_s}
    probe timings (the ROOFLINE round-6 fit: 25.75 + 0.075·c ms on
    v5e with the legacy 18-buffer carry).  Returns (base_s, slope_s)."""
    cs = np.asarray(sorted(times), dtype=np.float64)
    ts = np.asarray([times[int(c)] for c in cs], dtype=np.float64)
    slope, base = np.polyfit(cs, ts, 1)
    return float(base), float(slope)


def pick_dispatch_chunk(base_s: float, slope_s: float, dispatch_s: float,
                        cmin: int = 10, cmax: int = 90) -> int:
    """Amortization point of ``per_tree(c) = base + slope·c +
    dispatch/c``: c* = sqrt(dispatch / slope), clamped to [cmin, cmax].
    A non-positive slope (the packed carry's target state) means longer
    chunks are free — take cmax and amortize the dispatch RPC fully."""
    del base_s                     # the additive base doesn't move c*
    if slope_s <= 0.0:
        return cmax
    c = (max(dispatch_s, 0.0) / slope_s) ** 0.5
    return int(min(max(round(c), cmin), cmax))


class _ValidSet:
    """Per-validation-set device state (the ScoreUpdater analog,
    reference score_updater.hpp:17-120)."""

    def __init__(self, dataset: Dataset, num_class: int, init_score: float,
                 metrics: List[Metric]):
        self.dataset = dataset
        self.num_data = dataset.num_data
        self.bins = jax.device_put(dataset.group_bins)
        self.scores = jnp.full((num_class, dataset.num_data), 0.0,
                               dtype=jnp.float32)
        if dataset.metadata.init_score is not None:
            init = dataset.metadata.init_score.astype(np.float32)
            self.scores = jnp.asarray(
                init.reshape(num_class, dataset.num_data))
        if init_score != 0.0:
            self.scores = self.scores + init_score
        self.metrics = metrics


class GBDT:
    """Gradient Boosting Decision Tree trainer."""

    def __init__(self, config: Config, train_set: Dataset,
                 objective: Optional[Objective] = None,
                 custom_objective: bool = False):
        self.config = config
        self.train_set = train_set
        self.num_data = train_set.num_data
        self.objective = (None if custom_objective else
                          (objective if objective is not None
                           else create_objective(config)))
        self.num_class = config.num_tree_per_iteration
        self.shrinkage_rate = config.learning_rate

        if self.objective is not None:
            self.objective.init(train_set.metadata, self.num_data)

        self.grower = TreeGrower(train_set, config)
        # multi-host (finalize_global): device metadata arrays must
        # follow the assembled per-host-padded row layout, sharded
        self._mh = self.grower._mh_local is not None
        if self._mh and self.objective is not None:
            if self.objective.is_renew_tree_output:
                Log.fatal(
                    "multi-host training does not support "
                    "RenewTreeOutput objectives (l1/huber/quantile/"
                    f"mape) yet — got {self.objective.name}; the "
                    "percentile refit needs a global sort across hosts")
            self.objective.repad_device_arrays(
                lambda a: self.grower.policy.place_rows(
                    self.grower.pad_rows(a)))
        self.models: List[Tree] = []
        self.device_trees: List[TreeArrays] = []   # kept for DART drops
        self.iter_ = 0
        self.train_metrics: List[Metric] = []
        self.valid_sets: List[_ValidSet] = []
        self.valid_names: List[str] = []

        # boost_from_average (reference gbdt.cpp:362-384)
        self.init_score = 0.0
        has_init = train_set.metadata.init_score is not None
        if (self.objective is not None and config.boost_from_average
                and not has_init and self.num_class == 1):
            self.init_score = float(self.objective.boost_from_score())
            if abs(self.init_score) > 1e-15:
                Log.info(f"Start training from score {self.init_score:f}")

        base = np.zeros((self.num_class, self.num_data), dtype=np.float32)
        if has_init:
            base += train_set.metadata.init_score.reshape(
                self.num_class, self.num_data).astype(np.float32)
        base += self.init_score
        padded = np.stack([self.grower.pad_rows(base[c])
                           for c in range(self.num_class)])
        self.scores = self.grower.policy.place_score_rows(padded)

        # per-phase wall-clock accounting (the TIMETAG analog,
        # reference gbdt.cpp:21-29/52-61); reported at Log.debug level
        # when training finishes
        self.timer = PhaseTimer()
        self._rng = np.random.RandomState(config.seed)
        self._bag_rng = jax.random.PRNGKey(config.bagging_seed)
        self._iter_key_rng = np.random.RandomState(config.bagging_seed)
        self._feat_rng = np.random.RandomState(config.feature_fraction_seed)
        self._grad_fn = jax.jit(self._compute_gradients)
        self._update_train_fn = jax.jit(self._update_train_scores)
        self._predict_valid_fn = jax.jit(self._predict_valid)
        self._eval_cache: Dict[Tuple[int, int], List[float]] = {}
        # lazily-materialized host models: finished device trees queue in
        # _pending as (TreeArrays, shrinkage, bias) and are pulled in one
        # batched transfer by flush_models()
        self._pending: List[Tuple[TreeArrays, float, float]] = []
        self._scale_offset = 0   # foreign (init_model) trees precede ours
        self._tree_scale: List[float] = []    # DART renorm per model idx
        self._tree_shrink: List[float] = []   # shrinkage at train time
        # (feeds the batched device predict; reset_parameter may vary it)
        self._applied_scale: List[float] = []  # scale baked into models[i]
        self._nl_window: List[jax.Array] = []  # deferred 1-leaf stop checks
        # (entries are () or (n,) device arrays — kept stacked so a
        # chunk never pays per-iteration slice dispatches)
        self._nl_count = 0
        # deferred no-split stop detection: each check is a device->host
        # pull (a full RPC round trip on a remote-attached chip, ~60 ms
        # measured) — amortize it far beyond the reference's every-
        # iteration check; 1-leaf trees contribute exactly zero score,
        # so the late rollback is exact (see _check_stop_window)
        self._stop_check_every = 64
        # threefry PRNGKey(seed) layout is [hi, lo] uint32 — verified
        # once so chunk key batches can be built host-side in numpy
        # (n PRNGKey dispatches per chunk each cost a remote RPC)
        self._np_keys_ok = bool(np.array_equal(
            np.asarray(jax.random.PRNGKey(7)),
            np.array([0, 7], np.uint32)))
        self._fused_step = None
        self._fused_chunk = None
        self._fused_chunk_n = 0
        # packed tree carry (round 7): the fused chunk stacks each
        # tree as ONE byte-packed record (tree.TreeRecordLayout) so
        # the scan carries 2 output buffers instead of 18 — the
        # round-6 diagnosis traced the per-iteration chunk penalty to
        # the backend's handling of the 18 O(chunk) stacked outputs.
        # "off" restores the legacy per-field carry (parity-pinned).
        self._packed_carry = str(getattr(config, "packed_tree_carry",
                                         "auto")).lower() \
            not in ("off", "false", "0")
        self._bag_state: Optional[jax.Array] = None
        # early stopping state per (dataset, metric-output)
        self._best_score: Dict[Tuple[int, int], float] = {}
        self._best_iter: Dict[Tuple[int, int], int] = {}
        self.best_iteration = -1

        # row weights as count channel (bagging multiplies into this)
        w = train_set.metadata.weight
        self._full_counts = self.grower.policy.place_rows(
            self.grower.pad_rows(np.ones(self.num_data,
                                         dtype=np.float32)))
        self._weights_dev = (None if w is None else
                             self.grower.policy.place_rows(
                                 self.grower.pad_rows(
                                     w.astype(np.float32))))
        self._bag_mask: Optional[jax.Array] = None

        # EVERY O(N) device array must cross the jit boundary as an
        # ARGUMENT, never as a closure: closures are inlined as MLIR
        # constants, which (a) makes XLA compile time linear in rows
        # (~80 s per million measured — a HIGGS-scale compile took
        # 25+ min) and (b) is impossible for multi-host sharded arrays
        # (tracing fetches values spanning non-addressable devices).
        # The captives pytree is built per call and bound to the usual
        # attributes for the dynamic extent of the trace (the grower's
        # _ohb_arg pattern).

    def _build_captives(self):
        obj_caps = {}
        if self.objective is not None:
            obj_caps = {k: v for k, v in self.objective.__dict__.items()
                        if k.endswith("_dev")
                        and isinstance(v, jax.Array)}
        return {
            "bins": self.grower.bins,
            "binsT": self.grower.binsT,
            "rv": self.grower._row_valid,
            "fc": self._full_counts,
            "w": self._weights_dev,
            "obj": obj_caps,
            "vbins": tuple(vs.bins for vs in self.valid_sets),
        }

    @contextmanager
    def _bound_captives(self, cap):
        if cap is None:
            yield
            return
        g, obj = self.grower, self.objective
        saved = (g.bins, g.binsT, g._row_valid, self._full_counts,
                 self._weights_dev,
                 {k: obj.__dict__[k] for k in cap["obj"]}
                 if obj is not None else {})
        g.bins, g.binsT = cap["bins"], cap["binsT"]
        g._row_valid = cap["rv"]
        self._full_counts, self._weights_dev = cap["fc"], cap["w"]
        if obj is not None:
            obj.__dict__.update(cap["obj"])
        try:
            yield
        finally:
            (g.bins, g.binsT, g._row_valid, self._full_counts,
             self._weights_dev) = saved[:5]
            if obj is not None:
                obj.__dict__.update(saved[5])

    # ------------------------------------------------------------------
    def add_valid(self, valid_set: Dataset, name: str) -> None:
        if self._mh:
            Log.fatal("multi-host training does not support validation "
                      "sets yet (metric scores live sharded across "
                      "hosts) — evaluate after training instead")
        # bin-alignment gate: validation trees are walked in TRAIN bin
        # space, so the valid set's mappers must be the training
        # mappers (feature_infos encodes the bin bounds — equal infos
        # means numerically identical binning).  The reference's
        # c_api/python package reject unaligned validation data too.
        if self.train_set is not None and \
                valid_set is not self.train_set and \
                valid_set.feature_infos() != self.train_set.feature_infos():
            Log.fatal(f"validation set {name!r} is not bin-aligned to "
                      "the training data — create it with "
                      "reference=<train dataset> (its own bin mappers "
                      "differ from the training mappers)")
        if self.train_set is not None and valid_set is not self.train_set:
            # storage-layout gate: equal feature_infos no longer imply
            # an equal matrix layout — the same data constructed under
            # a different bin_packing packs (and group-reorders)
            # differently, and _predict_valid walks the valid matrix
            # with the TRAINING set's packed_groups
            def _lay(ds):
                lay = getattr(ds, "bin_layout", None)
                return lay.to_state() if lay is not None else None
            if _lay(valid_set) != _lay(self.train_set):
                Log.fatal(
                    f"validation set {name!r} has a different bin-"
                    f"matrix storage layout ({_lay(valid_set)}) than "
                    f"the training data ({_lay(self.train_set)}) — "
                    "construct it with reference=<train dataset> or "
                    "the same bin_packing setting")
        metrics = create_metrics(self.config)
        for m in metrics:
            m.init(valid_set.metadata, valid_set.num_data)
        self.valid_sets.append(
            _ValidSet(valid_set, self.num_class, self.init_score, metrics))
        self.valid_names.append(name)

    def add_train_metrics(self) -> None:
        self.train_metrics = create_metrics(self.config)
        for m in self.train_metrics:
            m.init(self.train_set.metadata, self.num_data)

    # ------------------------------------------------------------------
    def _compute_gradients(self, scores):
        """scores: (K, n_padded) -> (K, n_padded) grad/hess, zero-padded."""
        if self._mh:
            # multi-host layout: per-host padding blocks are interleaved
            # — the objective's device arrays were re-padded to match,
            # so gradients run full-width (padded rows produce values
            # that never count: their leaf_id is -1)
            s = scores
        else:
            s = scores[:, :self.num_data]
        if self.num_class == 1:
            g, h = self.objective.get_gradients(s[0])
            g, h = g[None, :], h[None, :]
        else:
            g, h = self.objective.get_gradients(s.T)
            g, h = g.T, h.T
        pad = scores.shape[1] - s.shape[1]
        if pad:
            g = jnp.pad(g, ((0, 0), (0, pad)))
            h = jnp.pad(h, ((0, 0), (0, pad)))
        return g, h

    # ------------------------------------------------------------------
    def _bagging_counts(self, iteration: int):
        """Per-iteration bagging mask (reference gbdt.cpp:234-316 with
        mask-based rows instead of index subsets)."""
        cfg = self.config
        if cfg.bagging_freq <= 0 or cfg.bagging_fraction >= 1.0:
            return self._full_counts, None
        if iteration % cfg.bagging_freq == 0 or self._bag_mask is None:
            self._bag_rng, sub = jax.random.split(self._bag_rng)
            u = jax.random.uniform(sub, (self.grower.n_padded,))
            self._bag_mask = (u < cfg.bagging_fraction) & \
                (self._full_counts > 0)
        counts = jnp.where(self._bag_mask, 1.0, 0.0)
        return counts, self._bag_mask

    # ------------------------------------------------------------------
    def _feature_mask_np(self) -> np.ndarray:
        """Per-tree feature sampling (reference
        serial_tree_learner.cpp:252-345 BeforeTrain); host-side."""
        f = self.config.feature_fraction
        F = self.grower.num_features
        if f >= 1.0:
            return np.ones(F, dtype=bool)
        used = max(1, int(round(F * f)))
        idx = self._feat_rng.choice(F, size=used, replace=False)
        mask = np.zeros(F, dtype=bool)
        mask[idx] = True
        return mask

    def _feature_mask(self) -> jax.Array:
        return jnp.asarray(self._feature_mask_np())

    # ------------------------------------------------------------------
    def _update_train_scores(self, scores, leaf_id, leaf_value, class_idx,
                             shrinkage):
        delta = leaf_value_broadcast(leaf_id, leaf_value) * shrinkage
        return scores.at[class_idx].add(delta)

    def _predict_valid(self, tree: TreeArrays, bins):
        # train and reference-aligned validation matrices share ONE
        # storage layout (dataset alignment copies bin_layout), so the
        # grower's packed_groups applies to both
        g = self.grower
        return predict_binned(tree, bins, g.f_group, g.g2f_lut, g.f_missing,
                              g.f_default_bin, g.f_num_bin,
                              max_steps=self.config.num_leaves,
                              packed_groups=g.pack_P)

    # ------------------------------------------------------------------
    # hooks for DART/GOSS/RF subclasses --------------------------------
    def _before_boosting(self) -> None:
        """Called before gradient computation (DART drops trees here)."""

    def _after_iteration(self) -> None:
        """Called after the iteration's trees are in (DART normalizes)."""

    def _sample_rows(self, g, h, counts):
        """Row-sampling hook for the custom-gradient path; GOSS
        reweights gradients here."""
        return g, h, counts

    def _sample_rows_fused(self, g, h, counts, key):
        """Jit-traceable row-sampling hook (GOSS overrides)."""
        return g, h, counts

    def _sample_active(self) -> bool:
        """Whether _sample_rows_fused does anything this iteration
        (static per compile — GOSS flips it once)."""
        return False

    # ------------------------------------------------------------------
    def _use_bagging_fused(self) -> bool:
        """Whether the fused step draws a bagging mask (GOSS replaces
        bagging entirely — reference goss.hpp Bagging override)."""
        cfg = self.config
        return cfg.bagging_freq > 0 and cfg.bagging_fraction < 1.0

    # ------------------------------------------------------------------
    def _feature_masks(self) -> jax.Array:
        """(K, F) per-tree feature sampling masks for one iteration."""
        if self.config.feature_fraction >= 1.0:
            if not hasattr(self, "_full_feature_masks"):
                self._full_feature_masks = jnp.ones(
                    (self.num_class, self.grower.num_features), bool)
            return self._full_feature_masks
        return jnp.asarray(np.stack(
            [self._feature_mask_np() for _ in range(self.num_class)]))

    # ------------------------------------------------------------------
    def _build_fused(self):
        """One boosting iteration as a single jitted program: gradients,
        bagging draw, K tree growths, train-score and valid-score
        updates.  The only per-iteration host traffic left is the async
        dispatch itself."""
        vbins = tuple(vs.bins for vs in self.valid_sets)

        def step(scores, vscores, bag_mask, key, fmask, shrinkage,
                 ohb=None, cap=None, fresh_bag=False,
                 sample_active=False):
            # sample_active is a static cache key mirroring
            # self._sample_active(), which _boost_one reads at trace time
            del sample_active
            # trace-time only (retrace sentinel + compile counter):
            # runs once per compilation, never on the dispatch path
            TELEMETRY.note_trace("gbdt.fused_step",
                                 (scores.shape, len(vscores)))
            vb = vbins if cap is None else cap["vbins"]
            with self._bound_captives(cap):
                return self._boost_one(scores, vscores, bag_mask, key,
                                       fmask, shrinkage, fresh_bag,
                                       vb, ohb)

        # no donation here either: the same heap corruption bisected on
        # the fused chunk (see _build_fused_chunk) reproduces on this
        # per-iteration program once several booster shapes jit it in
        # one process — the C-API suite's flaky SIGABRT/SIGSEGV inside
        # jax eager dispatch traced to exactly this path (r7)
        self._fused_step = jax.jit(
            step, static_argnames=("fresh_bag", "sample_active"))

    # ------------------------------------------------------------------
    def _host_qkey(self, class_idx: int):
        """Per-(iteration, class) stochastic-rounding key for the
        HOST-DRIVEN tree paths (RF, custom gradients) — the fused
        chunk derives its own inside _boost_one."""
        if not self._quant_stochastic():
            return None
        import jax as _jax
        seed = int(self._iter_key_rng.randint(0, 2**31 - 1))
        return _jax.random.fold_in(_jax.random.PRNGKey(seed), class_idx)

    def _quant_stochastic(self) -> bool:
        """Whether the int8 quantization rounds stochastically (the v4
        recipe; REQUIRED by skewed-gradient objectives like lambdarank
        — see ops/histogram.py quantize_gradients).  Auto mode defers
        to the objective's need_stochastic_quant."""
        if not self.grower.use_quant:
            return False
        mode = int(getattr(self.config, "quant_stochastic_rounding",
                           -1))
        if mode >= 0:
            return bool(mode)
        return (self.objective is not None
                and getattr(self.objective, "need_stochastic_quant",
                            False))

    def can_chunk(self) -> bool:
        """Whether multi-iteration fused chunks are valid: plain GBDT
        gradients only.  DART/RF mutate state between iterations on the
        host; GOSS flips its sampling activation mid-run, which a
        compiled chunk would freeze at build time."""
        return type(self).__name__ == "GBDT"

    def _boost_one(self, scores, vscores, bag_mask, key, fmask,
                   shrinkage, fresh_bag, vbins, ohb=None):
        """One boosting iteration's device body — shared by the
        per-iteration fused step and the multi-iteration chunk
        (``fresh_bag`` may be a python bool or a traced scalar)."""
        cfg = self.config
        use_bag = self._use_bagging_fused()
        n_pad = self.grower.n_padded
        g, h = self._compute_gradients(scores)
        kb, ks = jax.random.split(key)
        if use_bag:
            u = jax.random.uniform(kb, (n_pad,))
            new_mask = (u < cfg.bagging_fraction) & (self._full_counts > 0)
            bag_mask = jnp.where(fresh_bag, new_mask, bag_mask)
            counts = jnp.where(bag_mask, 1.0, 0.0)
        else:
            counts = self._full_counts
        if self._sample_active():
            g, h, counts = self._sample_rows_fused(g, h, counts, ks)
        g, h = self._mask_gradients(g, h, counts)
        trees = []
        nl = jnp.int32(1)
        new_vscores = list(vscores)
        # stochastic-rounding key for the int8 quantization (folded off
        # the iteration key so the bagging/GOSS streams are untouched)
        kq = (jax.random.fold_in(key, 0x51AB)
              if self._quant_stochastic() else None)
        for k in range(self.num_class):
            tree, leaf_id, row_val = self.grower._train_tree_impl(
                g[k], h[k], counts, fmask[k], ohb,
                qkey=None if kq is None else jax.random.fold_in(kq, k))
            tree = self._finalize_tree(tree, leaf_id, k, scores, counts)
            # a no-split tree must contribute nothing (the reference
            # skips UpdateScore when num_leaves==1, gbdt.cpp:427-460)
            ok = (tree.num_leaves > 1).astype(jnp.float32)
            tree = tree._replace(leaf_value=tree.leaf_value * ok)
            renew = (self.objective is not None
                     and self.objective.is_renew_tree_output)
            if row_val is not None and not renew:
                # fused path: the exit-route already carried each row's
                # leaf value — skip the separate (N, L) broadcast
                delta = row_val * ok * shrinkage
            else:
                delta = leaf_value_broadcast(leaf_id,
                                             tree.leaf_value) * shrinkage
            scores = scores.at[k].add(delta)
            for i, vb in enumerate(vbins):
                pv = self._predict_valid(tree, vb)
                new_vscores[i] = new_vscores[i].at[k].add(pv * shrinkage)
            trees.append(tree)
            nl = jnp.maximum(nl, tree.num_leaves)
        return scores, tuple(new_vscores), bag_mask, tuple(trees), nl

    def _build_fused_chunk(self, n_iters: int):
        """n_iters boosting iterations as ONE jitted lax.scan — on a
        remote-attached TPU every dispatch costs an RPC round trip
        (measured ~40% of wall-clock at one call per iteration), so
        headless stretches of training run chunked.  The reference has
        no analog: its Train loop is host-driven per iteration
        (gbdt.cpp:318-336).

        Packed carry (default): each iteration's K trees leave the
        scan as ONE (K, record_size) uint8 stack (grower.emit_tree_
        record), so the while-loop carry holds two O(chunk) output
        buffers — the packed records and the num_leaves series — and
        the per-iteration chunk penalty the 18-buffer carry paid
        disappears (tests/test_carry_hlo.py pins this in compiled
        HLO)."""
        vbins = tuple(vs.bins for vs in self.valid_sets)
        shrinkage = self.shrinkage_rate
        packed = self._packed_carry

        def chunk(scores, vscores, bag_mask, keys, fmasks, fresh_flags,
                  ohb=None, cap=None):
            TELEMETRY.note_trace("gbdt.fused_chunk",
                                 (keys.shape[0], scores.shape))
            vb = vbins if cap is None else cap["vbins"]

            def one_iter(carry, xs):
                scores, vscores, bag_mask = carry
                key, fmask, fresh_bag = xs
                scores, vscores, bag_mask, trees, nl = self._boost_one(
                    scores, vscores, bag_mask, key, fmask, shrinkage,
                    fresh_bag, vb, ohb)
                if packed:
                    trees = jnp.stack(
                        [self.grower.emit_tree_record(t) for t in trees])
                return (scores, vscores, bag_mask), (trees, nl)

            with self._bound_captives(cap):
                (scores, vscores, bag_mask), (trees, nls) = jax.lax.scan(
                    one_iter, (scores, vscores, bag_mask),
                    (keys, fmasks, fresh_flags))
            return scores, vscores, bag_mask, trees, nls

        # score donation is DISABLED on the fused chunk: donating the
        # scores buffer into the chunk program intermittently corrupts
        # the host heap on this jaxlib's CPU backend (glibc "corrupted
        # double-linked list" / SIGSEGV mid-run, ~50% of 90-iteration
        # runs once more than one chunk shape is compiled — bisected
        # across {packed, legacy} x {donate, no-donate}: every crashing
        # combination donated, every non-donating one was stable over
        # 20+ runs).  The cost is one scores-sized device copy per
        # CHUNK — noise against the chunk body; revisit on a jaxlib
        # upgrade.  The per-iteration _fused_step donation fell to the
        # same bisect: the C-API suite's long-flaky mid-suite SIGABRT/
        # SIGSEGV (many booster shapes jitted per process) stopped
        # reproducing (0/8) once its donation was dropped too.
        return jax.jit(chunk)

    def train_chunk(self, n_iters: int) -> bool:
        """Run n_iters boosting iterations in one device program.
        Returns True when the deferred no-split check stopped training."""
        tm = TELEMETRY
        # host cost is timed from METHOD ENTRY: the per-chunk python
        # prep (key/fmask/flag assembly, pending bookkeeping) is host
        # wall too, and the pre-r9 bench timed the whole call — the
        # counter must cover the same window for series continuity
        t0 = time.perf_counter() if tm.on else 0.0
        cfg = self.config
        chunk_key = (n_iters, len(self.valid_sets), self.shrinkage_rate,
                     self._sample_active())
        if self._fused_chunk_n != chunk_key:
            self._fused_chunk = self._build_fused_chunk(n_iters)
            self._fused_chunk_n = chunk_key
        use_bag = self._use_bagging_fused()
        if self._bag_state is None:
            self._bag_state = self._full_counts > 0
        # the per-iteration seed and feature-mask draws below consume
        # host RNG state BEFORE the dispatch can fail — snapshot the
        # streams so a failed dispatch restores them and a retry or
        # engine-level chunk downshift re-draws the IDENTICAL
        # sequence (the byte-identity guarantee under failure,
        # docs/RELIABILITY.md)
        _rng_snap = (self._iter_key_rng.get_state(),
                     self._feat_rng.get_state())
        seeds = np.asarray([self._iter_key_rng.randint(0, 2**31 - 1)
                            for _ in range(n_iters)], np.uint32)
        if self._np_keys_ok and not use_bag \
                and not self._sample_active() \
                and not self._quant_stochastic():
            # keys unused by the chunk body (no bagging draw, no GOSS
            # sampling, no stochastic quantization rounding): reuse a
            # cached device array and skip the per-chunk host->device
            # transfer entirely
            cache = getattr(self, "_chunk_keys", None)
            if cache is None or cache.shape[0] != n_iters:
                cache = jnp.zeros((n_iters, 2), jnp.uint32)
                self._chunk_keys = cache
            keys = cache
        elif self._np_keys_ok:
            keys = jnp.asarray(np.stack(
                [np.zeros(n_iters, np.uint32), seeds], axis=1))
        else:  # pragma: no cover - unexpected key layout
            keys = jnp.stack([jax.random.PRNGKey(int(s)) for s in seeds])
        if self.config.feature_fraction >= 1.0:
            cache = getattr(self, "_chunk_fmasks", None)
            if cache is None or cache.shape[0] != n_iters:
                cache = jnp.ones(
                    (n_iters, self.num_class, self.grower.num_features),
                    bool)
                self._chunk_fmasks = cache
            fmasks = cache
        else:
            fmasks = jnp.asarray(np.stack(
                [np.stack([self._feature_mask_np()
                           for _ in range(self.num_class)])
                 for _ in range(n_iters)]))
        if use_bag:
            fresh = np.zeros(n_iters, bool)
            for j in range(n_iters):
                fresh[j] = (self.iter_ + j) % cfg.bagging_freq == 0
        else:
            # all-False flags never change: cache the device constant
            cache = getattr(self, "_chunk_fresh", None)
            if cache is None or cache.shape[0] != n_iters:
                cache = jnp.zeros(n_iters, bool)
                self._chunk_fresh = cache
            fresh = cache
        self.timer.start("tree")
        span = tm.start_span("train_chunk", iters=n_iters)

        def _enqueue():
            # fault seam BEFORE the dispatch: an injected failure (or
            # SIGKILL) leaves training state as if the chunk was never
            # dispatched, so a retry — or a checkpoint resume — is
            # exact.  Transient-classified errors (connection/timeout/
            # UNAVAILABLE RPC statuses) retry under the config policy;
            # anything else (OOM included) propagates to the caller's
            # degradation ladder.
            FAULTS.fault_point("gbdt.train_chunk")
            return self._fused_chunk(
                self.scores, tuple(vs.scores for vs in self.valid_sets),
                self._bag_state, keys, fmasks,
                fresh if isinstance(fresh, jax.Array)
                else jnp.asarray(fresh),
                self.grower.ohb, self._build_captives())

        try:
            with tm.span("host_dispatch"):
                scores, vscores, bag, trees, nls = retry_call(
                    self._dispatch_guard(_enqueue, "gbdt.train_chunk"),
                    policy=self._retry_policy(),
                    seam="gbdt.train_chunk")
            if tm.on:
                # the r7 bench split, now first-class counters: time-
                # to-return is the host/dispatch cost (the async
                # enqueue, an RPC on a remote-attached chip); the
                # optional fence attributes the remainder to device
                # execution
                tm.add("host_dispatch_ms",
                       (time.perf_counter() - t0) * 1e3)
                tm.fence_ready(scores)
                tm.add("trees_dispatched", n_iters * self.num_class)
                tm.add("iterations", n_iters)
                tm.add("chunks_dispatched", 1)
                tm.gauge("dispatch_chunk_size", n_iters)
                tm.sample_memory(device=tm.spans_on)
        except BaseException:
            # one guard covers the enqueue AND the telemetry fence
            # (an async device OOM materializes at the fence, still
            # before any state commits): restore the RNG streams so a
            # retry or downshifted re-dispatch draws the IDENTICAL
            # seed/feature-mask sequence
            self._iter_key_rng.set_state(_rng_snap[0])
            self._feat_rng.set_state(_rng_snap[1])
            self.timer.stop("tree")
            tm.end_span(span)
            raise
        tm.end_span(span)
        if tm.on and self.grower.policy.nproc > 1:
            # per-host step wall -> fleet max/min/mean + straggler
            # ratio via a tiny allgather (all hosts run this SPMD
            # loop in lockstep, so the collective is safe here)
            from ..parallel.monitor import record_step_wall
            record_step_wall(time.perf_counter() - t0)
        self.scores = scores
        for vs, s in zip(self.valid_sets, vscores):
            vs.scores = s
        self._bag_state = bag
        bias0 = self.init_score if (self.iter_ == 0 and
                                    self.init_score != 0.0) else 0.0
        # trees stay STACKED on device until flush_models — slicing per
        # tree here would cost hundreds of tiny dispatches, defeating
        # the point of chunking.  Packed carry: ONE (n_iters, K,
        # record_size) uint8 stack; legacy: one TreeArrays stack per
        # class.
        if self._packed_carry:
            self._pending.append(("rstack", trees, n_iters,
                                  self.shrinkage_rate, bias0))
            for j in range(n_iters):
                for k in range(self.num_class):
                    self.device_trees.append(("recref", trees, j, k))
                    self._tree_scale.append(1.0)
                    self._tree_shrink.append(self.shrinkage_rate)
        else:
            stacks = list(trees)                  # one stack per class
            self._pending.append(("stack", stacks, n_iters,
                                  self.shrinkage_rate, bias0))
            for j in range(n_iters):
                for stack in stacks:
                    self.device_trees.append(("stackref", stack, j))
                    self._tree_scale.append(1.0)
                    self._tree_shrink.append(self.shrinkage_rate)
        self._nl_window.append(nls)          # stays stacked on device
        self._nl_count += n_iters
        self.iter_ += n_iters
        self.timer.stop("tree")
        self._transport_epoch_tick()
        if self._nl_count >= self._stop_check_every:
            return self._check_stop_window()
        return False

    def tune_dispatch_chunk(self, probes: Tuple[int, int] = (4, 16),
                            cmin: int = 10, cmax: int = 90):
        """``dispatch_chunk=auto``: re-fit the per-iteration chunk
        slope from two timed probe chunks and pick the amortization
        point.  Each probe size runs TWICE — the first call compiles
        (discarded), the second is timed; probe chunks are real
        training iterations, not throwaway work.  The host dispatch
        cost is the time train_chunk takes to RETURN (the async
        enqueue, which on a remote-attached TPU carries the ~220 ms
        RPC); the slope is fitted on the REMAINDER (return-to-drain,
        the device execution) — folding the dispatch into the fitted
        times would subtract dispatch/(c1·c2) from the slope and bias
        the pick toward cmax exactly where dispatch is large.

        Returns (chunk, info) where info records the fit
        (base_s/slope_s/dispatch_s/per-probe timings), the training
        iterations consumed, and whether the deferred no-split check
        stopped training mid-probe."""
        import time as _time

        times: Dict[int, float] = {}
        disp = []
        iters_used = 0
        stopped = False
        # the probe measures the RAW async enqueue (time-to-return) —
        # a telemetry device fence inside train_chunk would fold the
        # device wall into it and poison the slope fit
        span = TELEMETRY.start_span("tune_dispatch_chunk")
        with TELEMETRY.suspend_fence():
            for c in probes:
                for timed in (False, True):
                    t0 = _time.perf_counter()
                    stop = self.train_chunk(c)
                    t_return = _time.perf_counter() - t0
                    jax.block_until_ready(self.scores)
                    t_total = _time.perf_counter() - t0
                    iters_used += c
                    if timed:
                        times[c] = (t_total - t_return) / c
                        disp.append(t_return)
                    if stop:
                        stopped = True
                        break
                if stopped:
                    break
        TELEMETRY.end_span(span)
        if stopped or len(times) < 2:
            return cmin, {"iters_used": iters_used, "stopped": stopped,
                          "probe_per_tree_s": times}
        base_s, slope_s = fit_chunk_slope(times)
        dispatch_s = float(np.median(disp))
        chunk = pick_dispatch_chunk(base_s, slope_s, dispatch_s,
                                    cmin=cmin, cmax=cmax)
        info = {"iters_used": iters_used, "stopped": False,
                "probe_per_tree_s": times, "base_s": base_s,
                "slope_s_per_iter": slope_s, "dispatch_s": dispatch_s,
                "chunk": chunk}
        Log.debug(f"dispatch_chunk=auto fit: base {base_s * 1e3:.2f} ms "
                  f"+ {slope_s * 1e3:.4f} ms/iter·chunk, dispatch "
                  f"{dispatch_s * 1e3:.1f} ms -> chunk {chunk}")
        return chunk, info

    def train_one_iter(self, grad: Optional[np.ndarray] = None,
                       hess: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (reference gbdt.cpp:386-481).
        Custom grad/hess (shape (N,) or (N, K)) bypass the objective —
        the LGBM_BoosterUpdateOneIterCustom path."""
        if grad is not None and hess is not None:
            return self._train_one_iter_custom(grad, hess)
        if self.objective is None:
            Log.fatal("No objective and no custom gradients")
        tm = TELEMETRY
        t0 = time.perf_counter() if tm.on else 0.0  # host wall from
        # method entry (same window discipline as train_chunk)
        self._before_boosting()
        self.timer.start("tree")
        if self._fused_step is None:
            self._build_fused()
        cfg = self.config
        use_bag = self._use_bagging_fused()
        fresh_bag = bool(use_bag and (self._bag_state is None or
                                      self.iter_ % cfg.bagging_freq == 0))
        if self._bag_state is None:
            self._bag_state = self._full_counts > 0
        # RNG snapshot: the key/feature-mask draws precede the
        # dispatch; a failed dispatch restores the streams so a retry
        # trains the identical iteration (the masks are drawn ONCE,
        # outside the retried closure, for the same reason)
        _rng_snap = (self._iter_key_rng.get_state(),
                     self._feat_rng.get_state())
        key = jax.random.PRNGKey(
            int(self._iter_key_rng.randint(0, 2**31 - 1)))
        fmasks = self._feature_masks()
        span = tm.start_span("boost_iter", iteration=self.iter_)

        def _enqueue():
            FAULTS.fault_point("gbdt.train_one_iter")
            return self._fused_step(
                self.scores, tuple(vs.scores for vs in self.valid_sets),
                self._bag_state, key, fmasks,
                jnp.asarray(self.shrinkage_rate, jnp.float32),
                self.grower.ohb, self._build_captives(),
                fresh_bag=fresh_bag, sample_active=self._sample_active())

        try:
            with tm.span("host_dispatch"):
                scores, vscores, bag, trees, nl = retry_call(
                    self._dispatch_guard(_enqueue,
                                         "gbdt.train_one_iter"),
                    policy=self._retry_policy(),
                    seam="gbdt.train_one_iter")
            if tm.on:
                tm.add("host_dispatch_ms",
                       (time.perf_counter() - t0) * 1e3)
                tm.fence_ready(scores)
                tm.add("trees_dispatched", self.num_class)
                tm.add("iterations", 1)
        except BaseException:
            # covers the enqueue and the fence (async OOM surfaces at
            # the fence): restore RNG streams for an exact retry
            self._iter_key_rng.set_state(_rng_snap[0])
            self._feat_rng.set_state(_rng_snap[1])
            self.timer.stop("tree")
            tm.end_span(span)
            raise
        tm.end_span(span)
        if tm.on and self.grower.policy.nproc > 1:
            from ..parallel.monitor import record_step_wall
            record_step_wall(time.perf_counter() - t0)
        self.scores = scores
        for vs, s in zip(self.valid_sets, vscores):
            vs.scores = s
        self._bag_state = bag
        bias = self.init_score if (self.iter_ == 0 and
                                   self.init_score != 0.0) else 0.0
        for tree in trees:
            self.device_trees.append(tree)
            self._pending.append(("tree", tree, self.shrinkage_rate, bias))
            self._tree_scale.append(1.0)
            self._tree_shrink.append(self.shrinkage_rate)
        self._nl_window.append(nl)
        self._nl_count += 1
        self._after_iteration()
        self.iter_ += 1
        self.timer.stop("tree")
        self._transport_epoch_tick()
        if self._nl_count >= self._stop_check_every:
            return self._check_stop_window()
        return False

    # ------------------------------------------------------------------
    def _transport_epoch_tick(self) -> None:
        """Elastic-membership epoch boundary (the WorldLedger protocol,
        parallel/transport.py): with a TCP transport active, every
        ``transport_epoch_iters`` completed iterations all participants
        tick the coordinator — dead peers retire (degraded continuation
        per ``sharded_allow_degraded``), and waiting joiners are
        admitted with this model's captured state as handoff (the r12
        byte-identical-resume snapshot: a joiner restoring it trains
        the exact iterations the world trains next).  Strictly BETWEEN
        iterations, so a collective can never race a membership
        change; with an unchanged world the tick is one tiny control
        round."""
        from ..parallel import transport as _transport
        tp = _transport.active()
        if tp is None or tp.world_size < 1:
            return
        if self.iter_ % max(1, tp.epoch_every) != 0:
            return

        def _handoff() -> bytes:
            import pickle as _pickle
            state, _stopped = self.capture_state()
            return _pickle.dumps(state, protocol=4)

        info = tp.epoch_tick(
            handoff=_handoff,
            allow_degraded=bool(getattr(self.config,
                                        "sharded_allow_degraded",
                                        False)))
        if info.get("changed"):
            Log.warning(
                f"transport epoch {info['epoch']}: world is now "
                f"{info['world_size']} (dead={info['dead']}, "
                f"admitted={info['admitted']}) — training continues "
                "on the reformed membership")

    # ------------------------------------------------------------------
    def _train_one_iter_custom(self, grad, hess) -> bool:
        """Custom-gradient iteration (gradients cross the host boundary
        every call, like the reference's UpdateOneIterCustom)."""
        if self._mh:
            Log.fatal("multi-host training does not support custom "
                      "gradient functions yet (host gradients cannot "
                      "follow the sharded row layout)")
        self._before_boosting()
        self.timer.start("boosting")
        grad = np.asarray(grad, dtype=np.float32).reshape(
            self.num_class, self.num_data)
        hess = np.asarray(hess, dtype=np.float32).reshape(
            self.num_class, self.num_data)
        pad = self.grower.n_padded - self.num_data
        g = jnp.asarray(np.pad(grad, ((0, 0), (0, pad))))
        h = jnp.asarray(np.pad(hess, ((0, 0), (0, pad))))
        self.timer.stop("boosting")
        self.timer.start("bagging")
        counts, bag_mask = self._bagging_counts(self.iter_)
        g, h, counts = self._sample_rows(g, h, counts)
        g, h = self._mask_gradients(g, h, counts)
        self.timer.stop("bagging")

        self.timer.start("tree")
        bias = self.init_score if (self.iter_ == 0 and
                                   self.init_score != 0.0) else 0.0
        nl = jnp.int32(1)
        for k in range(self.num_class):
            feature_mask = self._feature_mask()
            tree_arrays, leaf_id, _ = self.grower.train_tree(
                g[k], h[k], counts, feature_mask,
                qkey=self._host_qkey(k))
            tree_arrays = self._finalize_tree(tree_arrays, leaf_id, k,
                                              self.scores, counts)
            ok = (tree_arrays.num_leaves > 1).astype(jnp.float32)
            tree_arrays = tree_arrays._replace(
                leaf_value=tree_arrays.leaf_value * ok)
            self.device_trees.append(tree_arrays)
            self.scores = self._update_train_fn(
                self.scores, leaf_id, tree_arrays.leaf_value, k,
                self.shrinkage_rate)
            for vs in self.valid_sets:
                delta = self._predict_valid_fn(tree_arrays, vs.bins)
                vs.scores = vs.scores.at[k].add(
                    delta * self.shrinkage_rate)
            self._pending.append(("tree", tree_arrays,
                                  self.shrinkage_rate, bias))
            self._tree_scale.append(1.0)
            self._tree_shrink.append(self.shrinkage_rate)
            nl = jnp.maximum(nl, tree_arrays.num_leaves)
        self.timer.stop("tree")
        if TELEMETRY.on:
            TELEMETRY.add("trees_dispatched", self.num_class)
            TELEMETRY.add("iterations", 1)
        self._nl_window.append(nl)
        self._after_iteration()
        self.iter_ += 1
        self._transport_epoch_tick()
        if len(self._nl_window) >= self._stop_check_every:
            return self._check_stop_window()
        return False

    # ------------------------------------------------------------------
    def _check_stop_window(self) -> bool:
        """Deferred no-split detection: pull the queued per-iteration
        max-num_leaves scalars in ONE transfer; if some iteration grew
        no tree, roll back everything after it and stop (the reference
        checks every iteration — here 1-leaf trees contribute exactly
        zero score, so late rollback is exact)."""
        if not self._nl_window:
            return False
        vals = np.asarray(jnp.concatenate(
            [jnp.atleast_1d(x) for x in self._nl_window]))
        self._nl_window = []
        self._nl_count = 0
        for j, v in enumerate(vals):
            if int(v) <= 1:
                overrun = len(vals) - j
                for _ in range(overrun):
                    self.rollback_one_iter()
                Log.warning("Stopped training because there are no more "
                            "leaves that meet the split requirements.")
                return True
        return False

    # ------------------------------------------------------------------
    def flush_models(self, final: bool = False) -> None:
        """Materialize queued device trees into host ``self.models`` in
        one batched device->host transfer, and reconcile DART weight
        rescales on already-materialized trees.  Only a ``final`` flush
        consumes the deferred no-split window (popping degenerate tail
        trees) — mid-training flushes must leave the window for
        train_one_iter's own stop detection."""
        if final and self._nl_window:
            self._check_stop_window()
        for i, t in enumerate(self.models):
            if self._applied_scale[i] != self._tree_scale[i]:
                r = self._tree_scale[i] / self._applied_scale[i]
                t.leaf_value *= r
                t.internal_value *= r
                t.shrinkage *= r
                self._applied_scale[i] = self._tree_scale[i]
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        span = TELEMETRY.start_span("model_flush", entries=len(pending))
        # ONE device->host transfer for everything queued: per-tree
        # entries are stacked, chunk entries already are stacks (packed
        # record stacks travel as their single uint8 buffer)
        plain = [p[1] for p in pending if p[0] == "tree"]
        stacked_plain = (jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *plain) if plain else None)
        chunk_stacks = [p[1] for p in pending if p[0] == "stack"]
        rec_stacks = [p[1] for p in pending if p[0] == "rstack"]
        host_plain, host_chunks, host_recs = jax.device_get(
            (stacked_plain, chunk_stacks, rec_stacks))

        def append_tree(arrs, shrinkage, bias):
            t = Tree.from_grower_arrays(arrs, self.train_set)
            t.apply_shrinkage(shrinkage)
            if bias != 0.0:
                # fold the init score into the first tree so saved models
                # and raw predictions carry it (reference gbdt.cpp:452-454)
                t.leaf_value += bias
                t.internal_value += bias
            idx = len(self.models)
            scale = self._tree_scale[idx]
            if scale != 1.0:
                t.leaf_value *= scale
                t.internal_value *= scale
                t.shrinkage *= scale
            self.models.append(t)
            self._applied_scale.append(scale)

        i_plain = 0
        i_chunk = 0
        i_rec = 0
        n_before = len(self.models)
        layout = self.grower.record_layout
        for p in pending:
            if p[0] == "tree":
                _, _tree, shrinkage, bias = p
                arrs = {f: np.asarray(getattr(host_plain, f)[i_plain])
                        for f in host_plain._fields}
                append_tree(arrs, shrinkage, bias)
                i_plain += 1
            elif p[0] == "rstack":
                _, _recs, n_iters, shrinkage, bias0 = p
                recs = host_recs[i_rec]       # (chunk, K, record_size)
                i_rec += 1
                for j in range(n_iters):
                    for k in range(recs.shape[1]):
                        arrs = layout.unpack_tree_record(recs[j, k])
                        append_tree(arrs, shrinkage,
                                    bias0 if j == 0 else 0.0)
            else:
                _, _stacks, n_iters, shrinkage, bias0 = p
                stacks = host_chunks[i_chunk]
                i_chunk += 1
                for j in range(n_iters):
                    for stack in stacks:
                        arrs = {f: np.asarray(getattr(stack, f)[j])
                                for f in stack._fields}
                        append_tree(arrs, shrinkage,
                                    bias0 if j == 0 else 0.0)
        TELEMETRY.add("trees_flushed", len(self.models) - n_before)
        TELEMETRY.end_span(span)

    # ------------------------------------------------------------------
    # crash-safe checkpointing (docs/RELIABILITY.md) ------------------
    def _retry_policy(self) -> RetryPolicy:
        p = getattr(self, "_retry_policy_cache", None)
        if p is None:
            p = RetryPolicy.from_config(self.config)
            self._retry_policy_cache = p
        return p

    def _dispatch_guard(self, fn, seam: str):
        """Deadline-bound a dispatch enqueue under
        ``watchdog_dispatch_s`` (docs/RELIABILITY.md, deadline
        watchdog): an enqueue that has not returned within the
        deadline — a wedged backend RPC, a ``hang`` fault — dumps
        all-thread stacks and raises a classified ``StallError``,
        which the surrounding ``retry_call`` treats as transient
        (the enqueue precedes any state mutation, so re-entering is
        exact).  Disarmed (the default 0) this returns ``fn``
        untouched — zero overhead, identical programs."""
        wd = float(getattr(self.config, "watchdog_dispatch_s", 0.0)
                   or 0.0)
        if wd <= 0:
            return fn
        from ..reliability.watchdog import run_with_deadline

        def _bounded():
            return run_with_deadline(fn, wd, phase="dispatch",
                                     seam=seam)
        return _bounded

    def can_checkpoint(self) -> bool:
        """Whether full-state checkpointing covers this booster: plain
        GBDT and GOSS (their entire RNG state lives in the captured
        streams).  DART re-scales finished trees from host-side drop
        state and RF mutates averaged leaf outputs between iterations
        — neither round-trips through capture_state yet."""
        return type(self).__name__ in ("GBDT", "GOSS") and not self._mh

    def capture_state(self) -> Tuple[dict, bool]:
        """Snapshot FULL training state for a crash-safe checkpoint:
        host models, score caches, bagging/key RNG streams, and
        early-stopping bookkeeping — everything a resumed run needs to
        produce byte-identical trees to an uninterrupted one.  The
        deferred no-split window is consumed first (it is the one
        piece of state that references device-resident tree stacks);
        returns (state, stopped) where stopped means the window
        detected end-of-training."""
        stopped = self._check_stop_window() if self._nl_window else False
        self.flush_models()
        state = {
            "iter_": self.iter_,
            "models": list(self.models),
            "tree_scale": list(self._tree_scale),
            "applied_scale": list(self._applied_scale),
            "tree_shrink": list(self._tree_shrink),
            # informational only: restore_state deliberately sets
            # scale_offset to len(models) instead (restored trees are
            # host-only and route like init_model foreign trees)
            "scale_offset": self._scale_offset,
            "shrinkage_rate": self.shrinkage_rate,
            "init_score": self.init_score,
            "scores": np.asarray(self.scores),
            "valid_scores": [np.asarray(vs.scores)
                             for vs in self.valid_sets],
            "bag_state": (None if self._bag_state is None
                          else np.asarray(self._bag_state)),
            "bag_mask": (None if self._bag_mask is None
                         else np.asarray(self._bag_mask)),
            "bag_rng": np.asarray(self._bag_rng),
            "iter_key_rng": self._iter_key_rng.get_state(),
            "feat_rng": self._feat_rng.get_state(),
            "py_rng": self._rng.get_state(),
            "best_score": dict(self._best_score),
            "best_iter": dict(self._best_iter),
            "best_iteration": self.best_iteration,
            "num_class": self.num_class,
            "num_data": self.num_data,
            "n_padded": self.grower.n_padded,
            "num_valid": len(self.valid_sets),
        }
        if hasattr(self, "_goss_key"):          # GOSS host-path stream
            state["goss_key"] = np.asarray(self._goss_key)
        return state, stopped

    def restore_state(self, state: dict) -> None:
        """Adopt a capture_state snapshot: the inverse restore, run on
        a freshly-constructed GBDT over the SAME dataset (the caller
        verified the checkpoint fingerprint).  Raises CheckpointError
        on any shape/identity mismatch rather than training garbage."""
        if state.get("num_class") != self.num_class or \
                state.get("num_data") != self.num_data or \
                state.get("n_padded") != self.grower.n_padded or \
                state.get("num_valid") != len(self.valid_sets):
            raise CheckpointError(
                "checkpoint state does not match this training setup "
                f"(saved num_data={state.get('num_data')}/"
                f"num_class={state.get('num_class')}/padded="
                f"{state.get('n_padded')}/valid={state.get('num_valid')}"
                f" vs {self.num_data}/{self.num_class}/"
                f"{self.grower.n_padded}/{len(self.valid_sets)})")
        import jax.numpy as jnp
        self.iter_ = int(state["iter_"])
        # in-place: Booster.models aliases this list
        self.models[:] = state["models"]
        self._tree_scale[:] = state["tree_scale"]
        self._applied_scale[:] = state["applied_scale"]
        self._tree_shrink[:] = state["tree_shrink"]
        # restored trees live only on host — register them like
        # init_model foreign trees so the in-session binned device
        # predict (which only knows post-resume device stacks) stands
        # down in favor of the host/stacked path
        self._scale_offset = len(self.models)
        self.shrinkage_rate = float(state["shrinkage_rate"])
        self.init_score = float(state["init_score"])
        self.scores = self.grower.policy.place_score_rows(
            np.asarray(state["scores"], np.float32))
        for vs, arr in zip(self.valid_sets, state["valid_scores"]):
            vs.scores = jnp.asarray(np.asarray(arr, np.float32))
        self._bag_state = (None if state["bag_state"] is None
                           else jnp.asarray(state["bag_state"]))
        mask = state.get("bag_mask")
        self._bag_mask = None if mask is None else jnp.asarray(mask)
        self._bag_rng = jnp.asarray(
            np.asarray(state["bag_rng"], np.uint32))
        self._iter_key_rng.set_state(state["iter_key_rng"])
        self._feat_rng.set_state(state["feat_rng"])
        self._rng.set_state(state["py_rng"])
        self._best_score = dict(state["best_score"])
        self._best_iter = dict(state["best_iter"])
        self.best_iteration = int(state["best_iteration"])
        if "goss_key" in state and hasattr(self, "_goss_key"):
            self._goss_key = jnp.asarray(
                np.asarray(state["goss_key"], np.uint32))
        self.device_trees = []
        self._pending = []
        self._nl_window = []
        self._nl_count = 0

    # ------------------------------------------------------------------
    def _mask_gradients(self, g, h, counts):
        """Apply bagging mask and row weights to gradient channels.
        Row weights are already inside the objective's gradients
        (reference semantics); only the bag mask zeroes rows here."""
        mask = counts > 0
        return g * mask[None, :], h * mask[None, :]

    # ------------------------------------------------------------------
    def _finalize_tree(self, tree_arrays: TreeArrays, leaf_id, class_idx,
                       scores, counts) -> TreeArrays:
        """Objective-specific leaf refitting hook (RenewTreeOutput,
        reference serial_tree_learner.cpp:776-806).  Pure/jittable:
        ``scores`` are the pre-update scores, ``counts`` the bag mask."""
        if self.objective is not None and \
                self.objective.is_renew_tree_output:
            tree_arrays = self._renew_tree_output(tree_arrays, leaf_id,
                                                  class_idx, scores, counts)
        return tree_arrays

    def _renew_tree_output(self, tree_arrays, leaf_id, class_idx,
                           scores, counts):
        """Re-fit leaf outputs to the objective's percentile (L1-family
        objectives; reference regression_objective.hpp RenewTreeOutput).
        Device: lexicographic sort by (leaf, residual) then per-leaf
        percentile interpolation."""
        from ..ops.percentile import leaf_percentiles
        n = self.num_data
        obj = self.objective
        pred = scores[class_idx, :n]
        label = obj._label_dev
        residual = label - pred
        alpha = obj.renew_alpha
        if hasattr(obj, "_label_weight_dev"):
            w = obj._label_weight_dev          # mape weighting
        elif obj.weight is not None:
            w = obj._weight_dev
        else:
            w = None
        # restrict to in-bag rows (reference passes bag_data_indices,
        # gbdt.cpp:446-447): out-of-bag rows get leaf -1 and are ignored
        lid = jnp.where(counts[:n] > 0, leaf_id[:n], -1)
        L = self.config.num_leaves
        new_values = leaf_percentiles(residual, lid, L, alpha, w)
        ok = tree_arrays.leaf_count > 0
        return tree_arrays._replace(
            leaf_value=jnp.where(ok, new_values,
                                 tree_arrays.leaf_value))

    # ------------------------------------------------------------------
    def eval_metrics(self, which: str = "all"
                     ) -> List[Tuple[str, str, float, bool]]:
        """Returns (dataset_name, metric_name, value, bigger_better).
        ``which``: 'all', 'train' or 'valid' — scoped so eval_train /
        eval_valid don't pay for metrics they discard."""
        self.timer.start("metric")
        try:
            with TELEMETRY.span("eval_metrics"):
                return self._eval_metrics_impl(which)
        finally:
            self.timer.stop("metric")

    def _eval_metrics_impl(self, which="all"):
        out = []
        if self.train_metrics and which in ("all", "train"):
            s = self._scores_for_eval(self.scores[:, :self.num_data])
            for m in self.train_metrics:
                for name, v in zip(m.names(), m.eval(s, self.objective)):
                    out.append(("training", name, v, m.bigger_is_better))
        if which in ("all", "valid"):
            for vs, vname in zip(self.valid_sets, self.valid_names):
                s = self._scores_for_eval(vs.scores)
                for m in vs.metrics:
                    for name, v in zip(m.names(),
                                       m.eval(s, self.objective)):
                        out.append((vname, name, v,
                                    m.bigger_is_better))
        return out

    def _scores_for_eval(self, scores):
        if self.num_class == 1:
            return scores[0]
        return scores.T       # (N, K)

    # ------------------------------------------------------------------
    def check_early_stopping(self, results, iteration: int) -> bool:
        """Reference gbdt.cpp:582-639: stop as soon as ANY validation
        metric has not improved for early_stopping_round iterations;
        best_iteration comes from the triggering metric."""
        rounds = self.config.early_stopping_round
        if rounds <= 0:
            return False
        for i, (dname, mname, value, bigger) in enumerate(results):
            if dname == "training":
                continue
            key = (i, 0)
            score = value if bigger else -value
            if key not in self._best_score or score > self._best_score[key]:
                self._best_score[key] = score
                self._best_iter[key] = iteration
            elif iteration - self._best_iter[key] >= rounds:
                self.best_iteration = self._best_iter[key] + 1
                return True
        return False

    # ------------------------------------------------------------------
    def _materialize_devtree(self, entry):
        """device_trees entry -> TreeArrays (chunk entries are lazy
        slices of a stacked chunk; packed-carry entries unpack their
        byte record on device)."""
        if isinstance(entry, tuple) and entry and entry[0] == "stackref":
            _, stack, j = entry
            return jax.tree_util.tree_map(lambda x: x[j], stack)
        if isinstance(entry, tuple) and entry and entry[0] == "recref":
            from ..ops.predict import unpack_tree_records_device
            _, recs, j, k = entry
            return unpack_tree_records_device(
                recs[j, k], self.config.num_leaves,
                self.grower.max_feature_bin)
        return entry

    def rollback_one_iter(self) -> None:
        """reference gbdt.cpp:483-499."""
        if self.num_trees < self.num_class:
            return
        # pending bookkeeping: one iteration = num_class trees
        shrinkage = self.shrinkage_rate
        if self._pending:
            last = self._pending[-1]
            if last[0] in ("stack", "rstack"):
                kind, stacks, n, shrinkage, bias0 = last
                if n <= 1:
                    self._pending.pop()
                else:
                    self._pending[-1] = (kind, stacks, n - 1,
                                         shrinkage, bias0)
            else:
                for _ in range(self.num_class):
                    _, _t, shrinkage, _b = self._pending.pop()
        else:
            for _ in range(self.num_class):
                self.models.pop()
                self._applied_scale.pop()
        for k in reversed(range(self.num_class)):
            tree_arrays = self._materialize_devtree(self.device_trees.pop())
            self._tree_scale.pop()
            if self._tree_shrink:
                self._tree_shrink.pop()
            self.scores = self.scores.at[k].add(
                -shrinkage * self._predict_valid_fn(
                    tree_arrays, self.grower.bins))
            for vs in self.valid_sets:
                vs.scores = vs.scores.at[k].add(
                    -shrinkage * self._predict_valid_fn(
                        tree_arrays, vs.bins))
        self.iter_ -= 1

    # ------------------------------------------------------------------
    @property
    def num_trees(self) -> int:
        n = len(self.models)
        for p in self._pending:
            if p[0] == "stack":
                n += p[2] * len(p[1])
            elif p[0] == "rstack":
                n += p[2] * p[1].shape[1]
            else:
                n += 1
        return n
