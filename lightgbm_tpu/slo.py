"""SLO burn-rate engine over the in-process metric registry.

The observability stack so far produces SIGNALS — counters, latency
histograms, gauges, spans, a fleet event journal — but judging them
("is serving healthy?") happens off-box, in whatever dashboards the
operator wired to the Prometheus textfiles.  This module closes that
loop in-process: declarative SLO rules (a JSON file named by
``Config.slo_rules``) are evaluated on a timer against the live
:data:`~lightgbm_tpu.telemetry.TELEMETRY` registry using the
multi-window burn-rate method (an SRE-workbook-style fast window that
catches sharp regressions plus a slow window that catches smoulders),
and a breach becomes a first-class event: ``ltpu_slo_*`` gauges on the
scrape surface, an ``slo_breach`` entry in the fleet event journal
(with the active trace context), a flight-recorder dump, and a warn
log.  ``GET /slo`` on the shared telemetry listener answers the
current verdict as JSON, and ``python -m lightgbm_tpu.slo check
--url`` turns that into a CI/cron-able exit code.

Rule grammar (``{"rules": [...], "fast_window_s": 60,
"slow_window_s": 600}``; windows optional) — four rule kinds, each
producing ``burn = observed / bound`` per window (>= 1 is a breach):

- ``quantile``: a latency bound over a histogram —
  ``{"name": "p99", "kind": "quantile", "hist": "predict_latency_ms",
  "q": 0.99, "max_ms": 250}``.  The windowed histogram is the bucket
  DELTA between now and the window-start snapshot, so an old latency
  spike ages out of the verdict.
- ``ratio``: an error/shed budget over two counters —
  ``{"kind": "ratio", "num": "serve_shed_requests",
  "den": "serve_requests", "max": 0.01}`` (windowed deltas; a den
  delta of 0 reads as burn 0 — no traffic, no verdict).
- ``rate``: an events-per-second ceiling on one counter —
  ``{"kind": "rate", "counter": "retry_exhausted_total",
  "max_per_s": 0.1}``.
- ``gauge``: an instantaneous bound on a gauge —
  ``{"kind": "gauge", "gauge": "straggler_ratio", "max": 2.0}``
  (no windowing; gauges are already point-in-time).  Quality PSI
  ceilings ride this kind (``quality_psi_max``).

Off-mode cost: :meth:`SloEngine.evaluate` and the timer body return
after ONE mode check when ``telemetry=off``, and nothing here touches
the dispatch path at all — the ``telemetry=off`` HLO-identity pin is
unaffected by definition (host-side only).
"""
from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any, Dict, List, Optional

from .telemetry import TELEMETRY, _COUNTERS, hist_quantile
from .utils.log import Log

RULE_KINDS = ("quantile", "ratio", "rate", "gauge")
DEFAULT_FAST_WINDOW_S = 60.0
DEFAULT_SLOW_WINDOW_S = 600.0
MAX_SNAPSHOTS = 512     # bound on the windowed-baseline ring


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise ValueError(f"slo_rules: {msg}")


def parse_rules(text: str) -> Dict[str, Any]:
    """Parse + validate an SLO rules document (raises ``ValueError``
    on any malformed rule — ``Config.check`` calls this eagerly so a
    typo'd rules file fails the run instead of silently never
    alerting, the ``fault_plan`` contract)."""
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"slo_rules: not valid JSON ({e})") from None
    _require(isinstance(doc, dict), "top level must be an object")
    rules = doc.get("rules")
    _require(isinstance(rules, list) and rules,
             'needs a non-empty "rules" array')
    fast = float(doc.get("fast_window_s", DEFAULT_FAST_WINDOW_S))
    slow = float(doc.get("slow_window_s", DEFAULT_SLOW_WINDOW_S))
    _require(0 < fast <= slow,
             "windows need 0 < fast_window_s <= slow_window_s")
    seen = set()
    out: List[Dict[str, Any]] = []
    for i, r in enumerate(rules):
        _require(isinstance(r, dict), f"rule {i} must be an object")
        kind = r.get("kind")
        _require(kind in RULE_KINDS,
                 f"rule {i}: kind must be one of {RULE_KINDS}, "
                 f"got {kind!r}")
        name = str(r.get("name") or f"rule{i}")
        _require(name not in seen, f"duplicate rule name {name!r}")
        seen.add(name)
        rule = {"name": name, "kind": kind}
        if kind == "quantile":
            _require(bool(r.get("hist")),
                     f"rule {name!r}: quantile needs a 'hist' name")
            q = float(r.get("q", 0.99))
            _require(0 < q < 1, f"rule {name!r}: q must be in (0, 1)")
            bound = float(r.get("max_ms", r.get("max", 0)))
            _require(bound > 0,
                     f"rule {name!r}: quantile needs max_ms > 0")
            rule.update(hist=str(r["hist"]), q=q, bound=bound)
        elif kind == "ratio":
            _require(bool(r.get("num")) and bool(r.get("den")),
                     f"rule {name!r}: ratio needs 'num' and 'den' "
                     "counter names")
            bound = float(r.get("max", 0))
            _require(bound > 0, f"rule {name!r}: ratio needs max > 0")
            rule.update(num=str(r["num"]), den=str(r["den"]),
                        bound=bound)
        elif kind == "rate":
            _require(bool(r.get("counter")),
                     f"rule {name!r}: rate needs a 'counter' name")
            bound = float(r.get("max_per_s", 0))
            _require(bound > 0,
                     f"rule {name!r}: rate needs max_per_s > 0")
            rule.update(counter=str(r["counter"]), bound=bound)
        else:  # gauge
            _require(bool(r.get("gauge")),
                     f"rule {name!r}: gauge needs a 'gauge' name")
            bound = float(r.get("max", 0))
            _require(bound > 0, f"rule {name!r}: gauge needs max > 0")
            rule.update(gauge=str(r["gauge"]), bound=bound)
        out.append(rule)
    return {"rules": out, "fast_window_s": fast, "slow_window_s": slow}


def load_rules(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return parse_rules(f.read())


class SloEngine:
    """Timer-evaluated burn-rate engine over one rules document.

    Keeps a bounded ring of ``(ts, counters, hist-counts)`` snapshots
    so each evaluation can form WINDOWED deltas: the baseline for a
    window is the newest snapshot at least ``window_s`` old (bootstrap:
    before any snapshot has aged past the window, the oldest snapshot
    — or process start, i.e. the cumulative totals — serves as the
    baseline, so a fresh process still alerts on its first bad
    minute).  All reads go through the public ``Telemetry`` snapshot
    accessors; nothing here holds the telemetry lock across rule
    evaluation."""

    def __init__(self, rules: Dict[str, Any],
                 interval_s: float = 10.0):
        self.rules = rules["rules"]
        self.fast_s = float(rules["fast_window_s"])
        self.slow_s = float(rules["slow_window_s"])
        self.interval_s = max(0.5, float(interval_s))
        self._snaps = collections.deque(maxlen=MAX_SNAPSHOTS)
        self._lock = threading.Lock()
        self._breached: Dict[str, bool] = {}
        self._timer: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.evaluations = 0    # timer/test observability

    # -- windowed reads -------------------------------------------------
    def _baseline(self, now: float, window_s: float):
        """Newest snapshot older than ``window_s`` (None = process
        start, i.e. cumulative-total deltas during bootstrap)."""
        base = None
        for snap in self._snaps:     # oldest -> newest
            if now - snap[0] >= window_s:
                base = snap
            else:
                break
        if base is None and self._snaps:
            base = self._snaps[0]
            if now - base[0] < 1e-9:
                return None
        return base

    @staticmethod
    def _counter_delta(name, counters, base):
        cur = float(counters.get(name, 0.0))
        if base is None:
            return cur
        return cur - float(base[1].get(name, 0.0))

    @staticmethod
    def _hist_delta(name, hists, base):
        h = hists.get(name)
        if h is None:
            return None
        if base is None or name not in base[2]:
            return h
        prev = base[2][name]
        if list(prev["bounds"]) != list(h["bounds"]):
            return h    # bounds changed (reset): cumulative view
        counts = [c - p for c, p in zip(h["counts"], prev["counts"])]
        return {"bounds": h["bounds"], "counts": counts,
                "count": max(0, h["count"] - prev["count"]),
                "sum": h["sum"] - prev["sum"]}

    def _rule_burn(self, rule, counters, hists, gauges, base,
                   span_s: float) -> float:
        kind = rule["kind"]
        if kind == "gauge":
            v = gauges.get(rule["gauge"])
            return 0.0 if v is None else float(v) / rule["bound"]
        if kind == "quantile":
            h = self._hist_delta(rule["hist"], hists, base)
            if h is None or h["count"] <= 0:
                return 0.0
            return hist_quantile(h, rule["q"]) / rule["bound"]
        if kind == "ratio":
            den = self._counter_delta(rule["den"], counters, base)
            if den <= 0:
                return 0.0
            num = self._counter_delta(rule["num"], counters, base)
            return (num / den) / rule["bound"]
        # rate
        d = self._counter_delta(rule["counter"], counters, base)
        return (d / max(span_s, 1e-9)) / rule["bound"]

    # -- evaluation -----------------------------------------------------
    def evaluate(self) -> Dict[str, Any]:
        """One evaluation pass: compute fast/slow burn per rule,
        publish ``slo_burn*`` gauges, journal breach TRANSITIONS
        (warn-once until the rule recovers), and return the verdict
        document (what ``GET /slo`` serves)."""
        tm = TELEMETRY
        if tm.mode < _COUNTERS:    # off-mode: one attribute check
            return {"enabled": False, "breaching": [], "rules": []}
        now = time.perf_counter()
        counters = tm.counters()
        hists = tm.histograms()
        gauges = tm.gauges()
        with self._lock:
            self.evaluations += 1
            fast_base = self._baseline(now, self.fast_s)
            slow_base = self._baseline(now, self.slow_s)
            t0 = getattr(tm, "_t0", now)
            fast_span = (now - fast_base[0]) if fast_base \
                else max(now - t0, 1e-9)
            slow_span = (now - slow_base[0]) if slow_base \
                else max(now - t0, 1e-9)
            results = []
            breaching = []
            worst = 0.0
            for rule in self.rules:
                fast = self._rule_burn(rule, counters, hists, gauges,
                                       fast_base, fast_span)
                slow = self._rule_burn(rule, counters, hists, gauges,
                                       slow_base, slow_span)
                burn = max(fast, slow)
                worst = max(worst, burn)
                breach = burn >= 1.0
                name = rule["name"]
                tm.gauge(f"slo_burn.{name}", round(fast, 6))
                tm.gauge(f"slo_slow_burn.{name}", round(slow, 6))
                was = self._breached.get(name, False)
                self._breached[name] = breach
                if breach:
                    breaching.append(name)
                if breach and not was:
                    tm.journal.emit(
                        "slo_breach", seam="serving.request",
                        rule=name, rule_kind=rule["kind"],
                        burn=round(burn, 4), bound=rule["bound"])
                    tm.flight.dump(
                        "slo_breach", seam="serving.request",
                        rule=name, rule_kind=rule["kind"],
                        burn=round(burn, 4), bound=rule["bound"])
                    Log.warning(
                        f"SLO BREACH: rule {name!r} ({rule['kind']}) "
                        f"burning at {burn:.2f}x its budget "
                        f"(fast {fast:.2f}x / slow {slow:.2f}x)")
                elif was and not breach:
                    tm.journal.emit(
                        "slo_recover", seam="serving.request",
                        rule=name, burn=round(burn, 4))
                    Log.info(f"SLO recovered: rule {name!r} at "
                             f"{burn:.2f}x budget")
                results.append({
                    "rule": name, "kind": rule["kind"],
                    "bound": rule["bound"],
                    "fast_burn": round(fast, 6),
                    "slow_burn": round(slow, 6),
                    "breaching": breach})
            tm.gauge("slo_burn", round(worst, 6))
            tm.gauge("slo_breaching", len(breaching))
            # snapshot AFTER evaluation: the next pass's baselines
            hist_counts = {k: {"bounds": v["bounds"],
                               "counts": v["counts"],
                               "count": v["count"], "sum": v["sum"]}
                           for k, v in hists.items()}
            self._snaps.append((now, counters, hist_counts))
        return {"enabled": True, "breaching": breaching,
                "worst_burn": round(worst, 6),
                "fast_window_s": self.fast_s,
                "slow_window_s": self.slow_s,
                "rules": results}

    # -- timer ----------------------------------------------------------
    def start(self) -> None:
        if self._timer is not None and self._timer.is_alive():
            return
        self._stop.clear()
        self._timer = threading.Thread(
            target=self._run, daemon=True, name="ltpu-slo")
        self._timer.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._timer = self._timer, None
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            if TELEMETRY.mode < _COUNTERS:
                continue
            try:
                self.evaluate()
            except Exception as e:  # pragma: no cover - engine bug
                Log.warning(f"slo engine evaluation failed: {e}")

    # -- HTTP -----------------------------------------------------------
    def http_route(self, method, path, body, headers):
        """``GET /slo`` on the shared telemetry listener: evaluate on
        demand, 200 when clean, 503 when any rule is breaching (so a
        probe can alert off the status code alone)."""
        verdict = self.evaluate()
        status = 503 if verdict.get("breaching") else 200
        return (status, "application/json",
                json.dumps(verdict, sort_keys=True).encode(), None)


# -- process-global engine (Config-armed, like transport.install) -------
_ACTIVE: Optional[SloEngine] = None
_INSTALL_LOCK = threading.Lock()


def active() -> Optional[SloEngine]:
    return _ACTIVE


def install(engine: Optional[SloEngine]) -> Optional[SloEngine]:
    """Install (or clear, with None) the process-global engine:
    stops/unmounts the previous one, starts the timer and mounts
    ``GET /slo`` for the new one."""
    global _ACTIVE
    with _INSTALL_LOCK:
        prev = _ACTIVE
        if prev is not None:
            prev.stop()
            TELEMETRY.unregister_http_route("/slo")
        _ACTIVE = engine
        if engine is not None:
            TELEMETRY.register_http_route("/slo", engine.http_route)
            engine.start()
        return prev


def apply_config(cfg) -> None:
    """Arm the engine from ``Config.slo_rules`` (a JSON rules path).
    An empty knob leaves any armed engine alone — internally-built
    default Configs must not disarm a run's SLO watch mid-flight (the
    ``watchdog.apply_config`` contract)."""
    path = str(getattr(cfg, "slo_rules", "") or "")
    if not path:
        return
    rules = load_rules(path)
    install(SloEngine(
        rules,
        interval_s=float(getattr(cfg, "slo_eval_interval_s", 10.0)
                         or 10.0)))


# -- CLI ----------------------------------------------------------------
def _cmd_check(argv: List[str]) -> int:
    """``python -m lightgbm_tpu.slo check --url http://host:port``:
    fetch ``/slo`` from a live process and turn the verdict into an
    exit code — 0 clean, 1 breaching, 2 usage/unreachable — the
    cron/CI contract (mirrors ``telemetry merge``'s rc discipline)."""
    import argparse
    import urllib.error
    import urllib.request
    ap = argparse.ArgumentParser(
        prog="lightgbm_tpu.slo check",
        description="query a live process's /slo verdict")
    ap.add_argument("--url", required=True,
                    help="base URL of the telemetry listener "
                         "(e.g. http://127.0.0.1:9090)")
    ap.add_argument("--timeout", type=float, default=5.0)
    try:
        args = ap.parse_args(argv)
    except SystemExit:
        return 2
    url = args.url.rstrip("/") + "/slo"
    try:
        req = urllib.request.urlopen(url, timeout=args.timeout)
        doc = json.loads(req.read().decode())
    except urllib.error.HTTPError as e:
        if e.code != 503:
            print(f"slo check: {url} -> HTTP {e.code}")
            return 2
        doc = json.loads(e.read().decode())
    except (OSError, ValueError) as e:
        print(f"slo check: cannot reach {url}: {e}")
        return 2
    print(json.dumps(doc, sort_keys=True, indent=2))
    if not doc.get("enabled", False):
        print("slo check: telemetry off or no rules armed")
        return 2
    if doc.get("breaching"):
        print(f"slo check: BREACHING: {', '.join(doc['breaching'])}")
        return 1
    print("slo check: all rules within budget")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import sys
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("check",):
        print("usage: python -m lightgbm_tpu.slo check --url URL")
        return 2
    return _cmd_check(argv[1:])


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
