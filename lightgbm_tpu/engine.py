"""Training entry points: train() and cv().

The lgb.train / lgb.cv analogs (reference: python-package/lightgbm/
engine.py:18-230 train, :312 cv) driving the device GBDT loop with the
reference's callback/early-stopping protocol.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .booster import Booster
from .config import Config
from .dataset import Dataset
from .reliability import checkpoint as _ckpt
from .reliability.retry import is_oom
from .telemetry import TELEMETRY
from .utils.log import Log


def train(params: Dict[str, Any], train_set: Dataset,
          num_boost_round: int = 100,
          valid_sets: Optional[Sequence[Dataset]] = None,
          valid_names: Optional[Sequence[str]] = None,
          fobj: Optional[Callable] = None,
          feval: Optional[Callable] = None,
          init_model: Optional[Union[str, "Booster"]] = None,
          feature_name: Union[str, Sequence[str]] = "auto",
          categorical_feature: Union[str, Sequence] = "auto",
          early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[dict] = None,
          verbose_eval: Union[bool, int] = True,
          learning_rates: Optional[Union[Sequence[float],
                                         Callable]] = None,
          keep_training_booster: bool = False,
          callbacks: Optional[Sequence[Callable]] = None,
          resume: Optional[Union[bool, str]] = None) -> Booster:
    """Train a gradient-boosted model (reference engine.py:18-229;
    parameter order follows the reference signature engine.py:18-24).

    ``feature_name``/``categorical_feature`` apply to a still-lazy
    train_set before construction (engine.py:122-123);
    ``learning_rates`` (list or callable of the iteration index) is
    sugar for a reset_parameter callback (engine.py:167-168);
    ``keep_training_booster=False`` (the reference default,
    engine.py:224-226) releases the training state after the final
    flush — the returned booster predicts and serves as ``init_model``
    for continued training, but update() on it errors.

    ``resume`` (docs/RELIABILITY.md): ``None`` defers to
    ``config.resume`` (default "auto" — scan for the newest valid
    checkpoint when ``checkpoint_freq`` is active); ``False``/"off"
    always starts cold; a string path resumes from exactly that
    checkpoint file.  A resumed run continues FULL training state
    (model, score cache, RNG streams, early-stopping bookkeeping) and
    produces byte-identical trees to an uninterrupted run."""
    params = dict(params or {})
    if feature_name != "auto" and hasattr(train_set, "set_feature_name"):
        train_set.set_feature_name(feature_name)
    if categorical_feature != "auto" \
            and hasattr(train_set, "set_categorical_feature"):
        train_set.set_categorical_feature(categorical_feature)
    if learning_rates is not None:
        from .callback import reset_parameter
        callbacks = list(callbacks or []) + [
            reset_parameter(learning_rate=learning_rates)]
    if early_stopping_rounds is not None and not any(
            k in params for k in ("early_stopping_round",
                                  "early_stopping_rounds", "early_stopping")):
        params["early_stopping_round"] = early_stopping_rounds
    # params aliases override the argument (reference engine.py:85-91)
    from .config import PARAM_ALIASES
    has_num_iter = "num_iterations" in params or any(
        PARAM_ALIASES.get(str(k).lower()) == "num_iterations" for k in params)
    if not has_num_iter:
        params["num_iterations"] = num_boost_round
    config = Config.from_params(params)
    num_boost_round = config.num_iterations
    # config.verbosity routes to the process-global Log level on the
    # python API too, not only in CLI runs (the reference's Config
    # verbosity is global the same way); Log.fatal ignores the level
    Log.set_level(config.verbose)

    resume_arg = config.resume if resume is None else resume
    if isinstance(resume_arg, bool):
        resume_arg = "auto" if resume_arg else "off"
    resume_arg = str(resume_arg or "off")
    if resume_arg.lower() not in ("off", "false", "0", "none", "auto") \
            and init_model is not None:
        # an explicit checkpoint path + init_model is a contradiction,
        # not a precedence question: the checkpoint carries the FULL
        # training state (model included), so whichever fingerprint
        # happens to match would silently discard the other input.
        # (resume="auto" composes fine — the fingerprint carries the
        # init_model identity, so auto only ever adopts checkpoints
        # from an identically-seeded run.)  Checked BEFORE any dataset
        # construction: the conflict must fail fast.
        raise ValueError(
            "engine.train: both init_model= and an explicit resume= "
            f"checkpoint path ({resume_arg!r}) are set — the "
            "checkpoint already contains the full training state, so "
            "one of them would be silently ignored. Pass resume='off' "
            "to continue from init_model, or drop init_model to "
            "resume from the checkpoint.")

    if hasattr(train_set, "construct"):
        core_train = train_set.construct(config)
    else:
        core_train = train_set
    aligned = []
    for vs in (valid_sets or []):
        if not hasattr(vs, "construct"):
            aligned.append(vs)
        elif vs is train_set:
            aligned.append(core_train)
        else:
            # bin-align lazy valid sets to the training mappers (the
            # reference package calls set_reference in train(); a
            # valid set binned with its own mappers would evaluate
            # trees whose thresholds live in train bin space)
            aligned.append(vs.construct_aligned(core_train, config)
                           if hasattr(vs, "construct_aligned")
                           else vs.construct(config))
    valid_sets = aligned
    train_set = core_train

    booster = Booster(config=config, train_set=train_set,
                      init_model=init_model,
                      custom_objective=fobj is not None)

    valid_sets = list(valid_sets or [])
    names = list(valid_names or [])
    while len(names) < len(valid_sets):
        names.append(f"valid_{len(names)}")
    for vs, name in zip(valid_sets, names):
        if vs is train_set:
            booster.gbdt.add_train_metrics()
        else:
            booster.gbdt.add_valid(vs, name)

    if config.is_training_metric and not booster.gbdt.train_metrics:
        booster.gbdt.add_train_metrics()

    eval_freq = (verbose_eval if isinstance(verbose_eval, int)
                 and not isinstance(verbose_eval, bool)
                 else config.output_freq)
    show_eval = bool(verbose_eval)

    if evals_result is not None:
        evals_result.clear()

    # --- reliability wiring (docs/RELIABILITY.md) --------------------
    # Periodic model snapshots (reference gbdt.cpp:330-334 writes
    # <output_model>.snapshot_iter_N every snapshot_freq iterations)
    # are handled INLINE in the loop, not as a callback: the callback
    # form silently forced every snapshotting run to per-iteration
    # dispatch (chunkable checks `not callbacks`), and wrote through a
    # bare save_model a kill mid-write would tear.  Snapshots now go
    # through the atomic writer with rolling retention, and fused
    # chunks are CUT at snapshot/checkpoint boundaries instead of
    # being disabled.
    snap_on = config.snapshot_freq > 0 and bool(config.output_model)
    ckpt_on = config.checkpoint_freq > 0
    ckpt_prefix = config.checkpoint_path or \
        (config.output_model or "LightGBM_model.txt") + ".ckpt"
    if ckpt_on and not booster.gbdt.can_checkpoint():
        Log.warning(
            f"checkpoint_freq is set but boosting_type="
            f"{config.boosting_type} training state does not "
            "round-trip through checkpoints yet (gbdt/goss only); "
            "continuing without checkpoints")
        ckpt_on = False
    if ckpt_on and (fobj is not None or feval is not None):
        # a python callable has no stable identity to fingerprint: a
        # rerun with an EDITED fobj/feval would silently adopt the old
        # run's checkpoint and train a hybrid of two objectives
        Log.warning(
            "checkpoint_freq is set but custom fobj/feval callables "
            "cannot be fingerprinted for safe resume; continuing "
            "without checkpoints")
        ckpt_on = False
    # init_model identity rides the fingerprint: a continued-training
    # run (seeded scores + foreign trees) and a fresh run must never
    # adopt each other's checkpoints
    init_key = (init_model if isinstance(init_model, str)
                else "<booster>" if init_model is not None else "")
    fingerprint = (_ckpt.training_fingerprint(config, train_set,
                                              len(valid_sets), init_key)
                   if ckpt_on else None)

    def _save_checkpoint(it: int) -> bool:
        """Full-state checkpoint at iteration ``it``; True when the
        consumed no-split window says training is over."""
        t0 = time.perf_counter()
        span = TELEMETRY.start_span("checkpoint_save", iteration=it)
        state, stopped = booster.gbdt.capture_state()
        payload = {"iteration": it, "gbdt": state, "stopped": stopped,
                   "evals_result": evals_result or {}}
        path = _ckpt.save_rolling(ckpt_prefix, it, payload, fingerprint,
                                  keep=config.checkpoint_keep)
        TELEMETRY.end_span(span)
        TELEMETRY.add("checkpoint_saves", 1)
        TELEMETRY.add("checkpoint_save_ms",
                      (time.perf_counter() - t0) * 1e3)
        Log.debug(f"checkpoint saved: {path}")
        return stopped

    def _after_iterations(it: int, force: bool = False) -> bool:
        """Snapshot/checkpoint work due once iteration count ``it`` is
        reached (``force`` fires both regardless of the schedule —
        the catch-up after an unaligned stretch); True when training
        must stop."""
        if snap_on and (force or it % config.snapshot_freq == 0):
            booster.gbdt.flush_models()
            _ckpt.atomic_write_text(
                f"{config.output_model}.snapshot_iter_{it}",
                booster.model_to_string())
            _ckpt.prune_snapshots(config.output_model,
                                  config.snapshot_keep)
        if ckpt_on and (force or it % config.checkpoint_freq == 0):
            return _save_checkpoint(it)
        return False

    def _boundary(it: int) -> Optional[int]:
        """Iterations until the next periodic snapshot/checkpoint —
        fused chunks are cut here so their boundaries LAND on the
        snapshot/checkpoint schedule."""
        nxt = None
        for freq, on in ((config.snapshot_freq, snap_on),
                         (config.checkpoint_freq, ckpt_on)):
            if on:
                b = freq - (it % freq)
                nxt = b if nxt is None else min(nxt, b)
        return nxt

    # headless stretches (no per-iteration callbacks/eval/early-stop
    # consumers) run as multi-iteration fused chunks: on a
    # remote-attached TPU each dispatch is an RPC round trip, ~40% of
    # wall-clock at one call per iteration
    # (show_eval is irrelevant: with no valid sets and no train metrics
    # there is nothing to print between iterations)
    chunkable = (fobj is None and feval is None and not callbacks
                 and evals_result is None
                 and config.early_stopping_round <= 0
                 and not booster.gbdt.valid_sets
                 and not booster.gbdt.train_metrics
                 and booster.gbdt.can_chunk())
    # dispatch_chunk: iterations fused per device program.  An integer
    # pins it; "auto" re-fits the per-iteration chunk slope from two
    # probe chunks and picks the amortization point against the
    # measured dispatch cost (GBDT.tune_dispatch_chunk).  The probe
    # pass only runs where it can pay off — a real accelerator (the
    # dispatch RPC is what's being amortized; on the CPU simulation it
    # is sub-ms and auto degenerates to the default 10) and a run long
    # enough to absorb the probe iterations.
    chunk_cfg = str(config.dispatch_chunk).lower()
    chunk_size = 10 if chunk_cfg in ("auto", "") \
        else max(1, int(float(chunk_cfg)))

    stopped_early = False
    iteration = 0

    # --- resume (docs/RELIABILITY.md): continue from the newest valid
    # checkpoint (auto) or an explicit checkpoint file ---------------
    loaded = None
    if resume_arg.lower() not in ("off", "false", "0", "none", ""):
        if resume_arg.lower() == "auto":
            if ckpt_on:
                loaded = _ckpt.find_resume(ckpt_prefix, fingerprint,
                                           max_iteration=num_boost_round)
        else:
            # explicit checkpoint path: invalid files error LOUDLY —
            # the user named this exact file, silence would train a
            # different model than they asked for
            fp = fingerprint if fingerprint is not None else \
                _ckpt.training_fingerprint(config, train_set,
                                           len(valid_sets), init_key)
            _fp, payload = _ckpt.read_checkpoint(resume_arg, fp)
            loaded = (int(payload["iteration"]), payload, resume_arg)
    if loaded is not None:
        it0, payload, ck_path = loaded
        span = TELEMETRY.start_span("checkpoint_resume", iteration=it0)
        try:
            booster.gbdt.restore_state(payload["gbdt"])
        except _ckpt.CheckpointError as e:
            TELEMETRY.end_span(span)
            if resume_arg.lower() != "auto":
                raise
            Log.warning(f"cannot resume from {ck_path}: {e}; "
                        "starting cold")
        else:
            TELEMETRY.end_span(span)
            iteration = it0
            if evals_result is not None:
                evals_result.update(payload.get("evals_result") or {})
            Log.info(f"Resumed training from checkpoint {ck_path} at "
                     f"iteration {it0}")
            if payload.get("stopped"):
                # the checkpointed run had already detected end of
                # training (no-split stop window): training further
                # would grow no-gain trees past the detected end
                Log.warning(
                    "checkpoint marks the end of training (no leaves "
                    "met the split requirements); not training "
                    "further")
                num_boost_round = min(num_boost_round, it0)

    oom_warned = False

    def _train_chunk_guarded(c: int):
        """Dispatch one fused chunk with the OOM degradation ladder:
        RESOURCE_EXHAUSTED halves the chunk length (down to 1) and
        re-dispatches — trained trees are byte-identical at every
        chunk length (test_packed_carry), so the downshift degrades
        only dispatch amortization, never the model.  Returns
        (stop, iterations_actually_dispatched)."""
        nonlocal chunk_size, oom_warned
        while True:
            it0 = booster.gbdt.iter_
            try:
                return booster.gbdt.train_chunk(c), c
            except Exception as e:
                if not (config.oom_downshift and is_oom(e)) or c <= 1:
                    raise
                if booster.gbdt.iter_ != it0:
                    # the OOM surfaced AFTER the chunk committed state
                    # (async backend, late materialization at a fence
                    # or the stop-window pull): scores/iter_ already
                    # absorbed the poisoned chunk, so re-dispatching
                    # would train on garbage — fail cleanly instead;
                    # checkpoint resume is the recovery path for this
                    raise
                c = max(1, c // 2)
                chunk_size = max(1, min(chunk_size, c))
                TELEMETRY.add("oom_downshifts", 1)
                TELEMETRY.journal.emit("oom_downshift",
                                       seam="gbdt.train_chunk",
                                       new_chunk=chunk_size)
                TELEMETRY.flight.dump("oom_downshift",
                                      seam="gbdt.train_chunk",
                                      new_chunk=chunk_size)
                if not oom_warned:
                    oom_warned = True
                    Log.warning(
                        "RESOURCE_EXHAUSTED during fused-chunk "
                        f"dispatch ({e}); downshifting dispatch_chunk "
                        f"to {chunk_size} and continuing")

    train_span = TELEMETRY.start_span("train",
                                      num_boost_round=num_boost_round)
    # tuner gate counts REMAINING iterations: a resumed run near its
    # target must not spend (or overshoot with) probe chunks
    if chunkable and chunk_cfg in ("auto", "") \
            and num_boost_round - iteration >= 60:
        import jax
        if jax.default_backend() in ("tpu", "axon"):
            chunk_size, info = booster.gbdt.tune_dispatch_chunk()
            iteration += info["iters_used"]
            if info.get("stopped"):
                num_boost_round = iteration
            else:
                TELEMETRY.gauge("dispatch_chunk_auto", chunk_size)
                Log.info(
                    f"dispatch_chunk=auto: fitted slope "
                    f"{info['slope_s_per_iter'] * 1e3:.4f} ms/iter·chunk,"
                    f" dispatch {info['dispatch_s'] * 1e3:.1f} ms -> "
                    f"chunk {chunk_size}")
            if iteration > 0 and (snap_on or ckpt_on):
                # the probe chunks trained real iterations without
                # boundary alignment: write a catch-up snapshot/
                # checkpoint so a preemption right after the probe
                # window has something to resume from
                _after_iterations(iteration, force=True)
    while iteration < num_boost_round:
        remaining = num_boost_round - iteration
        if chunkable:
            # chunk length: the configured size, capped by what's left
            # and CUT at snapshot/checkpoint boundaries (a cut chunk
            # repeats the same length every period, so it costs one
            # extra compile total, not one per snapshot).  Tails of
            # 10+ run as one odd-length chunk — a single extra compile
            # instead of per-iteration dispatches, each paying the RPC
            # the chunking exists to amortize.
            c = min(chunk_size, remaining)
            bound = _boundary(iteration)
            cut = bound is not None and bound <= c
            if cut:
                c = bound
            if c == chunk_size or cut or c >= 10:
                stop, done = _train_chunk_guarded(c)
                iteration += done
                if stop or _after_iterations(iteration):
                    break
                continue
        if callbacks:
            for cb in callbacks:
                if getattr(cb, "before_iteration", False):
                    cb(_CallbackEnv(booster, params, iteration,
                                    num_boost_round, None))
        if fobj is not None:
            grad, hess = fobj(booster._current_train_scores(), train_set)
            stop = booster.gbdt.train_one_iter(grad, hess)
        else:
            stop = booster.gbdt.train_one_iter()
        if stop:
            break

        results = booster.gbdt.eval_metrics()
        if feval is not None:
            fr = feval(booster._current_train_scores(), train_set)
            if fr is not None:
                if not isinstance(fr, list):
                    fr = [fr]
                for name, val, bigger in fr:
                    results.append(("feval", name, val, bigger))
        if evals_result is not None:
            for dname, mname, value, _ in results:
                evals_result.setdefault(dname, collections.OrderedDict()) \
                    .setdefault(mname, []).append(value)
        if show_eval and results and eval_freq > 0 \
                and (iteration + 1) % eval_freq == 0:
            msg = "\t".join(f"{d}'s {m}: {v:g}"
                            for d, m, v, _ in results)
            Log.info(f"[{iteration + 1}]\t{msg}")
        if callbacks:
            env = _CallbackEnv(booster, params, iteration, num_boost_round,
                               [(d, m, v, b) for d, m, v, b in results])
            for cb in callbacks:
                if not getattr(cb, "before_iteration", False):
                    try:
                        cb(env)
                    except EarlyStopException as e:
                        booster.best_iteration = e.best_iteration + 1
                        stopped_early = True
            if stopped_early:
                break
        if booster.gbdt.check_early_stopping(results, iteration):
            booster.best_iteration = booster.gbdt.best_iteration
            Log.info(f"Early stopping at iteration {iteration + 1}, best "
                     f"iteration is {booster.best_iteration}")
            stopped_early = True
            break
        iteration += 1
        if _after_iterations(iteration):
            break
    if not stopped_early:
        booster.best_iteration = -1
    if booster.gbdt is not None:
        booster.gbdt.flush_models(final=True)
    TELEMETRY.end_span(train_span)
    if booster.gbdt is not None and booster.gbdt.timer.acc:
        Log.debug("training phase timings: "
                  + booster.gbdt.timer.report())
    if str(config.quality).lower() == "on" \
            and booster.gbdt is not None and booster.models:
        # model-quality reference profile (docs/MODEL_MONITORING.md):
        # captured while the training state is still resident — the
        # feature histograms read the already-built bin matrix, the
        # score histogram reads the boosting score cache, so capture
        # costs one bincount pass + a pred_leaf over a strided sample.
        # save_model persists it as <model>.quality.json; serving
        # monitors bin live traffic against it.
        from .quality import build_profile
        try:
            booster.quality_profile = build_profile(booster, train_set,
                                                    config)
        except Exception as e:  # capture must never fail the training
            Log.warning(
                f"quality profile capture failed "
                f"({type(e).__name__}: {e}); model trains/saves "
                "without a profile")
    if not keep_training_booster:
        # reference engine.py:224-226: the default return is a
        # predictor — training state (binned device matrix, padded
        # score arrays) is released; prediction and use as init_model
        # keep working
        booster.free_dataset()
    if config.predict_warm_buckets and booster.num_trees() > 0:
        # serving warm-up: pre-compile the bucketed device predictor
        # for the declared batch shapes, so the first request after
        # deploy pays a cache hit instead of a compile
        booster.warm_predictor(config.predict_warm_buckets)
    return booster


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score=None):
        self.best_iteration = best_iteration
        self.best_score = best_score


_CallbackEnv = collections.namedtuple(
    "LightGBMCallbackEnv",
    ["model", "params", "iteration", "end_iteration", "evaluation_result_list"])


class CVBooster:
    """Ensemble of per-fold boosters (reference engine.py:230-260).
    Attribute access fans out to every fold's booster and returns the
    list of results."""

    def __init__(self, boosters=None):
        self.boosters = list(boosters or [])
        self.best_iteration = -1

    def _append(self, booster):
        self.boosters.append(booster)

    def __getattr__(self, name):
        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs)
                    for b in self.boosters]
        return handler


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       early_stopping_rounds=None, seed: int = 0,
       callbacks=None, verbose_eval=None,
       return_cvbooster: bool = False) -> Dict[str, List[float]]:
    """K-fold cross-validation (reference engine.py:312-425)."""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    config = Config.from_params(params)
    # keep the pre-construct raw data in hand: with the reference's
    # free_raw_data=True default the constructed core drops it, but cv
    # re-bins each fold from raw (the reference's cv instead subsets
    # the constructed dataset; per-fold re-binning is this framework's
    # equivalent, and fold mappers are refit per fold like `lgb.cv`
    # semantics require)
    lazy_data = getattr(train_set, "data", None)
    if hasattr(train_set, "construct"):
        train_set = train_set.construct(config)
    label = train_set.metadata.label
    n = train_set.num_data
    rng = np.random.RandomState(seed)

    if folds is None:
        idx = np.arange(n)
        if stratified and config.objective in ("binary", "multiclass",
                                               "multiclassova"):
            folds = _stratified_folds(label, nfold, rng, shuffle)
        else:
            if shuffle:
                rng.shuffle(idx)
            folds = [(np.setdiff1d(idx, idx[i::nfold], assume_unique=False),
                      idx[i::nfold]) for i in range(nfold)]

    raw = train_set._raw_data
    if raw is None and lazy_data is not None \
            and not isinstance(lazy_data, str):
        # free_raw_data=True (the default) dropped the converted matrix
        # at construct; re-convert the caller's in-memory data once for
        # the per-fold re-binning (costs one extra materialization —
        # pass free_raw_data=False to avoid it)
        from .basic import _is_sparse, _to_matrix
        raw = (lazy_data.tocsr() if _is_sparse(lazy_data)
               else _to_matrix(lazy_data, None))
    if raw is None:
        Log.fatal("cv requires the Dataset's raw data: pass an "
                  "in-memory matrix, or a non-streaming file dataset "
                  "with free_raw_data=False (two_round streaming never "
                  "materializes the matrix)")

    results: Dict[str, List[float]] = collections.defaultdict(list)
    boosters = []
    fold_evals = []
    for train_idx, test_idx in folds:
        dtrain = Dataset.from_matrix(
            raw[train_idx], label=label[train_idx],
            weight=None if train_set.metadata.weight is None
            else train_set.metadata.weight[train_idx],
            config=config,
            categorical_features=train_set._categorical_features)
        dtest = Dataset.from_matrix(
            raw[test_idx], label=label[test_idx],
            weight=None if train_set.metadata.weight is None
            else train_set.metadata.weight[test_idx],
            config=config, reference=dtrain)
        er: dict = {}
        bst = train(params, dtrain, num_boost_round, valid_sets=[dtest],
                    valid_names=["valid"], fobj=fobj, feval=feval,
                    early_stopping_rounds=early_stopping_rounds,
                    evals_result=er, verbose_eval=False,
                    # the reference's cv never frees fold boosters —
                    # a returned CVBooster stays trainable/evaluable
                    keep_training_booster=True)
        boosters.append(bst)
        fold_evals.append(er.get("valid", {}))

    if fold_evals and fold_evals[0]:
        num_iters = min(len(next(iter(fe.values()))) for fe in fold_evals)
        for mname in fold_evals[0]:
            for i in range(num_iters):
                vals = [fe[mname][i] for fe in fold_evals]
                results[f"{mname}-mean"].append(float(np.mean(vals)))
                results[f"{mname}-stdv"].append(float(np.std(vals)))
    out = dict(results)
    if return_cvbooster:
        cvb = CVBooster(boosters)
        cvb.best_iteration = max((b.best_iteration for b in boosters),
                                 default=-1)
        out["cvbooster"] = cvb
    return out


def _stratified_folds(label, nfold, rng, shuffle):
    classes = np.unique(label)
    fold_test = [[] for _ in range(nfold)]
    for c in classes:
        idx = np.nonzero(label == c)[0]
        if shuffle:
            rng.shuffle(idx)
        for i in range(nfold):
            fold_test[i].append(idx[i::nfold])
    folds = []
    all_idx = np.arange(len(label))
    for i in range(nfold):
        test = np.concatenate(fold_test[i])
        train_idx = np.setdiff1d(all_idx, test)
        folds.append((train_idx, test))
    return folds
