"""Multi-host ingestion + rendezvous.

TPU-native redesign of the reference's distributed loading protocol
(reference: src/io/dataset_loader.cpp:424-456 row partitioning,
:523-605 + :828-886 distributed bin finding with mapper allgather):

  * Rendezvous: ``jax.distributed.initialize`` (the Linkers TCP-mesh
    construction, linkers_socket.cpp:20-78, collapses to one call; the
    coordinator address plays mlist.txt's role).
  * Distributed bin finding: every host samples ITS OWN row shard,
    the per-host samples are allgathered (multihost_utils), and every
    host fits bin mappers + EFB bundles from the identical combined
    sample — deterministic construction replaces the reference's
    serialized-mapper allgather (same result, no custom wire format).
  * Per-host binning: each host bins ONLY its row shard into its local
    (N_local, G) uint8 matrix; the training mesh then assembles the
    global row-sharded array with
    ``jax.make_array_from_process_local_data``.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..config import Config
from ..utils.log import Log


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               config: Optional[Config] = None) -> None:
    """Join the multi-host rendezvous (reference Network::Init +
    Linkers ctor).  With no arguments, jax auto-detects the cluster
    environment (TPU pod metadata / SLURM / env vars).

    Transient rendezvous failures (coordinator still starting, DNS
    races) retry with bounded backoff under the config's retry policy
    and the reference ``time_out`` budget — the ``distributed.init``
    seam in the fault harness (docs/RELIABILITY.md).

    ``Config.collective_transport`` selects the collective plane:
    ``xla`` rendezvouses through ``jax.distributed`` (cross-process
    XLA collectives, pods); ``tcp`` builds the host-side TCP transport
    (``parallel/transport.py``) instead — no ``jax.distributed`` at
    all, so multi-process training works on the CPU backend; ``auto``
    picks tcp exactly when cross-process XLA collectives are
    unavailable (docs/Parallel-Learning-Guide.md)."""
    from ..reliability.faults import FAULTS
    from ..reliability.retry import RetryPolicy, retry_call
    from . import transport as _transport

    mode = _transport.resolve_transport_mode(config, num_processes)
    if mode == "tcp" and (num_processes or 1) > 1:
        if coordinator_address is None or process_id is None:
            raise ValueError(
                "collective_transport=tcp needs an explicit "
                "coordinator_address, num_processes and process_id "
                "(no cluster auto-detection on the host-side plane)")
        tp = _transport.TcpTransport.create(
            coordinator_address, int(num_processes), int(process_id),
            config=config)
        _transport.install(tp)
        from ..telemetry import TELEMETRY
        TELEMETRY.mark_sync("rendezvous")
        return

    def _init():
        FAULTS.fault_point("distributed.init")
        import jax
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)

    if config is None:
        policy = RetryPolicy()
    else:
        policy = RetryPolicy.from_config(config)
        policy.budget_s = config.time_out * 60.0
    retry_call(_init, seam="distributed.init", policy=policy)
    # the rendezvous barrier exits near-simultaneously on every host:
    # mark it as the clock-sync anchor the cross-host trace merge
    # aligns shards on (docs/OBSERVABILITY.md, trace merge)
    from ..telemetry import TELEMETRY
    TELEMETRY.mark_sync("rendezvous")


def sample_local_rows(local_data: np.ndarray, sample_cnt: int,
                      seed: int) -> np.ndarray:
    """FIXED-SIZE (sample_cnt, F+1) row sample of this host's shard:
    the collective requires identical shapes on every process, so
    shards smaller than the quota pad with rows whose trailing
    validity column is 0 (dropped after the gather).  Each host uses a
    DIFFERENT derived seed so the combined sample isn't biased toward
    identical row positions."""
    n, f = local_data.shape
    rng = np.random.RandomState(seed + 7919 * _process_index())
    out = np.zeros((sample_cnt, f + 1), dtype=np.float64)
    take = min(n, sample_cnt)
    if n <= sample_cnt:
        out[:take, :f] = np.asarray(local_data, dtype=np.float64)
    else:
        idx = rng.choice(n, size=sample_cnt, replace=False)
        idx.sort()
        out[:, :f] = np.asarray(local_data[idx], dtype=np.float64)
    out[:take, f] = 1.0
    return out


def _allgather(arr: np.ndarray) -> np.ndarray:
    """Host collective backend call — the ``collectives.allgather``
    fault seam every gather in this module routes through (a preempted
    peer surfaces here as an UNAVAILABLE RPC error).

    Deliberately NO per-host retry: collectives are entered in
    lockstep by every process, so one host re-entering alone would
    either hang (no peer joins its retry) or pair with a peer's NEXT
    collective and gather mismatched data.  A failed collective fails
    the job loudly; recovery is job restart + checkpoint resume
    (docs/RELIABILITY.md).

    Unlike the in-program collectives (trace-time byte accounting
    only), this call BLOCKS the host, so its wall is a true fenced
    collective latency: counted in ``collective_host_allgather_*``
    and observed into the ``collective_host_allgather_ms`` histogram
    — and, with ``watchdog_collective_s`` armed, deadline-bounded:
    a gather wedged past the deadline (a peer that HANGS instead of
    dying leaves this call blocked forever otherwise) dumps all-thread
    stacks and raises a classified ``StallError``, the ``Network``
    ``time_out`` semantic the reference puts on every socket op."""
    import time

    from ..reliability import watchdog as _watchdog
    from ..reliability.faults import FAULTS
    from ..telemetry import TELEMETRY as tm

    def _gather() -> np.ndarray:
        FAULTS.fault_point("collectives.allgather")
        from . import transport as _transport
        tp = _transport.active()
        if tp is not None:
            # host-side TCP plane: the Bruck allgather returns the
            # same stacked (P, *shape) array the XLA path does
            return tp.allgather(arr)
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(arr))

    t0 = time.perf_counter() if tm.on else 0.0
    with tm.span("collective_allgather"):
        out = _watchdog.run_with_deadline(
            _gather, _watchdog.deadline("collective"),
            phase="host_collective", seam="collectives.allgather")
    if tm.on:
        # bytes as a counter; latency ONLY as the histogram — its
        # _sum/_count already carry total wall and call count, and a
        # same-named counter would collide with the histogram family
        # in the Prometheus exposition
        tm.add("collective_host_allgather_bytes", int(out.nbytes))
        tm.observe("collective_host_allgather_ms",
                   (time.perf_counter() - t0) * 1e3)
    return out


def allgather_samples(local_sample: np.ndarray) -> np.ndarray:
    """(S, F+1) per-host padded sample -> (sum valid, F) combined
    sample, identical on every host (the redesign of the reference's
    per-feature serialized-mapper allgather)."""
    gathered = _allgather(local_sample)
    flat = gathered.reshape(-1, local_sample.shape[1])
    valid = flat[:, -1] > 0.5
    return flat[valid, :-1]


def construct_sharded(local_data: np.ndarray, label=None, weight=None,
                      group=None, config: Optional[Config] = None,
                      categorical_features: Optional[Sequence[int]] = None,
                      feature_names: Optional[Sequence[str]] = None):
    """Build THIS HOST's shard of the distributed dataset: mappers and
    EFB bundles are fitted from the globally-gathered sample (bit-equal
    on every host), then only the local rows are binned.

    Returns a CoreDataset whose ``group_bins`` holds N_local rows; the
    caller assembles the global array over the mesh with
    ``jax.make_array_from_process_local_data``.
    """
    from ..data_loader import split_sample_columns
    from ..dataset import Dataset as CoreDataset
    from . import transport as _transport
    config = config or Config()
    local_data = np.asarray(local_data, dtype=np.float64)
    tp = _transport.active()
    if tp is not None and tp.world_size > 1:
        # TCP plane: the r16 boundary-candidate protocol crosses the
        # real wire — this process's candidates (sharded.binfind seam)
        # gather over the transport and merge in rank order, so the
        # fitted mappers are byte-equal to the in-process sharded fit
        # (and, quotas permitting, to a single-host whole-data fit)
        from ..sharded import binfind
        cand = binfind.collect_candidates(local_data, config,
                                          tp.rank, tp.world_size)
        sample_vals, sample_rows, total = \
            binfind.gather_merge_remote(cand, tp)
    else:
        local_sample = sample_local_rows(
            local_data, max(1, config.bin_construct_sample_cnt //
                            max(1, _num_processes())),
            config.data_random_seed)
        combined = allgather_samples(local_sample)
        # the COMBINED sample drives mapper + EFB fitting (bit-equal
        # on every host); construction then reuses the single-host
        # streaming machinery with one local "push" of this host's
        # rows
        sample_vals, sample_rows = split_sample_columns(combined)
        total = combined.shape[0]
    ds = CoreDataset.from_sampled_columns(
        sample_vals, sample_rows, total,
        local_data.shape[0], config=config,
        categorical_features=categorical_features,
        feature_names=feature_names)
    ds.push_rows(local_data, 0)
    ds.finish_load()
    if label is not None:
        ds.metadata.set_label(np.asarray(label))
    ds.metadata.set_weight(weight)
    ds.metadata.set_group(group)
    return ds


def finalize_global(ds):
    """Promote a per-host shard dataset (construct_sharded) into the
    GLOBAL training view: metadata (labels/weights — bytes-per-row
    small) is allgathered into assembled global row order (host 0's
    rows, then host 1's, ...), ``num_data`` becomes the global count,
    while ``group_bins`` stays THIS host's rows — the grower assembles
    the global HBM array over the mesh with
    ``jax.make_array_from_process_local_data`` (the redesign of
    reference data_parallel_tree_learner.cpp:117-246, where each
    machine trains on its shard and histograms are reduce-scattered).
    """
    from ..dataset import Metadata
    from . import transport as _transport
    nproc = _num_processes()
    if nproc <= 1:
        return ds
    n_local = ds.num_data
    counts = _allgather(np.array([n_local], dtype=np.int64)).ravel()
    if not (counts == counts[0]).all():
        Log.fatal("multi-host training requires equal row shards per "
                  f"host, got {counts.tolist()} — pad the tail shard")
    if ds.metadata.query_boundaries is not None:
        Log.fatal("multi-host ranking (query groups) is not supported "
                  "yet — queries must not span hosts")
    n_global = int(counts.sum())
    md = Metadata(n_global)
    md.label = _allgather(
        np.ascontiguousarray(ds.metadata.label)).reshape(-1) \
        .astype(np.float32)
    if ds.metadata.weight is not None:
        md.weight = _allgather(
            np.ascontiguousarray(ds.metadata.weight)).reshape(-1) \
            .astype(np.float32)
    if ds.metadata.init_score is not None:
        # init_score is class-major per host ((K, n_local) flattened);
        # a naive concat would interleave hosts inside classes
        init_l = np.ascontiguousarray(ds.metadata.init_score)
        k = max(1, len(init_l) // n_local)
        gathered = _allgather(init_l).reshape(nproc, k, n_local)
        md.init_score = np.transpose(gathered, (1, 0, 2)).reshape(-1)
    ds.metadata = md
    tp = _transport.active()
    if tp is not None and tp.world_size > 1:
        # host-side TCP plane: no cross-process XLA arrays exist here,
        # so the global bin matrix REPLICATES — every process gathers
        # all (N_local, C) uint8 bin shards in rank order (row-wise
        # concat is layout-safe for every bin_packing: packing is
        # per-row) and then runs the IDENTICAL deterministic
        # single-host training program.  Trees are byte-identical to a
        # single-process run by construction; memory is the full
        # matrix per process (docs/Parallel-Learning-Guide.md names
        # this the tcp plane's scaling bound — the xla plane keeps
        # bins row-sharded)
        bins = _allgather(np.ascontiguousarray(ds.group_bins))
        ds.group_bins = np.ascontiguousarray(
            bins.reshape(-1, bins.shape[-1]))
        ds.num_data = n_global
        ds._pushed_rows = n_global
        return ds
    ds._mh_local_rows = n_local
    ds._multihost = True
    ds.num_data = n_global
    return ds


def _num_processes() -> int:
    """World size as the ACTIVE transport sees it — an installed TCP
    transport (including a degraded or elastically-grown world) wins
    over ``jax.process_count()``, so quota math and telemetry report
    honest sizes."""
    from . import transport as _transport
    tp = _transport.active()
    if tp is not None:
        return tp.world_size
    import jax
    try:
        return jax.process_count()
    except Exception:  # pragma: no cover - uninitialized
        return 1


def _process_index() -> int:
    """This process's rank in the active world (transport first, then
    ``jax.process_index()``) — elastic re-joins get FRESH ranks, and
    the sampling seams must derive their seeds from the rank actually
    held, not the one jax booted with."""
    from . import transport as _transport
    tp = _transport.active()
    if tp is not None:
        return tp.rank
    import jax
    try:
        return jax.process_index()
    except Exception:  # pragma: no cover - uninitialized
        return 0
