"""Collective-communication seam.

The functional equivalent of the reference's static Network class
(reference: include/LightGBM/network.h:86-296 — Allreduce,
ReduceScatter, Allgather, GlobalSyncUpByMin/Max/Mean, GlobalSum — and
the external-function injection point Network::Init(num_machines, rank,
reduce_scatter_fn, allgather_fn) at network.h:96 / c_api.h:760).

Inside jitted programs the collectives are implicit in shardings (see
parallel/mesh.py); this module exists for code that needs EXPLICIT
collective calls — the voting learner's vote exchange, distributed
objective syncs (RenewTreeOutput's GlobalSum, gbdt.cpp:795-804), and
tests that inject a fake backend the way LGBM_NetworkInitWithFunctions
allowed.  The default backend maps straight onto jax.lax collectives
over a named mesh axis; a host backend (numpy, single process) makes
the distributed code paths unit-testable without any devices.
"""
from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import TELEMETRY


def traced_nbytes(x) -> int:
    """Byte size of an array/tracer from its abstract shape+dtype —
    Python arithmetic only, safe to call at trace time inside jitted
    bodies (no host-library calls, no HLO change)."""
    size = 1
    for d in getattr(x, "shape", ()) or ():
        size *= int(d)
    dt = getattr(x, "dtype", None)
    return size * (int(dt.itemsize) if dt is not None else 4)


def _note_collective(kind: str, x) -> None:
    """Trace-time collective accounting (docs/OBSERVABILITY.md,
    distributed observability): counts explicit collective call SITES
    and their payload bytes per kind.  Inside a jitted body this runs
    once per trace (so the counters read "bytes exchanged per
    compiled step", the same unit the MULTICHIP gate asserts);
    on the host backends it counts every call.  Pure host Python —
    the telemetry=off/counters identity guarantee holds because
    nothing here emits an op."""
    if TELEMETRY.on:
        TELEMETRY.add(f"collective_{kind}_calls", 1)
        TELEMETRY.add(f"collective_{kind}_bytes", traced_nbytes(x))


class Collectives:
    """Collective ops over a named mesh axis, usable inside shard_map."""

    def __init__(self, axis_name: Optional[str]):
        self.axis_name = axis_name

    @property
    def is_distributed(self) -> bool:
        return self.axis_name is not None

    # -- core three (the only ones the learners need; SURVEY §2.4) ----
    def allreduce_sum(self, x):
        if self.axis_name is None:
            return x
        _note_collective("allreduce", x)
        return jax.lax.psum(x, self.axis_name)

    def reduce_scatter(self, x, tiled_axis: int = 0):
        if self.axis_name is None:
            return x
        _note_collective("reduce_scatter", x)
        return jax.lax.psum_scatter(x, self.axis_name,
                                    scatter_dimension=tiled_axis,
                                    tiled=True)

    def all_gather(self, x, axis: int = 0):
        if self.axis_name is None:
            return x
        _note_collective("allgather", x)
        return jax.lax.all_gather(x, self.axis_name, axis=axis,
                                  tiled=True)

    # -- scalar sync helpers (network.h:165-257) ----------------------
    def global_sum(self, x):
        return self.allreduce_sum(x)

    def global_min(self, x):
        if self.axis_name is None:
            return x
        _note_collective("allreduce", x)
        return jax.lax.pmin(x, self.axis_name)

    def global_max(self, x):
        if self.axis_name is None:
            return x
        _note_collective("allreduce", x)
        return jax.lax.pmax(x, self.axis_name)

    def global_mean(self, x):
        if self.axis_name is None:
            return x
        _note_collective("allreduce", x)
        return jax.lax.pmean(x, self.axis_name)

    def argmax_sync(self, value, payload):
        """Global argmax with payload broadcast — the
        SyncUpGlobalBestSplit pattern (parallel_tree_learner.h:184-207):
        every shard contributes (gain, split-struct); all shards end up
        with the payload of the globally best gain."""
        if self.axis_name is None:
            return payload
        _note_collective("allgather", value)
        gains = jax.lax.all_gather(value, self.axis_name)
        best = jnp.argmax(gains)
        gathered = jax.tree_util.tree_map(
            lambda p: (_note_collective("allgather", p),
                       jax.lax.all_gather(p, self.axis_name))[1],
            payload)
        return jax.tree_util.tree_map(lambda g: g[best], gathered)

    def rank(self):
        if self.axis_name is None:
            return 0
        return jax.lax.axis_index(self.axis_name)

    def num_machines(self):
        if self.axis_name is None:
            return 1
        return jax.lax.axis_size(self.axis_name)


class HostCollectives(Collectives):
    """Single-process fake backend — the LGBM_NetworkInitWithFunctions
    analog for unit tests: simulates a k-way reduction by applying the
    reduction to caller-provided per-shard arrays."""

    def __init__(self, shards: int = 1):
        super().__init__(None)
        self.shards = shards

    def simulate_allreduce(self, per_shard_arrays):
        for a in per_shard_arrays:
            _note_collective("allreduce", a)
        return np.sum(np.stack(per_shard_arrays), axis=0)

    def simulate_reduce_scatter(self, per_shard_arrays, axis: int = 0):
        total = np.sum(np.stack(per_shard_arrays), axis=0)
        for a in per_shard_arrays:
            _note_collective("reduce_scatter", a)
        return np.array_split(total, self.shards, axis=axis)

    def simulate_allgather(self, per_shard_arrays, axis: int = 0):
        # the simulated gather carries the SAME reliability seam and
        # deadline as the real host collective (distributed._allgather):
        # sharded-construct merges route through here, so a chaos plan
        # naming collectives.allgather — including a hang bounded by
        # watchdog_collective_s — exercises the simulated participants
        # exactly like a pod would see it
        from ..reliability import watchdog as _watchdog
        from ..reliability.faults import FAULTS

        def _gather():
            FAULTS.fault_point("collectives.allgather")
            for a in per_shard_arrays:
                _note_collective("allgather", a)
            return np.concatenate(per_shard_arrays, axis=axis)

        return _watchdog.run_with_deadline(
            _gather, _watchdog.deadline("collective"),
            phase="host_collective", seam="collectives.allgather")


# ---------------------------------------------------------------------------
# Compressed histogram exchange (Config.hist_exchange): the data-
# parallel per-pass histogram psum is the largest recurring ICI
# payload (the MULTICHIP gate's byte window), and histogram bins are
# SMOOTH along the bin axis — neighboring bins hold similar mass — so
# a delta code along bins concentrates values near zero and a shared
# per-(leaf, group, channel) scale quantizes the deltas to int16/int8
# at bounded reconstruction error.  Delta-coding is linear, so it
# COMMUTES with the cross-shard sum: shards quantize against one
# pmax'd scale, psum the narrow integers (with world-size headroom so
# the integer sum can never overflow), and every shard reconstructs
# the identical f32 histogram by cumsum BEFORE the FixHistogram /
# parent-subtraction step.
# ---------------------------------------------------------------------------
HIST_EXCHANGE_MODES = ("f32", "q16", "q8")


def _exchange_qparams(mode: str, world: int):
    """(qmax, int dtype) for a codec mode: the quantization ceiling
    leaves ``world``-way summation headroom inside the wire dtype."""
    bits = 16 if mode == "q16" else 8
    qmax = (2 ** (bits - 1) - 1) // max(int(world), 1)
    if qmax < 1:
        raise ValueError(
            f"hist_exchange={mode}: world size {world} leaves no "
            f"quantization levels inside int{bits}; use "
            + ("hist_exchange=q16 or f32" if mode == "q8" else
               "hist_exchange=f32"))
    return qmax, (jnp.int16 if mode == "q16" else jnp.int8)


def exchange_histograms(hist, axis_name, mode: str = "f32",
                        world: int = 1):
    """Cross-shard histogram sum over ``axis_name`` under the
    ``hist_exchange`` codec.  ``hist`` is the local (L, G, B, 3) f32
    histogram (bin axis -2); returns the reconstructed f32 global sum
    on every shard.

    "f32" is the legacy raw psum — identical lowering, byte-identical
    trees.  "q16"/"q8" ship delta-coded integers plus a tiny f32
    scale payload; wire bytes land in the
    ``collective_hist_exchange_bytes`` counter (the int payload) and
    ``collective_hist_exchange_scale_bytes`` (the scales), so the
    MULTICHIP gate reads the compressed stream directly."""
    if mode not in HIST_EXCHANGE_MODES:
        raise ValueError(f"hist_exchange must be one of "
                         f"{HIST_EXCHANGE_MODES}, got {mode!r}")
    if axis_name is None:
        return hist
    if mode == "f32":
        _note_collective("hist_exchange", hist)
        return jax.lax.psum(hist, axis_name)
    qmax, qdt = _exchange_qparams(mode, world)
    first = hist[..., :1, :]
    delta = jnp.concatenate([first, jnp.diff(hist, axis=-2)], axis=-2)
    # ONE scale per (leaf, group, channel), shared across shards via
    # pmax so every shard quantizes against the same grid and the
    # integer sum dequantizes exactly once.  The non-integrality
    # residual rides the same pmax payload (bin axis, position 1):
    # channels whose deltas are integral on EVERY shard and fit qmax
    # (the count channel always; grad/hess too under the unit-gradient
    # objectives, e.g. regression_l1) ship verbatim on the unit grid —
    # the reconstruction is then bit-exact against the f32 psum
    amax = jnp.max(jnp.abs(delta), axis=-2, keepdims=True)
    frac = jnp.max(jnp.abs(delta - jnp.round(delta)), axis=-2,
                   keepdims=True)
    stat = jax.lax.pmax(jnp.concatenate([amax, frac], axis=-2),
                        axis_name)
    amax, frac = stat[..., :1, :], stat[..., 1:, :]
    _note_collective("hist_exchange_scale", stat)
    exact = (frac == 0) & (amax <= qmax)
    denom = jnp.where(exact, jnp.float32(qmax),
                      jnp.maximum(amax, 1e-30))
    q = jnp.clip(jnp.round(delta / denom * qmax),
                 -qmax, qmax).astype(qdt)
    _note_collective("hist_exchange", q)
    qsum = jax.lax.psum(q, axis_name)
    deq = qsum.astype(jnp.float32) * (denom / qmax)
    return jnp.cumsum(deq, axis=-2)


def host_exchange_histograms(per_shard_hists, mode: str = "f32"):
    """Single-process analog of :func:`exchange_histograms` over
    caller-provided per-shard numpy histograms — the
    HostCollectives.simulate_* pattern, so the codec path (and its
    byte counters) is unit-testable and benchable without devices.
    Carries the ``collectives.hist_exchange`` fault seam and the
    collective watchdog deadline exactly like the simulated
    allgather."""
    if mode not in HIST_EXCHANGE_MODES:
        raise ValueError(f"hist_exchange must be one of "
                         f"{HIST_EXCHANGE_MODES}, got {mode!r}")
    from ..reliability import watchdog as _watchdog
    from ..reliability.faults import FAULTS

    def _exchange():
        FAULTS.fault_point("collectives.hist_exchange")
        world = len(per_shard_hists)
        stack = np.stack([np.asarray(a, dtype=np.float32)
                          for a in per_shard_hists])
        if mode == "f32":
            for a in per_shard_hists:
                _note_collective("hist_exchange", a)
            return np.sum(stack, axis=0)
        bits = 16 if mode == "q16" else 8
        qmax = (2 ** (bits - 1) - 1) // world
        if qmax < 1:
            raise ValueError(
                f"hist_exchange={mode}: world size {world} leaves no "
                f"quantization levels inside int{bits}")
        npdt = np.int16 if mode == "q16" else np.int8
        delta = np.concatenate(
            [stack[..., :1, :], np.diff(stack, axis=-2)], axis=-2)
        amax = np.max(np.abs(delta), axis=(0, -2), keepdims=True)[0]
        # exact-integer fast path (see exchange_histograms): integral
        # channels that fit qmax ship verbatim on the unit grid
        frac = np.max(np.abs(delta - np.round(delta)), axis=(0, -2),
                      keepdims=True)[0]
        exact = (frac == 0) & (amax <= qmax)
        denom = np.where(exact, np.float32(qmax),
                         np.maximum(amax, np.float32(1e-30)))
        q = np.clip(np.round(delta / denom * qmax),
                    -qmax, qmax).astype(npdt)
        stat = np.concatenate([amax, frac], axis=-2)
        for s in range(world):
            _note_collective("hist_exchange", q[s])
            _note_collective("hist_exchange_scale", stat)
        qsum = np.sum(q.astype(np.int32), axis=0)
        deq = qsum.astype(np.float32) * (denom / np.float32(qmax))
        return np.cumsum(deq, axis=-2, dtype=np.float32)

    return _watchdog.run_with_deadline(
        _exchange, _watchdog.deadline("collective"),
        phase="host_collective", seam="collectives.hist_exchange")


class ExternalCollectives(HostCollectives):
    """User-injected reduce-scatter/allgather callables — the direct
    analog of LGBM_NetworkInitWithFunctions (reference c_api.h:760-762,
    network.h:96).  Callables receive and return numpy arrays; used by
    embedders that bring their own transport."""

    def __init__(self, num_machines: int, rank: int,
                 reduce_scatter_fn: Optional[Callable] = None,
                 allgather_fn: Optional[Callable] = None):
        super().__init__(shards=num_machines)
        self.external_rank = rank
        self.reduce_scatter_fn = reduce_scatter_fn
        self.allgather_fn = allgather_fn

    def simulate_reduce_scatter(self, per_shard_arrays, axis: int = 0):
        if self.reduce_scatter_fn is None:
            return super().simulate_reduce_scatter(per_shard_arrays, axis)
        return self.reduce_scatter_fn(per_shard_arrays)

    def simulate_allgather(self, per_shard_arrays, axis: int = 0):
        if self.allgather_fn is None:
            return super().simulate_allgather(per_shard_arrays, axis)
        return self.allgather_fn(per_shard_arrays)


# ---------------------------------------------------------------------------
# Compiled-program collective accounting: the sharding-implicit
# collectives (the SPMD partitioner inserts them — nothing in Python
# calls an op) are read back from the compiled module text.  This is
# the per-collective byte signal the MULTICHIP gate asserts
# (__graft_entry__) and a telemetric run exports (the "largest reduce
# 220320 B, 3 collectives/step" numbers as counters, not prose).
# ---------------------------------------------------------------------------
_HLO_COLLECTIVE_RE = re.compile(
    r"= .*?\s(all-reduce|reduce-scatter|all-gather|all-to-all|"
    r"collective-permute)(-start)?\(")
_HLO_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_HLO_ITEMSIZE = {"f64": 8, "s64": 8, "u64": 8, "f32": 4, "s32": 4,
                 "u32": 4, "bf16": 2, "f16": 2, "s16": 2, "u16": 2,
                 "s8": 1, "u8": 1, "pred": 1}
_REDUCE_KINDS = ("all-reduce", "reduce-scatter")


def scan_compiled_collectives(compiled_text: str) -> Dict:
    """Parse a compiled HLO module's collective ops into per-kind
    byte/count totals.  Tuple-shaped ops (XLA's collective combiner
    emits ``(f32[378], f32[8192]) all-reduce(...)``) account every
    member shape.  Returns ``{"kinds": {kind: {"count", "bytes"}},
    "ops": [(kind, total_bytes, worst_dim)], "largest_reduce_bytes",
    "reduce_count"}``."""
    kinds: Dict[str, Dict[str, int]] = {}
    ops: List[Tuple[str, int, int]] = []
    reduce_sizes: List[int] = []
    for ln in compiled_text.splitlines():
        m = _HLO_COLLECTIVE_RE.search(ln)
        if not m:
            continue
        kind = m.group(1)
        total = 0
        worst_dim = 0
        for dt, dims in _HLO_SHAPE_RE.findall(ln[:m.start(1)]):
            dvals = [int(d) for d in dims.split(",") if d]
            n = 1
            for d in dvals:
                n *= d
            total += n * _HLO_ITEMSIZE.get(dt, 4)
            worst_dim = max(worst_dim, max(dvals or [0]))
        k = kinds.setdefault(kind, {"count": 0, "bytes": 0})
        k["count"] += 1
        k["bytes"] += total
        ops.append((kind, total, worst_dim))
        if kind in _REDUCE_KINDS:
            reduce_sizes.append(total)
    return {
        "kinds": kinds,
        "ops": ops,
        "largest_reduce_bytes": max(reduce_sizes, default=0),
        "reduce_count": len(reduce_sizes),
    }


def record_compiled_collectives(compiled_text: str,
                                program: str = "step") -> Dict:
    """Scan a compiled module's collectives AND publish them as
    telemetry counters/gauges (no-op at ``telemetry=off``):
    ``hlo_collective_<kind>_count`` / ``hlo_collective_<kind>_bytes``
    per kind, the ``collective_largest_reduce_bytes`` /
    ``collective_reduce_count`` gauges, and a
    ``collective_profile.<program>`` string gauge naming the program
    scanned.  Returns the scan dict."""
    stats = scan_compiled_collectives(compiled_text)
    if TELEMETRY.on:
        for kind, k in sorted(stats["kinds"].items()):
            name = kind.replace("-", "_")
            TELEMETRY.add(f"hlo_collective_{name}_count", k["count"])
            TELEMETRY.add(f"hlo_collective_{name}_bytes", k["bytes"])
        TELEMETRY.gauge("collective_largest_reduce_bytes",
                        stats["largest_reduce_bytes"])
        TELEMETRY.gauge("collective_reduce_count",
                        stats["reduce_count"])
        TELEMETRY.gauge(f"collective_profile.{program}",
                        "+".join(f"{k}:{v['count']}x"
                                 for k, v in
                                 sorted(stats["kinds"].items()))
                        or "none")
    return stats


_external: Optional[ExternalCollectives] = None


def install_external(num_machines: int, rank: int,
                     reduce_scatter_fn: Optional[Callable] = None,
                     allgather_fn: Optional[Callable] = None) -> None:
    """Install a process-global external backend (the
    LGBM_NetworkInitWithFunctions seam, exposed via capi.py)."""
    global _external
    _external = ExternalCollectives(num_machines, rank,
                                    reduce_scatter_fn, allgather_fn)


def external() -> Optional[ExternalCollectives]:
    return _external
