"""Host-side TCP collective transport: the Linker analog.

The reference LightGBM runs ``data_parallel`` training over real
process boundaries through its socket/MPI ``Linkers``
(src/network/linkers_socket.cpp:20-78 TCP-mesh construction,
src/network/network.cpp Bruck allgather + recursive-halving
allreduce).  Our in-program collectives are implicit in shardings
(``parallel/mesh.py``) — but cross-process XLA collectives do not
exist on the CPU backend at all, so until now every multi-process
path either skipped or simulated its participants in-process.

This module is the missing layer 2: a coordinator-rendezvous TCP
transport over persistent peer sockets and length-prefixed frames,
implementing

* **Bruck-style allgather** (log2(P) rounds over byte blocks, so
  variable-length payloads — pickled candidate sets, bin shards —
  gather without padding),
* **ring allreduce** (reduce-scatter + allgather rings for integer
  payloads — order-independent, therefore EXACT; float payloads take
  the gather + rank-ordered ``np.sum(np.stack(...))`` route instead,
  which is bit-identical to ``HostCollectives``' simulated reduction
  and deterministic across runs), and
* **ring reduce-scatter** (rank ``r`` ends with chunk ``r`` of the
  world sum).

Selection rides ``Config.collective_transport``:

* ``xla``  — the existing ``jax.distributed`` + cross-process-XLA
  regime (pods),
* ``tcp``  — this transport (host-side numpy collectives),
* ``auto`` — TCP exactly when cross-process XLA collectives are
  unavailable (more than one process requested on the CPU backend),
  XLA otherwise.

Reliability contract: every communication round fires the
``transport.round`` fault seam (``peer_drop``/``peer_slow`` chaos
actions land here) and, with ``watchdog_collective_s`` armed, bounds
its socket waits by the collective deadline — a hung peer surfaces as
a retry-transient :class:`~..reliability.watchdog.StallError` with
all-thread stacks dumped, a DEAD peer (reset/EOF) as
:class:`TransportPeerLost` (a ``ConnectionError``, so the retry
machinery classifies it transient; the epoch protocol below is the
actual recovery path).  Rendezvous/mesh connects fire
``transport.connect`` and retry under the config's bounded policy.

Elastic membership (the :class:`WorldLedger` epoch protocol): the
coordinator (rank 0) owns the membership ledger.  ``epoch_tick()`` is
a control-plane barrier every participant enters between training
iterations (``Config.transport_epoch_iters``); the coordinator
collects one TICK per live member, notices dead members (their
control socket EOFs) and pending JOIN requests, and — only at this
boundary — publishes a new ledger: survivors drop the dead ranks
(degraded continuation per ``sharded_allow_degraded``), joiners are
admitted with a fresh rank plus a HANDOFF payload (caller-provided
state bytes, e.g. a pickled ``GBDT.capture_state``, plus the
r16 shard-cache manifest location), and every member rebuilds the
peer mesh for the new epoch.  Between boundaries the mesh is static,
so collectives never race a membership change.

Observability: ``collective_tcp_bytes`` / ``collective_tcp_rounds``
counters (plus per-primitive ``collective_tcp_<primitive>_*``
splits), the ``collective_tcp_round_ms`` latency histogram, and the
``collective_tcp_world`` gauge (docs/OBSERVABILITY.md).
"""
from __future__ import annotations

import collections
import pickle
import select
import socket
import struct
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..utils.log import Log

TRANSPORT_MODES = ("auto", "xla", "tcp")

# wire protocol v2: every frame is
#   magic(u16) | ver(u8) | tag(u8) | seq(u32) | len(u32) | crc32(u32)
# The CRC covers the payload (crc 0 = unprotected frame, the bench's
# overhead-measurement mode); seq is a per-peer DATA-frame sequence
# number (control frames carry 0) — a reconnect re-sends the same seq,
# and the receiver's dup-discard makes the retried round idempotent.
# A v1 peer's 8-byte header parses here as ver 0 -> the version-skew
# refusal below, not a silent desync.
PROTOCOL_VERSION = 2
_MAGIC = 0x4C54                       # "LT"
_HDR = struct.Struct(">HBBIII")
# CRC verification toggle — module-level so the distributed_exchange
# bench can measure the wire path with integrity off; everything else
# runs with it ON
_FRAME_CRC = True
# frame tags (unchanged since wire v1)
TAG_DATA = 1        # collective payload
TAG_HELLO = 2       # rendezvous: rank announces its data listener
TAG_ROSTER = 3      # coordinator -> members: the epoch-0 ledger
TAG_IDENT = 4       # mesh: connecting peer announces its rank
TAG_TICK = 5        # member -> coordinator epoch barrier entry
TAG_DIRECTIVE = 6   # coordinator -> member/joiner: ledger for the
                    # next epoch (carries the receiver's rank)
TAG_JOIN = 7        # joiner -> coordinator admission request
TAG_HANDOFF = 8     # coordinator -> joiner: state + manifest handoff

# control-plane waits (rendezvous, tick collection) fall back to this
# when no collective deadline is armed; a JOIN waits longer — it
# blocks until the running world reaches its next epoch boundary
_CTRL_TIMEOUT_S = 120.0
_JOIN_TIMEOUT_S = 600.0
# after EOF on a member's control socket the coordinator waits this
# long for the member to re-home on a fresh connection (a control-plane
# blip) before declaring it dead; bounded by the collective budget
_REHOME_GRACE_S = 2.0
# single-candidate dial timeout during failover walks / reconnects —
# a dead process refuses instantly, this only bounds a wedged host
_DIAL_TIMEOUT_S = 5.0


class TransportError(ConnectionError):
    """TCP transport failure (rendezvous, framing, protocol)."""


class TransportPeerLost(TransportError):
    """A peer died mid-collective (reset/EOF on its socket).  A
    ``ConnectionError`` subclass ON PURPOSE: ``retry.is_transient``
    classifies it retryable, and the epoch protocol (``epoch_tick``
    with ``allow_degraded``) is the recovery path that actually
    removes the corpse from the world."""

    def __init__(self, rank: Optional[int], detail: str = ""):
        self.peer_rank = rank
        who = f"peer rank {rank}" if rank is not None else "peer"
        super().__init__(
            f"{who} lost mid-collective"
            + (f": {detail}" if detail else "")
            + " — survivors reform at the next epoch boundary "
              "(epoch_tick; docs/RELIABILITY.md peer-death row)")


class FrameCorrupt(TransportError):
    """A received frame failed its CRC32 payload check.  Counted as
    ``collective_tcp_crc_errors`` and journaled (kind ``crc_error``)
    at the receive site; a corrupt DATA frame converts to a clean
    in-epoch reconnect + idempotent resend, a corrupt CONTROL frame
    stays loud."""

    def __init__(self, tag: int, peer, want: int, got: int):
        self.tag = tag
        self.peer = peer
        super().__init__(
            f"frame CRC mismatch on tag {tag} from peer {peer}: "
            f"header crc 0x{want:08x}, payload crc 0x{got:08x} — "
            "bytes were corrupted in flight (never applied; "
            "docs/RELIABILITY.md frame-integrity row)")


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------
# payload-digest fold threshold: frames below it get a plain crc32;
# larger frames get the crc32 of their 64-bit XOR word-fold (+ tail
# bytes), which runs at memory bandwidth (~30x software crc32 here) —
# that is what keeps integrity-on inside the distributed_exchange
# bench's <2% q16 wire-path budget.  The fold catches any single-bit
# flip (and any odd number of flips per bit column); uncorrelated
# multi-word corruption escapes with ~2^-64 fold-collision odds.
_CRC_FOLD_MIN = 4096


def _payload_crc(payload: bytes) -> int:
    """The 4-byte header digest over ``payload`` (see fold note
    above).  Both ends compute the same function, so the header field
    stays a plain u32 checksum."""
    if len(payload) < _CRC_FOLD_MIN:
        return zlib.crc32(payload) & 0xFFFFFFFF
    n = len(payload) & ~7
    words = np.frombuffer(payload, dtype="<u8", count=n // 8)
    fold = int(np.bitwise_xor.reduce(words))
    crc = zlib.crc32(fold.to_bytes(8, "little"))
    return zlib.crc32(payload[n:], crc) & 0xFFFFFFFF


def _send_frame(sock: socket.socket, tag: int, payload: bytes,
                seq: int = 0) -> int:
    crc = _payload_crc(payload) if _FRAME_CRC else 0
    sock.sendall(_HDR.pack(_MAGIC, PROTOCOL_VERSION, tag, seq,
                           len(payload), crc) + payload)
    return len(payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError(
                "connection closed mid-frame (peer died or was "
                "dropped)")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket,
                expect_tag: Optional[int] = None,
                peer="?") -> Tuple[int, int, bytes]:
    """Read one frame -> (tag, seq, payload).  Verifies magic, then
    protocol version (BEFORE trusting the length field — a skewed
    peer's header is not laid out like ours), then the payload CRC."""
    magic, ver, tag, seq, n, crc = _HDR.unpack(
        _recv_exact(sock, _HDR.size))
    if magic != _MAGIC:
        raise TransportError(
            f"bad frame magic 0x{magic:04x} (expected 0x{_MAGIC:04x}) "
            "— not a lightgbm_tpu transport peer, or a desynchronized "
            "stream")
    if ver != PROTOCOL_VERSION:
        raise TransportError(
            f"transport protocol version mismatch: peer {peer} speaks "
            f"v{ver}, this process speaks v{PROTOCOL_VERSION} — "
            "upgrade skew across the world (a rolling restart must "
            "finish before mixed versions exchange frames; restart "
            "the older participant on the current build)")
    if expect_tag is not None and tag != expect_tag:
        raise TransportError(
            f"unexpected frame tag {tag} (expected {expect_tag})")
    payload = _recv_exact(sock, n)
    if _FRAME_CRC and crc != 0:
        got = _payload_crc(payload)
        if got != crc:
            from ..telemetry import TELEMETRY
            TELEMETRY.add("collective_tcp_crc_errors", 1)
            TELEMETRY.journal.emit(
                "crc_error", seam="transport.round", tag=tag,
                peer=str(peer), seq=seq, nbytes=n)
            raise FrameCorrupt(tag, peer, crc, got)
    return tag, seq, payload


def _refuse_skew(payload: dict, who: str) -> None:
    """Handshake-level (HELLO/IDENT/TICK) protocol-version refusal —
    the frame layer already rejects skewed headers; this catches a
    same-header build whose PAYLOAD contract moved."""
    ver = int(payload.get("ver", 0))
    if ver != PROTOCOL_VERSION:
        raise TransportError(
            f"{who} announced transport protocol v{ver}, this process "
            f"speaks v{PROTOCOL_VERSION} — upgrade skew: finish the "
            "rolling restart (restart the older participant) before "
            "it joins the world")


def _obj_frame(obj) -> bytes:
    return pickle.dumps(obj, protocol=4)


# ---------------------------------------------------------------------------
# world ledger
# ---------------------------------------------------------------------------
class WorldLedger:
    """Epoch-versioned membership: ``{rank: (host, data_port)}`` plus
    the epoch counter.  Immutable — ``degrade``/``admit`` return the
    NEXT epoch's ledger, so a collective in flight can never observe a
    half-applied membership change."""

    __slots__ = ("members", "epoch", "next_rank")

    def __init__(self, members: Dict[int, Tuple[str, int]],
                 epoch: int = 0, next_rank: Optional[int] = None):
        self.members = {int(r): (str(h), int(p))
                        for r, (h, p) in members.items()}
        self.epoch = int(epoch)
        # the high-water rank: survives degrades, so a retired rank is
        # never handed to a later joiner
        floor = (max(self.members) + 1) if self.members else 0
        self.next_rank = max(floor, int(next_rank or 0))

    @property
    def world_size(self) -> int:
        return len(self.members)

    def ranks(self) -> List[int]:
        return sorted(self.members)

    def degrade(self, dead: List[int]) -> "WorldLedger":
        """Next epoch's ledger with ``dead`` ranks retired.  Retired
        ranks are never reused — a recovered participant re-joins
        under a FRESH rank, so a stale frame from the corpse can
        never be attributed to its successor."""
        dead = set(int(d) for d in dead)
        left = {r: a for r, a in self.members.items() if r not in dead}
        if not left:
            raise TransportError(
                "ledger degrade would leave an empty world")
        return WorldLedger(left, self.epoch + 1,
                           next_rank=self.next_rank)

    def admit(self, addrs: List[Tuple[str, int]]
              ) -> Tuple["WorldLedger", List[int]]:
        """Next epoch's ledger with one fresh rank per joiner address;
        returns (ledger, assigned ranks)."""
        nxt = self.next_rank
        members = dict(self.members)
        assigned = []
        for a in addrs:
            members[nxt] = (str(a[0]), int(a[1]))
            assigned.append(nxt)
            nxt += 1
        return WorldLedger(members, self.epoch + 1,
                           next_rank=nxt), assigned

    def to_state(self) -> dict:
        return {"epoch": self.epoch, "next_rank": self.next_rank,
                "members": {str(r): list(a)
                            for r, a in self.members.items()}}

    @classmethod
    def from_state(cls, state: dict) -> "WorldLedger":
        return cls({int(r): (a[0], int(a[1]))
                    for r, a in state["members"].items()},
                   epoch=int(state["epoch"]),
                   next_rank=int(state.get("next_rank", 0)))

    def __repr__(self):
        return (f"WorldLedger(epoch={self.epoch}, "
                f"members={self.members})")


# ---------------------------------------------------------------------------
# the transport
# ---------------------------------------------------------------------------
class TcpTransport:
    """Persistent-socket TCP collective transport over one
    :class:`WorldLedger` epoch.  Create with :meth:`create` (founding
    members) or :meth:`join` (elastic re-join into a running world).
    """

    def __init__(self):
        self.rank: int = 0
        self.ledger: WorldLedger = WorldLedger({0: ("localhost", 0)})
        self.epoch_every: int = 1
        # fleet trace id (docs/OBSERVABILITY.md, Tracing): minted by
        # the coordinator at rendezvous and distributed with the
        # roster/directives, so collective rounds, epoch ticks and
        # join/handoff exchanges on DIFFERENT hosts tag their spans
        # and journal events with one shared trace
        self.trace_id: str = ""
        # handoff metadata published to joiners (e.g. the shard-cache
        # manifest directory); coordinator-side, caller-settable
        self.handoff_meta: dict = {}
        # a joiner's received handoff: {"meta": dict, "state": bytes}
        self.handoff: Optional[dict] = None
        self._ctrl: Dict[int, socket.socket] = {}   # coordinator only
        self._coord_sock: Optional[socket.socket] = None  # members
        self._ctrl_listener: Optional[socket.socket] = None
        self._data_listener: Optional[socket.socket] = None
        self._peers: Dict[int, socket.socket] = {}
        self._my_addr: Tuple[str, int] = ("localhost", 0)
        self._retry_policy = None
        self._lock = threading.Lock()
        self._closed = False
        # the coordinator is ALWAYS the lowest rank in the ledger
        # (founding coordinator is rank 0; joiners only ever get fresh
        # higher ranks) — so the successor after a coordinator death
        # is named by the replicated ledger itself, no election
        self._coord_rank: int = 0
        # reconnect dials per blip before TransportPeerLost/degrade
        self.reconnect_retries: int = 3
        # per-peer DATA-frame sequence state (reset at epoch flips,
        # KEPT across in-epoch reconnects — that continuity is what
        # makes a re-sent round idempotent)
        self._send_seq: Dict[int, int] = {}
        self._recv_seq: Dict[int, int] = {}
        # the last few DATA frames sent per peer, for resend after a
        # reconnect (a sender runs at most one round ahead of a
        # receiver, so a short log always covers the unacked window)
        self._sent_log: Dict[int, collections.deque] = {}
        # JOIN connections that arrived on the data listener outside a
        # tick (a joiner walking the ledger) — served at the next tick
        self._pending_joins: List[Tuple[socket.socket, dict]] = []

    # -- identity -----------------------------------------------------
    @property
    def world_size(self) -> int:
        return self.ledger.world_size

    @property
    def epoch(self) -> int:
        return self.ledger.epoch

    @property
    def is_coordinator(self) -> bool:
        return self.rank == self._coord_rank

    # -- construction -------------------------------------------------
    @classmethod
    def create(cls, coordinator_address: str, num_processes: int,
               process_id: int, config=None,
               bind_host: Optional[str] = None) -> "TcpTransport":
        """Founding rendezvous (the Linkers ctor / mlist.txt role of
        ``coordinator_address``): rank 0 listens there, every other
        rank connects, announces its data listener, and receives the
        epoch-0 roster; then the full peer mesh is built."""
        if num_processes < 1:
            raise ValueError(f"num_processes must be >= 1, got "
                             f"{num_processes}")
        if not (0 <= process_id < num_processes):
            raise ValueError(f"process_id {process_id} outside world "
                             f"of {num_processes}")
        self = cls()
        self.rank = int(process_id)
        self._init_policy(config)
        host, port = _parse_addr(coordinator_address)
        my_host = bind_host or host
        self._data_listener = _listen(my_host, 0)
        self._my_addr = (my_host, self._data_listener.getsockname()[1])

        if self.rank == 0:
            self._ctrl_listener = _listen(host, port)
            members = {0: self._my_addr}
            for _ in range(num_processes - 1):
                conn = self._accept(self._ctrl_listener)
                _, _, payload = _recv_frame(conn, TAG_HELLO)
                hello = pickle.loads(payload)
                _refuse_skew(hello, "rendezvous HELLO from rank "
                             f"{hello.get('rank')}")
                r = int(hello["rank"])
                if r in members or r in self._ctrl:
                    raise TransportError(
                        f"duplicate rendezvous rank {r}")
                members[r] = (hello["host"], int(hello["port"]))
                self._ctrl[r] = conn
            if sorted(members) != list(range(num_processes)):
                raise TransportError(
                    f"rendezvous ranks {sorted(members)} do not tile "
                    f"[0, {num_processes})")
            self.ledger = WorldLedger(members, epoch=0)
            from ..telemetry import new_trace_id
            self.trace_id = new_trace_id()
            state = self.ledger.to_state()
            state["trace"] = self.trace_id
            roster = _obj_frame(state)
            for r, conn in self._ctrl.items():
                _send_frame(conn, TAG_ROSTER, roster)
        else:
            self._coord_sock = self._connect_retry(host, port)
            _send_frame(self._coord_sock, TAG_HELLO, _obj_frame(
                {"rank": self.rank, "host": self._my_addr[0],
                 "port": self._my_addr[1],
                 "ver": PROTOCOL_VERSION}))
            self._coord_sock.settimeout(_CTRL_TIMEOUT_S)
            _, _, payload = _recv_frame(self._coord_sock, TAG_ROSTER,
                                        peer="coordinator")
            state = pickle.loads(payload)
            self.trace_id = str(state.get("trace", ""))
            self.ledger = WorldLedger.from_state(state)
        self._coord_rank = min(self.ledger.members)
        self._build_mesh()
        self._note_world()
        Log.info(f"tcp transport up: rank {self.rank} of "
                 f"{self.world_size} (epoch {self.epoch}, "
                 f"coordinator {coordinator_address})")
        return self

    @classmethod
    def join(cls, coordinator_address: str, config=None,
             bind_host: Optional[str] = None,
             timeout_s: float = _JOIN_TIMEOUT_S,
             ledger=None) -> "TcpTransport":
        """Elastic re-join: connect to a RUNNING world's coordinator,
        wait for admission at its next epoch boundary, receive the
        new ledger + the handoff payload (``self.handoff``), and build
        the mesh as a fresh rank.

        ``ledger`` (a :class:`WorldLedger` or its ``to_state()`` dict,
        e.g. from a checkpoint or a prior directive) arms the STALE
        COORDINATOR WALK: if ``coordinator_address`` refuses, the
        joiner dials the ledger's members in ascending rank order —
        the lowest live rank IS the coordinator (failover invariant),
        so the first successful connect lands the JOIN at the right
        door (the coordinator drains its data listener every tick)."""
        self = cls()
        self._init_policy(config)
        host, port = _parse_addr(coordinator_address)
        my_host = bind_host or host
        self._data_listener = _listen(my_host, 0)
        self._my_addr = (my_host, self._data_listener.getsockname()[1])
        led = None
        if ledger is not None:
            led = ledger if isinstance(ledger, WorldLedger) \
                else WorldLedger.from_state(dict(ledger))
        if led is None:
            self._coord_sock = self._connect_retry(host, port)
        else:
            candidates = [("coordinator", (host, port))] + \
                [(f"ledger rank {r}", led.members[r])
                 for r in led.ranks()]
            last: Optional[BaseException] = None
            for who, (h, p) in candidates:
                try:
                    self._coord_sock = _dial(h, int(p))
                    break
                except (ConnectionError, OSError, socket.timeout) as e:
                    last = e
                    Log.warning(
                        f"join: {who} at {h}:{p} unreachable ({e}) — "
                        "walking the replicated ledger for the live "
                        "coordinator")
            else:
                raise TransportError(
                    f"join: no reachable coordinator among "
                    f"{len(candidates)} candidate(s) — the whole "
                    f"world is gone? (last: {last})")
        _send_frame(self._coord_sock, TAG_JOIN, _obj_frame(
            {"host": self._my_addr[0], "port": self._my_addr[1],
             "ver": PROTOCOL_VERSION}))
        self._coord_sock.settimeout(float(timeout_s))
        _, _, payload = _recv_frame(self._coord_sock, TAG_DIRECTIVE,
                                    peer="coordinator")
        directive = pickle.loads(payload)
        self.rank = int(directive["you"])
        self.trace_id = str(directive.get("trace", ""))
        self.ledger = WorldLedger.from_state(directive["ledger"])
        self._coord_rank = min(self.ledger.members)
        if directive.get("hmeta"):
            self.handoff_meta = dict(directive["hmeta"])
        _, _, hpayload = _recv_frame(self._coord_sock, TAG_HANDOFF,
                                     peer="coordinator")
        self.handoff = pickle.loads(hpayload)
        self._coord_sock.settimeout(_CTRL_TIMEOUT_S)
        self._build_mesh()
        self._note_world()
        from ..telemetry import TELEMETRY
        TELEMETRY.journal.emit(
            "membership_join", seam="transport.connect",
            rank=self.rank, epoch=self.epoch, trace=self.trace_id,
            world=self.world_size)
        Log.info(f"tcp transport joined: rank {self.rank} of "
                 f"{self.world_size} at epoch {self.epoch}")
        return self

    def _init_policy(self, config) -> None:
        from ..reliability.retry import RetryPolicy
        if config is None:
            self._retry_policy = RetryPolicy()
        else:
            self._retry_policy = RetryPolicy.from_config(config)
            self._retry_policy.budget_s = \
                float(getattr(config, "time_out", 2)) * 60.0
            self.epoch_every = max(1, int(getattr(
                config, "transport_epoch_iters", 1) or 1))
            self.reconnect_retries = max(0, int(getattr(
                config, "transport_reconnect_retries", 3)))

    def _connect_retry(self, host: str, port: int) -> socket.socket:
        """Coordinator/peer connect under the bounded retry policy —
        the ``transport.connect`` seam (a coordinator still starting
        or a DNS race is transient, exactly like ``distributed.init``).
        """
        from ..reliability.faults import FAULTS
        from ..reliability.retry import retry_call

        def _connect():
            from ..reliability.faults import TransportChaos
            try:
                FAULTS.fault_point("transport.connect")
            except TransportChaos as e:
                # a network-shaped chaos action at connect time IS a
                # failed dial: transient, retried under the policy
                raise ConnectionResetError(str(e)) from e
            s = socket.create_connection((host, port),
                                         timeout=_CTRL_TIMEOUT_S)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return s

        return retry_call(_connect, seam="transport.connect",
                          policy=self._retry_policy)

    def _accept(self, listener: socket.socket) -> socket.socket:
        listener.settimeout(_CTRL_TIMEOUT_S)
        conn, _ = listener.accept()
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        conn.settimeout(_CTRL_TIMEOUT_S)
        return conn

    def _build_mesh(self) -> None:
        """(Re)build the persistent peer mesh for the CURRENT ledger:
        for every pair the HIGHER rank connects to the lower rank's
        data listener and identifies itself — a deterministic
        connection direction, so no pair ever cross-connects."""
        for s in self._peers.values():
            _quiet_close(s)
        self._peers = {}
        # fresh epoch, fresh sequence space (in-epoch reconnects KEEP
        # these — see _reconnect_peer)
        self._send_seq = {}
        self._recv_seq = {}
        self._sent_log = {}
        lower = [r for r in self.ledger.ranks() if r < self.rank]
        higher = [r for r in self.ledger.ranks() if r > self.rank]
        # connect up to every lower rank...
        for r in lower:
            h, p = self.ledger.members[r]
            s = self._connect_retry(h, p)
            _send_frame(s, TAG_IDENT, _obj_frame(
                {"rank": self.rank, "epoch": self.epoch,
                 "ver": PROTOCOL_VERSION}))
            self._peers[r] = s
        # ...and accept every higher rank (any order)
        expect = set(higher)
        while expect:
            conn = self._accept(self._data_listener)
            try:
                tag, _, payload = _recv_frame(conn)
            except TransportError:
                _quiet_close(conn)
                continue
            if tag == TAG_JOIN:
                # a ledger-walking joiner knocked during a reform —
                # park it for the next epoch tick
                self._pending_joins.append((conn,
                                            pickle.loads(payload)))
                continue
            if tag != TAG_IDENT:
                _quiet_close(conn)
                continue
            ident = pickle.loads(payload)
            _refuse_skew(ident, "mesh IDENT from rank "
                         f"{ident.get('rank')}")
            r = int(ident["rank"])
            if int(ident.get("epoch", self.epoch)) != self.epoch:
                # a corpse from a previous epoch racing the reform —
                # refuse it; the live peer reconnects with the right
                # epoch
                _quiet_close(conn)
                continue
            if r not in expect:
                raise TransportError(
                    f"unexpected mesh peer rank {r} "
                    f"(expected one of {sorted(expect)})")
            if ident.get("reconnect"):
                # a reconnect dial raced the epoch flip: complete its
                # ack handshake so the dialer unblocks
                _send_frame(conn, TAG_IDENT, _obj_frame(
                    {"rank": self.rank, "epoch": self.epoch,
                     "ver": PROTOCOL_VERSION,
                     "ack": self._recv_seq.get(r, 0)}))
            expect.discard(r)
            self._peers[r] = conn

    def _note_world(self) -> None:
        from ..telemetry import TELEMETRY
        if TELEMETRY.on:
            TELEMETRY.gauge("collective_tcp_world", self.world_size)
            TELEMETRY.gauge("collective_tcp_epoch", self.epoch)

    # -- round plumbing ----------------------------------------------
    def _peer(self, rank: int) -> socket.socket:
        try:
            return self._peers[rank]
        except KeyError:
            raise TransportPeerLost(
                rank, "no socket in the current epoch's mesh") \
                from None

    def _round(self, primitive: str,
               sends: List[Tuple[int, bytes]],
               recvs: List[int]) -> Dict[int, bytes]:
        """One communication round: send each payload, receive one
        DATA frame per listed peer.  Fires the ``transport.round``
        fault seam, bounds every socket wait by the armed collective
        deadline (hung peer -> ``StallError``), classifies dead peers
        as ``TransportPeerLost``, and lands bytes/rounds/latency in
        the ``collective_tcp_*`` telemetry family.  In spans mode the
        round records a ``transport_round`` span tagged with the
        active trace context (falling back to the fleet trace id the
        coordinator distributed at rendezvous), so the SAME round's
        spans on every host share one trace id in the merged
        timeline."""
        from ..telemetry import TELEMETRY as tm
        if not tm.spans_on:
            return self._round_inner(primitive, sends, recvs)
        from ..telemetry import current_trace, new_span_id
        ctx = current_trace()
        attrs = {"primitive": primitive, "epoch": self.epoch,
                 "span": new_span_id()}
        trace_id = ctx[0] if ctx is not None else self.trace_id
        if trace_id:
            attrs["trace"] = trace_id
        with tm.span("transport_round", **attrs):
            return self._round_inner(primitive, sends, recvs)

    def _round_inner(self, primitive: str,
                     sends: List[Tuple[int, bytes]],
                     recvs: List[int]) -> Dict[int, bytes]:
        from ..reliability import watchdog as _watchdog
        from ..reliability.faults import FAULTS, TransportChaos
        from ..telemetry import TELEMETRY as tm

        chaos: Optional[TransportChaos] = None
        try:
            FAULTS.fault_point("transport.round")
        except TransportChaos as e:
            chaos = e          # applied to real frames below
        except ConnectionError as e:
            # an injected peer_drop IS a reset socket with no live
            # endpoint to re-dial: classify it the way a real one
            # classifies
            raise TransportPeerLost(None, str(e)) from e
        deadline = _watchdog.deadline("collective")
        budget = deadline if deadline > 0 else _CTRL_TIMEOUT_S
        t0 = time.perf_counter()
        # sequence numbers are assigned ONCE per round — a reconnect
        # re-sends the SAME seq, and the receiver's dup-discard makes
        # the retried round idempotent (a chunk is never double-added)
        seq_of: Dict[int, int] = {}
        for r, _p in sends:
            seq_of[r] = self._send_seq.get(r, 0) + 1
            self._send_seq[r] = seq_of[r]
        corrupt = chaos is not None and chaos.action == "corrupt"
        dup = chaos is not None and chaos.action == "dup"
        if chaos is not None and chaos.action == "partition":
            self._chaos_partition(recvs or [r for r, _ in sends],
                                  chaos.duration_ms)
        out: Dict[int, bytes] = {}
        sent_ok: set = set()
        blips = 0
        while True:
            # sends ride a helper thread so a same-peer exchange can
            # never deadlock on full TCP buffers (both sides blocked
            # in sendall)
            send_err: List[Tuple[Optional[int], BaseException]] = []
            pending = [(r, p) for r, p in sends if r not in sent_ok]

            def _do_sends(pending=pending, send_err=send_err,
                          corrupt=corrupt, dup=dup):
                for r, payload in pending:
                    try:
                        if dup:
                            self._replay_last(r, budget)
                            dup = False
                        self._send_data(r, payload, seq_of[r], budget,
                                        corrupt=corrupt)
                        corrupt = False
                        sent_ok.add(r)
                    except BaseException as e:  # noqa: BLE001
                        send_err.append((r, e))
                        return

            sender = threading.Thread(target=_do_sends, daemon=True)
            sender.start()
            blip: Optional[Tuple[Optional[int], BaseException]] = None
            peer = None
            try:
                for peer in recvs:
                    if peer in out:
                        continue
                    out[peer] = self._recv_data(peer, budget)
            except socket.timeout:
                elapsed = time.perf_counter() - t0
                if deadline > 0:
                    _watchdog._record_stall("host_collective",
                                            "transport.round",
                                            deadline, elapsed)
                    raise _watchdog.StallError(
                        phase="host_collective",
                        seam="transport.round",
                        deadline_s=deadline,
                        elapsed_s=elapsed) from None
                raise TransportPeerLost(
                    peer, f"no frame within {budget:g}s") from None
            except (ConnectionError, OSError, TransportError) as e:
                if isinstance(e, TransportPeerLost):
                    raise
                blip = (peer, e)
            sender.join(timeout=budget)
            for r, e in send_err:
                if isinstance(e, socket.timeout) and deadline > 0:
                    elapsed = time.perf_counter() - t0
                    _watchdog._record_stall("host_collective",
                                            "transport.round",
                                            deadline, elapsed)
                    raise _watchdog.StallError(
                        phase="host_collective",
                        seam="transport.round",
                        deadline_s=deadline, elapsed_s=elapsed)
                if isinstance(e, (ConnectionError, OSError,
                                  TransportError)) \
                        and not isinstance(e, TransportPeerLost):
                    if blip is None:
                        blip = (r, e)
                else:
                    raise e
            # chaos one-shots are consumed by the first attempt; a
            # retried attempt re-sends the TRUE frame
            corrupt = dup = False
            if blip is None:
                break
            rank, cause = blip
            blips += 1
            if rank is None or blips > self.reconnect_retries:
                raise TransportPeerLost(rank, str(cause)) from cause
            # a reset/EOF/corrupt frame mid-round is a transient blip
            # until reconnection exhausts — reconnect within the
            # epoch, resync by ack, resend what the peer never applied
            self._reconnect_peer(rank, budget, cause)
        nbytes = sum(len(out[p]) for p in out) \
            + sum(len(p) for _, p in sends)
        if tm.on:
            tm.add("collective_tcp_bytes", nbytes)
            tm.add("collective_tcp_rounds", 1)
            tm.add(f"collective_tcp_{primitive}_bytes", nbytes)
            tm.add(f"collective_tcp_{primitive}_rounds", 1)
            tm.observe("collective_tcp_round_ms",
                       (time.perf_counter() - t0) * 1e3)
        return out

    # -- data-plane frames, reconnection ------------------------------
    def _send_data(self, rank: int, payload: bytes, seq: int,
                   budget: float, corrupt: bool = False) -> None:
        """One DATA frame to ``rank``, logged for post-reconnect
        resend.  ``corrupt`` (chaos) flips one payload bit IN FLIGHT —
        the header CRC still covers the true bytes, so the receiver
        must detect it."""
        # log BEFORE touching the socket: a dead socket must not keep
        # this frame out of the post-reconnect resend window
        log = self._sent_log.setdefault(
            rank, collections.deque(maxlen=4))
        if not log or log[-1][0] != seq:
            log.append((seq, payload))
        s = self._peer(rank)
        s.settimeout(budget)
        if corrupt and payload:
            crc = _payload_crc(payload) if _FRAME_CRC else 0
            bad = bytearray(payload)
            bad[0] ^= 0x01
            s.sendall(_HDR.pack(_MAGIC, PROTOCOL_VERSION, TAG_DATA,
                                seq, len(bad), crc) + bytes(bad))
            return
        _send_frame(s, TAG_DATA, payload, seq=seq)

    def _recv_data(self, rank: int, budget: float) -> bytes:
        """One in-sequence DATA payload from ``rank``: replayed or
        duplicated frames (seq <= last applied) are discarded, a
        sequence GAP is loud — it means a frame this process never saw
        was silently skipped."""
        from ..telemetry import TELEMETRY
        last = self._recv_seq.get(rank, 0)
        while True:
            s = self._peer(rank)
            s.settimeout(budget)
            _, seq, payload = _recv_frame(s, TAG_DATA, peer=rank)
            if seq <= last:
                TELEMETRY.add("collective_tcp_dup_frames", 1)
                continue
            if seq != last + 1:
                raise TransportError(
                    f"DATA sequence gap from rank {rank}: got seq "
                    f"{seq}, expected {last + 1} — a frame was lost "
                    "without a reconnect resync")
            self._recv_seq[rank] = seq
            return payload

    def _replay_last(self, rank: int, budget: float) -> None:
        """Chaos ``dup``: re-send the most recent DATA frame to
        ``rank`` with its ORIGINAL seq — the receiver's dup-discard
        must drop it."""
        log = self._sent_log.get(rank)
        if not log:
            return
        seq, payload = log[-1]
        s = self._peer(rank)
        s.settimeout(budget)
        _send_frame(s, TAG_DATA, payload, seq=seq)

    def _chaos_partition(self, victims: List[int], ms: int) -> None:
        """Chaos ``partition:<ms>``: sever the link to the first
        listed peer in BOTH directions (close our end — the peer sees
        FIN/RST), sit out the outage, then proceed into the round;
        reconnection heals the link and the resynced round completes
        bit-exact."""
        for v in victims:
            s = self._peers.get(v)
            if s is not None:
                Log.debug(f"chaos partition: severing link to rank "
                          f"{v} for {ms} ms")
                _quiet_close(s)
                break
        time.sleep(max(0, int(ms)) / 1e3)

    def _reconnect_peer(self, rank: int, budget: float,
                        cause: BaseException) -> None:
        """Heal the link to ``rank`` WITHIN the epoch: the higher rank
        dials the lower rank's data listener (same direction as the
        mesh build) under bounded backoff; an IDENT{reconnect} ack
        exchange tells each side the other's last applied seq, and any
        unacked frame is re-sent from the sent log.  Exhaustion — and
        only exhaustion — converts to :class:`TransportPeerLost`."""
        from ..telemetry import TELEMETRY
        old = self._peers.pop(rank, None)
        if old is not None:
            _quiet_close(old)
        if rank not in self.ledger.members:
            raise TransportPeerLost(
                rank, f"not in the epoch-{self.epoch} ledger") \
                from cause
        deadline_at = time.monotonic() + budget
        delay = 0.05
        last: BaseException = cause
        for attempt in range(max(1, self.reconnect_retries)):
            remain = deadline_at - time.monotonic()
            if remain <= 0:
                break
            try:
                if self.rank > rank:
                    conn = self._dial_reconnect(rank, remain)
                else:
                    conn = self._accept_reconnect(rank, remain)
                self._peers[rank] = conn
                TELEMETRY.add("collective_tcp_reconnects", 1)
                TELEMETRY.journal.emit(
                    "reconnect", seam="transport.round", peer=rank,
                    rank=self.rank, epoch=self.epoch,
                    trace=self.trace_id, attempt=attempt + 1,
                    cause=str(cause)[:160])
                Log.warning(
                    f"tcp transport: link to rank {rank} reconnected "
                    f"within epoch {self.epoch} (attempt "
                    f"{attempt + 1}; cause: {cause})")
                return
            except (ConnectionError, OSError, socket.timeout,
                    TransportError) as e:
                last = e
                time.sleep(min(delay, max(0.0, deadline_at
                                          - time.monotonic())))
                delay = min(delay * 2, 1.0)
        raise TransportPeerLost(
            rank, f"reconnect exhausted after "
            f"{max(1, self.reconnect_retries)} attempt(s) "
            f"(last: {last})") from cause

    def _dial_reconnect(self, rank: int,
                        remain: float) -> socket.socket:
        h, p = self.ledger.members[rank]
        s = _dial(h, p, timeout=min(_DIAL_TIMEOUT_S, remain))
        try:
            s.settimeout(max(0.1, remain))
            _send_frame(s, TAG_IDENT, _obj_frame(
                {"rank": self.rank, "epoch": self.epoch,
                 "ver": PROTOCOL_VERSION, "reconnect": True,
                 "ack": self._recv_seq.get(rank, 0)}))
            _, _, payload = _recv_frame(s, TAG_IDENT, peer=rank)
            reply = pickle.loads(payload)
            if int(reply.get("epoch", -1)) != self.epoch:
                raise TransportError(
                    f"reconnect ack from rank {rank} is for epoch "
                    f"{reply.get('epoch')}, not {self.epoch}")
            self._resync(rank, s, int(reply.get("ack", 0)))
            return s
        except BaseException:
            _quiet_close(s)
            raise

    def _accept_reconnect(self, rank: int,
                          remain: float) -> socket.socket:
        """Lower-rank side of a reconnect: accept on the data listener
        until the expected peer's IDENT{reconnect} arrives (other
        valid reconnects are adopted in passing; stale epochs and
        stray frames are refused).  Each attempt's wait is capped at
        the re-home grace, NOT the full collective budget — a live
        blipped peer redials within milliseconds, so a silent listener
        means the peer is dead and waiting the whole budget would turn
        every peer death into a near-hang for its lower-rank
        survivors."""
        deadline_at = time.monotonic() + min(remain, _REHOME_GRACE_S)
        while True:
            left = deadline_at - time.monotonic()
            if left <= 0:
                raise socket.timeout(
                    f"no reconnect dial from rank {rank} within "
                    f"{remain:g}s")
            self._data_listener.settimeout(min(0.5, left))
            try:
                conn, _ = self._data_listener.accept()
            except (socket.timeout, BlockingIOError):
                continue
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(min(_DIAL_TIMEOUT_S, max(0.1, left)))
            try:
                tag, _, payload = _recv_frame(conn)
                obj = pickle.loads(payload)
            except (ConnectionError, OSError, socket.timeout,
                    TransportError):
                _quiet_close(conn)
                continue
            if tag == TAG_JOIN:
                self._pending_joins.append((conn, obj))
                continue
            if tag != TAG_IDENT \
                    or int(obj.get("epoch", -1)) != self.epoch \
                    or not obj.get("reconnect"):
                _quiet_close(conn)
                continue
            _refuse_skew(obj, "reconnect IDENT from rank "
                         f"{obj.get('rank')}")
            r = int(obj["rank"])
            try:
                _send_frame(conn, TAG_IDENT, _obj_frame(
                    {"rank": self.rank, "epoch": self.epoch,
                     "ver": PROTOCOL_VERSION,
                     "ack": self._recv_seq.get(r, 0)}))
                self._resync(r, conn, int(obj.get("ack", 0)))
            except (ConnectionError, OSError, socket.timeout,
                    TransportError):
                # a failed handshake must CLOSE the conn — a leaked
                # half-healed socket leaves the dialer believing the
                # link is up and blocking on a frame that never comes
                _quiet_close(conn)
                continue
            if r == rank:
                return conn
            # a concurrent blip on another link: adopt its healed
            # socket and keep waiting for the one we came for
            old = self._peers.pop(r, None)
            if old is not None:
                _quiet_close(old)
            self._peers[r] = conn

    def _resync(self, rank: int, sock: socket.socket,
                their_ack: int) -> None:
        """Post-reconnect resend: every logged frame the peer never
        applied (seq > their ack) goes again, in order, with its
        ORIGINAL seq.  An ack below the log's reach is loud — the
        frames to replay are gone."""
        sent = self._send_seq.get(rank, 0)
        if sent <= their_ack:
            return
        log = self._sent_log.get(rank) or ()
        replay = [(q, p) for q, p in log if q > their_ack]
        if not replay or replay[0][0] != their_ack + 1:
            raise TransportError(
                f"reconnect resync with rank {rank} impossible: peer "
                f"acked seq {their_ack}, sent log covers "
                f"{[q for q, _ in log]} — unacked frames fell out of "
                "the resend window")
        for q, p in replay:
            _send_frame(sock, TAG_DATA, p, seq=q)

    # -- collectives --------------------------------------------------
    def allgather_bytes(self, payload: bytes,
                        primitive: str = "allgather") -> List[bytes]:
        """Bruck-style allgather over byte blocks (log2(P) rounds;
        reference network.cpp BruckAllgather): returns the P payloads
        in RANK ORDER — the deterministic merge order every consumer
        (candidate merge, histogram sum) relies on."""
        P = self.world_size
        if P == 1:
            return [payload]
        ranks = self.ledger.ranks()
        pos = ranks.index(self.rank)
        have: List[bytes] = [payload]     # have[i] = block of
        m = 1                             # ranks[(pos + i) % P]
        while m < P:
            cnt = min(m, P - m)
            dst = ranks[(pos - m) % P]
            src = ranks[(pos + m) % P]
            got = self._round(primitive,
                              [(dst, _obj_frame(have[:cnt]))], [src])
            have.extend(pickle.loads(got[src]))
            m += cnt
        out: List[bytes] = [b""] * P
        for i, blk in enumerate(have[:P]):
            out[(pos + i) % P] = blk
        return out

    def allgather_obj(self, obj, primitive: str = "allgather") -> List:
        """Allgather arbitrary (picklable) objects; rank order."""
        return [pickle.loads(b) for b in
                self.allgather_bytes(_obj_frame(obj), primitive)]

    def allgather(self, arr: np.ndarray) -> np.ndarray:
        """Allgather equal-shape arrays -> the stacked (P, \\*shape)
        array (the ``multihost_utils.process_allgather`` contract
        ``distributed._allgather`` expects)."""
        arr = np.ascontiguousarray(arr)
        parts = self.allgather_obj(arr)
        shapes = {p.shape for p in parts}
        if len(shapes) != 1:
            raise TransportError(
                f"allgather shape mismatch across ranks: {shapes} — "
                "use allgather_obj for variable-size payloads")
        return np.stack(parts, axis=0)

    def allreduce_sum(self, arr: np.ndarray,
                      primitive: str = "allreduce") -> np.ndarray:
        """World sum on every rank.  Integer payloads ride the ring
        (reduce-scatter + allgather — exact in any order); floats
        gather and sum in rank order, bit-identical to
        ``HostCollectives.simulate_allreduce``'s
        ``np.sum(np.stack(parts), axis=0)``."""
        arr = np.ascontiguousarray(arr)
        P = self.world_size
        if P == 1:
            return arr.copy()
        if arr.dtype.kind not in "iu":
            parts = self.allgather_obj(arr, primitive=primitive)
            return np.sum(np.stack(parts, axis=0), axis=0)
        flat = arr.reshape(-1)
        pad = (-len(flat)) % P
        if pad:
            flat = np.concatenate(
                [flat, np.zeros(pad, dtype=flat.dtype)])
        chunks = [c.copy() for c in np.split(flat, P)]
        ranks = self.ledger.ranks()
        pos = ranks.index(self.rank)
        right = ranks[(pos + 1) % P]
        left = ranks[(pos - 1) % P]
        # ring chunks are equal-length and of a dtype both ends
        # already know, so they cross as RAW bytes — no pickle copy,
        # and the wire carries exactly chunk.nbytes per hop (the
        # bench's q16/q8 payload-reduction gates measure these frames)
        dt = flat.dtype
        # ring reduce-scatter: chunk c starts at position c+1 and
        # accumulates rightward until it lands, fully summed, at
        # position c
        for s in range(P - 1):
            send_i = (pos - s - 1) % P
            recv_i = (pos - s - 2) % P
            got = self._round(
                primitive,
                [(right, chunks[send_i].tobytes())], [left])
            chunks[recv_i] = chunks[recv_i] \
                + np.frombuffer(got[left], dtype=dt)
        # ring allgather of the summed chunks
        for s in range(P - 1):
            send_i = (pos - s) % P
            recv_i = (pos - s - 1) % P
            got = self._round(
                primitive,
                [(right, chunks[send_i].tobytes())], [left])
            chunks[recv_i] = np.frombuffer(got[left], dtype=dt)
        out = np.concatenate(chunks)
        if pad:
            out = out[:-pad]
        return out.reshape(arr.shape)

    def reduce_scatter(self, arr: np.ndarray,
                       axis: int = 0) -> np.ndarray:
        """Ring reduce-scatter: rank r returns chunk r (``np.
        array_split`` tiling along ``axis``) of the world sum — the
        reference data-parallel histogram exchange shape
        (data_parallel_tree_learner.cpp:117-246)."""
        arr = np.ascontiguousarray(arr)
        P = self.world_size
        chunks = [np.ascontiguousarray(c)
                  for c in np.array_split(arr, P, axis=axis)]
        if P == 1:
            return chunks[0]
        ranks = self.ledger.ranks()
        pos = ranks.index(self.rank)
        right = ranks[(pos + 1) % P]
        left = ranks[(pos - 1) % P]
        acc = [c.copy() for c in chunks]
        for s in range(P - 1):
            send_i = (pos - s - 1) % P
            recv_i = (pos - s - 2) % P
            got = self._round(
                "reduce_scatter",
                [(right, _obj_frame(acc[send_i]))], [left])
            acc[recv_i] = acc[recv_i] + pickle.loads(got[left])
        return acc[pos]

    def pmax(self, arr: np.ndarray,
             primitive: str = "allreduce") -> np.ndarray:
        """Elementwise world max (the scale-sync primitive of the
        hist_exchange codec; max is associative+commutative, so the
        gather route is exact)."""
        parts = self.allgather_obj(np.ascontiguousarray(arr),
                                   primitive=primitive)
        return np.max(np.stack(parts, axis=0), axis=0)

    def barrier(self) -> None:
        self.allgather_bytes(b"", primitive="allgather")

    # -- compressed histogram exchange over the wire ------------------
    def exchange_histograms(self, local_hist: np.ndarray,
                            mode: str = "f32") -> np.ndarray:
        """The r21 ``hist_exchange`` codec over real TCP: q16/q8
        delta-coded integer payloads ship verbatim (int16/int8 on the
        wire, world-headroom so the ring sum can never overflow their
        own dtype) and the reconstruction is BIT-EXACT against
        ``collectives.host_exchange_histograms`` on the same shards —
        the scales cross as one pmax'd stat payload exactly like the
        in-program ``exchange_histograms`` lowering."""
        from ..reliability.faults import FAULTS
        from .collectives import HIST_EXCHANGE_MODES, _note_collective
        if mode not in HIST_EXCHANGE_MODES:
            raise ValueError(f"hist_exchange must be one of "
                             f"{HIST_EXCHANGE_MODES}, got {mode!r}")
        FAULTS.fault_point("collectives.hist_exchange")
        local = np.asarray(local_hist, dtype=np.float32)
        if mode == "f32":
            _note_collective("hist_exchange", local)
            # the payload frames carry their own primitive label, so
            # collective_tcp_hist_exchange_bytes reads the HISTOGRAM
            # wire bytes alone — the bench wire-reduction gate compares
            # exactly these frames across modes
            parts = self.allgather_obj(local, primitive="hist_exchange")
            return np.sum(np.stack(parts, axis=0), axis=0)
        world = self.world_size
        bits = 16 if mode == "q16" else 8
        qmax = (2 ** (bits - 1) - 1) // world
        if qmax < 1:
            raise ValueError(
                f"hist_exchange={mode}: world size {world} leaves no "
                f"quantization levels inside int{bits}")
        npdt = np.int16 if mode == "q16" else np.int8
        delta = np.concatenate(
            [local[..., :1, :], np.diff(local, axis=-2)], axis=-2)
        amax_l = np.max(np.abs(delta), axis=-2, keepdims=True)
        frac_l = np.max(np.abs(delta - np.round(delta)), axis=-2,
                        keepdims=True)
        # ONE pmax round syncs scale + integrality residual: the
        # elementwise world max of per-shard maxima IS the joint max
        # host_exchange_histograms takes over (shard, bin)
        stat = self.pmax(np.concatenate([amax_l, frac_l],
                                        axis=-2).astype(np.float32),
                         primitive="hist_scale")
        amax, frac = stat[..., :1, :], stat[..., 1:, :]
        exact = (frac == 0) & (amax <= qmax)
        denom = np.where(exact, np.float32(qmax),
                         np.maximum(amax, np.float32(1e-30)))
        q = np.clip(np.round(delta / denom * qmax),
                    -qmax, qmax).astype(npdt)
        _note_collective("hist_exchange", q)
        _note_collective("hist_exchange_scale", stat)
        # the narrow integers ride the ring IN the wire dtype — the
        # world-headroom qmax guarantees the running partial sums fit
        qsum = self.allreduce_sum(q, primitive="hist_exchange")
        deq = qsum.astype(np.int32).astype(np.float32) \
            * (denom / np.float32(qmax))
        return np.cumsum(deq, axis=-2, dtype=np.float32)

    # -- elastic membership -------------------------------------------
    def epoch_tick(self, handoff: Optional[Callable[[], bytes]] = None,
                   allow_degraded: bool = False) -> dict:
        """One epoch-boundary barrier.  Members TICK the coordinator
        and adopt its DIRECTIVE; the coordinator collects ticks,
        retires dead members, admits pending joiners (serving each the
        ``handoff()`` payload + ``handoff_meta``), and publishes the
        next ledger.  With an unchanged world this is one tiny
        control round.  Returns ``{"epoch", "world_size", "changed",
        "dead", "admitted"}``.

        A dead member with ``allow_degraded=False`` raises
        :class:`TransportPeerLost` — the fail-fast default mirrors
        ``sharded_allow_degraded``."""
        from ..reliability import watchdog as _watchdog
        from ..reliability.faults import FAULTS, TransportChaos
        chaos = None
        try:
            FAULTS.fault_point("transport.round")
        except TransportChaos as e:
            chaos = e
        except ConnectionError as e:
            raise TransportPeerLost(None, str(e)) from e
        deadline = _watchdog.deadline("collective")
        budget = deadline if deadline > 0 else _CTRL_TIMEOUT_S
        if chaos is not None and chaos.action == "partition" \
                and self.rank != self._coord_rank \
                and self._coord_sock is not None:
            # control-plane blip: sever our coordinator link; the
            # member tick below heals it by re-homing through the
            # coordinator's data listener (same walk as failover)
            _quiet_close(self._coord_sock)
            time.sleep(max(0, chaos.duration_ms) / 1e3)
        if self.rank == self._coord_rank:
            return self._coordinator_tick(handoff, allow_degraded,
                                          budget)
        return self._member_tick(handoff, allow_degraded, budget)

    def _member_tick(self, handoff, allow_degraded: bool,
                     budget: float) -> dict:
        try:
            if self._coord_sock is None:
                raise TransportError("no coordinator socket")
            self._coord_sock.settimeout(budget)
            _send_frame(self._coord_sock, TAG_TICK, _obj_frame(
                {"rank": self.rank, "epoch": self.epoch,
                 "trace": self.trace_id, "ver": PROTOCOL_VERSION}))
            _, _, payload = _recv_frame(self._coord_sock,
                                        TAG_DIRECTIVE,
                                        peer="coordinator")
        except (ConnectionError, OSError, socket.timeout,
                TransportError) as e:
            return self._coordinator_failover(e, handoff,
                                              allow_degraded, budget)
        directive = pickle.loads(payload)
        return self._adopt(directive)

    def _coordinator_failover(self, cause, handoff,
                              allow_degraded: bool,
                              budget: float) -> dict:
        """The coordinator is unreachable at a tick.  Walk the
        REPLICATED ledger inside a ``watchdog_collective_s``-bounded
        grace: re-dial the old coordinator's data listener first (a
        control-plane blip heals by re-homing to the SAME coordinator
        — no spurious failover on a one-sided reset), then every
        survivor in ascending rank order.  The lowest live rank is the
        deterministic successor; reaching our own rank on the walk
        means WE are it."""
        from ..reliability.faults import FAULTS
        try:
            FAULTS.fault_point("transport.failover")
        except ConnectionError as e:
            raise TransportPeerLost(self._coord_rank, str(e)) from e
        old = self._coord_rank
        if self._coord_sock is not None:
            _quiet_close(self._coord_sock)
            self._coord_sock = None
        Log.warning(
            f"tcp transport rank {self.rank}: coordinator rank {old} "
            f"unreachable at epoch {self.epoch} tick ({cause}) — "
            "walking the replicated ledger for the successor "
            "(docs/RELIABILITY.md coordinator-failover runbook)")
        deadline_at = time.monotonic() + budget
        candidates = [old] + [r for r in self.ledger.ranks()
                              if r != old]
        last: BaseException = cause
        for cand in candidates:
            remain = deadline_at - time.monotonic()
            if remain <= 0:
                break
            if cand == self.rank:
                return self._become_coordinator(
                    old, handoff, allow_degraded, max(0.5, remain))
            addr = self.ledger.members.get(cand)
            if addr is None:
                continue
            s = None
            try:
                s = _dial(addr[0], addr[1],
                          timeout=min(_DIAL_TIMEOUT_S, remain))
                s.settimeout(max(0.5, remain))
                _send_frame(s, TAG_TICK, _obj_frame(
                    {"rank": self.rank, "epoch": self.epoch,
                     "trace": self.trace_id,
                     "ver": PROTOCOL_VERSION, "rehome": True}))
                _, _, payload = _recv_frame(
                    s, TAG_DIRECTIVE, peer=f"successor {cand}")
            except (ConnectionError, OSError, socket.timeout,
                    TransportError) as e:
                last = e
                if s is not None:
                    _quiet_close(s)
                continue
            self._coord_sock = s
            from ..telemetry import TELEMETRY
            TELEMETRY.add("collective_tcp_rehomes", 1)
            TELEMETRY.journal.emit(
                "reconnect", seam="transport.failover",
                rank=self.rank, peer=cand, epoch=self.epoch,
                trace=self.trace_id, control_plane=True,
                cause=str(cause)[:160])
            Log.warning(
                f"tcp transport rank {self.rank}: re-homed control "
                f"traffic to rank {cand} ({'same coordinator' if cand == old else 'successor'})")
            return self._adopt(pickle.loads(payload))
        raise TransportPeerLost(
            old, f"coordinator failover exhausted every ledger "
            f"candidate (last: {last})") from cause

    def _become_coordinator(self, old: int, handoff,
                            allow_degraded: bool,
                            budget: float) -> dict:
        """This process is the lowest surviving rank: journal the
        change, take over the epoch protocol mid-run, and run the tick
        we were already inside — collecting the other survivors'
        re-homed TICKs on the data listener."""
        self._coord_rank = self.rank
        from ..telemetry import TELEMETRY
        TELEMETRY.add("collective_tcp_coordinator_changes", 1)
        TELEMETRY.journal.emit(
            "coordinator_change", seam="transport.failover",
            old=old, new=self.rank, epoch=self.epoch,
            trace=self.trace_id, world=self.world_size)
        Log.warning(
            f"tcp transport: rank {self.rank} is the new coordinator "
            f"(rank {old} died at epoch {self.epoch}; trace "
            f"{self.trace_id or '-'}) — resuming the epoch protocol "
            "mid-run")
        return self._coordinator_tick(handoff, allow_degraded, budget,
                                      pre_dead=[old])

    def _drain_listener(self, listener: socket.socket, budget: float,
                        ticked: Dict[int, bool],
                        joins: List[Tuple[socket.socket, dict]],
                        eof_at: Dict[int, float]) -> None:
        """Accept every pending connection on ``listener`` and sort
        its first frame: re-homed member TICKs replace control
        sockets, JOINs queue for admission, reconnect IDENTs heal data
        links that blipped into a tick boundary."""
        while True:
            listener.settimeout(0.0)
            try:
                conn, _ = listener.accept()
            except (BlockingIOError, socket.timeout, OSError):
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(min(_DIAL_TIMEOUT_S, budget))
            try:
                tag, _, payload = _recv_frame(conn)
                obj = pickle.loads(payload)
            except (ConnectionError, OSError, socket.timeout,
                    TransportError):
                _quiet_close(conn)
                continue
            if tag == TAG_JOIN:
                joins.append((conn, obj))
                continue
            if tag == TAG_TICK:
                if int(obj.get("epoch", -1)) != self.epoch:
                    _quiet_close(conn)   # stale-epoch corpse
                    continue
                r = int(obj["rank"])
                old = self._ctrl.pop(r, None)
                if old is not None:
                    _quiet_close(old)
                conn.settimeout(_CTRL_TIMEOUT_S)
                self._ctrl[r] = conn
                ticked[r] = True
                eof_at.pop(r, None)
                continue
            if tag == TAG_IDENT and obj.get("reconnect") \
                    and int(obj.get("epoch", -1)) == self.epoch:
                r = int(obj["rank"])
                _send_frame(conn, TAG_IDENT, _obj_frame(
                    {"rank": self.rank, "epoch": self.epoch,
                     "ver": PROTOCOL_VERSION,
                     "ack": self._recv_seq.get(r, 0)}))
                self._resync(r, conn, int(obj.get("ack", 0)))
                stale = self._peers.pop(r, None)
                if stale is not None:
                    _quiet_close(stale)
                self._peers[r] = conn
                continue
            _quiet_close(conn)

    def _coordinator_tick(self, handoff, allow_degraded: bool,
                          budget: float,
                          pre_dead: Optional[List[int]] = None
                          ) -> dict:
        dead: List[int] = list(pre_dead or [])
        expected = [r for r in self.ledger.ranks()
                    if r != self.rank and r not in dead]
        ticked: Dict[int, bool] = {}
        joins: List[Tuple[socket.socket, dict]] = \
            list(self._pending_joins)
        self._pending_joins = []
        # EOF on a member's control socket starts a short re-home
        # grace (a blipped member re-dials our data listener) before
        # the member is declared dead
        eof_at: Dict[int, float] = {
            r: 0.0 for r in expected if r not in self._ctrl}
        deadline_at = time.monotonic() + budget
        listeners = [ln for ln in (self._ctrl_listener,
                                   self._data_listener)
                     if ln is not None]
        while True:
            now = time.monotonic()
            for r in list(expected):
                if r in ticked:
                    continue
                started = eof_at.get(r)
                if started is not None and started > 0 \
                        and now - started > min(_REHOME_GRACE_S,
                                                budget):
                    dead.append(r)
                    expected.remove(r)
            pending = [r for r in expected if r not in ticked]
            if not pending:
                break
            if now >= deadline_at:
                for r in pending:
                    dead.append(r)
                    expected.remove(r)
                break
            socks = [self._ctrl[r] for r in pending
                     if r in self._ctrl] + listeners
            for r in pending:
                # a fresh successor has no control socket yet: start
                # its re-home wait against the FULL budget, not the
                # EOF grace
                if r not in self._ctrl and r not in eof_at:
                    eof_at[r] = 0.0
            try:
                rd, _, _ = select.select(
                    socks, [], [], min(0.25, deadline_at - now))
            except (OSError, ValueError):
                rd = []
            for s in rd:
                if s in listeners:
                    self._drain_listener(s, budget, ticked, joins,
                                         eof_at)
                    continue
                r = next((k for k, v in self._ctrl.items()
                          if v is s), None)
                if r is None:
                    continue
                try:
                    _recv_frame(s, TAG_TICK, peer=r)
                    ticked[r] = True
                    eof_at.pop(r, None)
                except (ConnectionError, OSError, socket.timeout,
                        TransportError):
                    _quiet_close(s)
                    self._ctrl.pop(r, None)
                    if r not in ticked:
                        eof_at[r] = time.monotonic()
        # one final drain for joiners/re-homes that raced the barrier
        for ln in listeners:
            self._drain_listener(ln, budget, ticked, joins, eof_at)
        if dead and not allow_degraded:
            for conn, _ in joins:
                _quiet_close(conn)
            raise TransportPeerLost(
                dead[0], "died before its epoch tick (arm "
                "sharded_allow_degraded for degraded continuation)")
        from ..telemetry import TELEMETRY
        ledger = self.ledger
        admitted: List[int] = []
        if dead:
            ledger = ledger.degrade(dead)
            TELEMETRY.journal.emit(
                "membership_degrade", seam="transport.round",
                dead=dead, epoch=ledger.epoch, trace=self.trace_id,
                world=ledger.world_size)
            Log.warning(
                f"tcp transport: peer rank(s) {dead} dead — world "
                f"degrades to {ledger.world_size} at epoch "
                f"{ledger.epoch} (survivor shards continue; "
                "docs/RELIABILITY.md)")
        skewed = [(c, j) for c, j in joins
                  if int(j.get("ver", 0)) != PROTOCOL_VERSION]
        for conn, j in skewed:
            Log.warning(
                f"tcp transport: refusing joiner {j.get('host')}:"
                f"{j.get('port')} speaking protocol v"
                f"{j.get('ver', 0)} (this world speaks v"
                f"{PROTOCOL_VERSION}) — finish the rolling restart "
                "before it re-joins")
            _quiet_close(conn)
        joins = [(c, j) for c, j in joins if (c, j) not in skewed]
        if joins:
            ledger, admitted = ledger.admit(
                [(j["host"], j["port"]) for _, j in joins])
            TELEMETRY.journal.emit(
                "membership_admit", seam="transport.round",
                admitted=admitted, epoch=ledger.epoch,
                trace=self.trace_id, world=ledger.world_size)
            Log.info(f"tcp transport: admitting joiner rank(s) "
                     f"{admitted} at epoch {ledger.epoch}")
        changed = ledger.epoch != self.ledger.epoch
        state = ledger.to_state()
        # the full ledger AND the handoff metadata ride EVERY
        # directive: any member can serve as successor without ever
        # having talked to a joiner
        directive = {"ledger": state, "changed": changed,
                     "dead": dead, "admitted": admitted,
                     "trace": self.trace_id, "coord": self.rank,
                     "hmeta": dict(self.handoff_meta)}
        for r, conn in list(self._ctrl.items()):
            try:
                _send_frame(conn, TAG_DIRECTIVE,
                            _obj_frame(dict(directive, you=r)))
            except (ConnectionError, OSError) as e:
                if not allow_degraded:
                    raise TransportPeerLost(r, str(e)) from e
        handoff_bytes = b""
        if joins and handoff is not None:
            handoff_bytes = handoff()
        for (conn, _), r in zip(joins, admitted):
            _send_frame(conn, TAG_DIRECTIVE,
                        _obj_frame(dict(directive, you=r)))
            _send_frame(conn, TAG_HANDOFF, _obj_frame(
                {"meta": dict(self.handoff_meta),
                 "state": handoff_bytes}))
            self._ctrl[r] = conn
        return self._adopt(dict(directive, you=self.rank))

    def _adopt(self, directive: dict) -> dict:
        new = WorldLedger.from_state(directive["ledger"])
        changed = bool(directive.get("changed"))
        if directive.get("trace"):
            self.trace_id = str(directive["trace"])
        if directive.get("hmeta"):
            # replicated so ANY survivor can serve joiners after a
            # coordinator death
            self.handoff_meta = dict(directive["hmeta"])
        if changed:
            self.ledger = new
            self._build_mesh()
            self._note_world()
            # every member (coordinator included) journals the epoch
            # flip with the SHARED fleet trace id, so the merged
            # timeline shows one trace spanning all host lanes
            from ..telemetry import TELEMETRY
            TELEMETRY.journal.emit(
                "epoch_change", seam="transport.round",
                epoch=self.epoch, rank=self.rank,
                world=self.world_size, trace=self.trace_id,
                dead=list(directive.get("dead") or []),
                admitted=list(directive.get("admitted") or []))
        # the coordinator is named by the ledger itself: lowest rank
        self._coord_rank = min(self.ledger.members)
        info = {"epoch": self.epoch, "world_size": self.world_size,
                "changed": changed,
                "dead": list(directive.get("dead") or []),
                "admitted": list(directive.get("admitted") or [])}
        return info

    # -- teardown -----------------------------------------------------
    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for s in self._peers.values():
            _quiet_close(s)
        for s in self._ctrl.values():
            _quiet_close(s)
        for conn, _ in self._pending_joins:
            _quiet_close(conn)
        for s in (self._coord_sock, self._ctrl_listener,
                  self._data_listener):
            if s is not None:
                _quiet_close(s)
        self._peers = {}
        self._ctrl = {}
        self._pending_joins = []
        self._sent_log = {}


# ---------------------------------------------------------------------------
# helpers + process-global registry
# ---------------------------------------------------------------------------
def _parse_addr(address: str) -> Tuple[str, int]:
    if not address or ":" not in address:
        raise ValueError(
            f"coordinator address {address!r} must be host:port")
    host, _, port = address.rpartition(":")
    return host, int(port)


def _dial(host: str, port: int,
          timeout: float = _DIAL_TIMEOUT_S) -> socket.socket:
    """One bounded dial (no retry policy): failover walks and
    reconnects bound each candidate attempt themselves."""
    s = socket.create_connection((host, int(port)),
                                 timeout=max(0.1, timeout))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _listen(host: str, port: int) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host, port))
    s.listen(64)
    return s


def _quiet_close(s: socket.socket) -> None:
    try:
        s.close()
    except OSError:
        pass


_active: Optional[TcpTransport] = None


def install(transport: Optional[TcpTransport]) -> None:
    """Install the process-global transport (``None`` uninstalls).
    ``distributed._allgather`` / ``_num_processes`` /
    ``sample_local_rows`` and the sharded candidate gather consult it
    before any ``jax`` world query."""
    global _active
    if _active is not None and transport is not None \
            and _active is not transport:
        _active.close()
    _active = transport


def active() -> Optional[TcpTransport]:
    return _active


def xla_multiprocess_available() -> bool:
    """Whether cross-process XLA collectives can run here: the CPU
    client cannot run multiprocess computations at all (the
    ``tests/test_distributed.py`` skip this transport exists to
    remove), so only a non-CPU backend qualifies."""
    try:
        import jax
        return jax.default_backend() != "cpu"
    except Exception:  # pragma: no cover - no backend at all
        return False


def resolve_transport_mode(config=None,
                           num_processes: Optional[int] = None) -> str:
    """``collective_transport`` resolution: explicit ``xla``/``tcp``
    win; ``auto`` picks TCP exactly when a multi-process world is
    requested and cross-process XLA collectives are unavailable
    (docs/Parallel-Learning-Guide.md transport-selection matrix)."""
    mode = str(getattr(config, "collective_transport", "auto")
               or "auto").lower()
    if mode not in TRANSPORT_MODES:
        raise ValueError(f"collective_transport must be one of "
                         f"{TRANSPORT_MODES}, got {mode!r}")
    if mode != "auto":
        return mode
    world = int(num_processes or 1)
    if world > 1 and not xla_multiprocess_available():
        return "tcp"
    return "xla"
