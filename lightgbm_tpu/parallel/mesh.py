"""Device mesh construction and sharding policies for distributed
tree learning.

TPU-native replacement for the reference's entire network stack
(reference: src/network/ Linkers + Bruck/recursive-halving/ring
collectives, network.cpp:64-314, and the tree_learner x device dispatch
tree_learner.cpp:9-33).  The hand-written socket/MPI collectives
disappear: parallelism is expressed as shardings over a
``jax.sharding.Mesh`` and XLA inserts the psum / reduce-scatter /
all-gather over ICI/DCN:

  * ``data`` learner  — rows sharded (DataParallelTreeLearner,
    data_parallel_tree_learner.cpp): the histogram matmul contracts the
    sharded row dimension, XLA emits exactly the ReduceScatter(+gather)
    of per-(leaf,group,bin) partial histograms the reference codes by
    hand (:147-162); constraining the histogram output to be
    feature-sharded reproduces the per-machine feature ownership.
  * ``feature`` learner — bins replicated, histogram columns sharded
    (FeatureParallelTreeLearner): split search is divided by feature,
    the global best split is a tiny argmax all-reduce
    (SyncUpGlobalBestSplit, parallel_tree_learner.h:184-207) that XLA
    derives from the replicated argmax.
  * ``voting`` learner — top-k gain preselection then a reduced
    histogram exchange (voting_parallel_tree_learner.cpp); expressed
    with the same constraints plus a top_k mask.

Multi-host: call ``jax.distributed.initialize()`` before building the
mesh; the same jitted program then spans hosts with collectives routed
over ICI within a pod and DCN across pods.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import Config
from ..utils.log import Log

DATA_AXIS = "data"
FEATURE_AXIS = "feature"


def build_mesh(config: Config) -> Optional[Mesh]:
    """Build the training mesh from config (mesh_shape/mesh_axes or all
    local devices on one axis matching the tree_learner)."""
    if config.tree_learner == "serial" and not config.mesh_shape:
        return None
    devices = jax.devices()
    if config.mesh_shape:
        shape = tuple(config.mesh_shape)
        axes = tuple(config.mesh_axes) or (DATA_AXIS,)
        n = int(np.prod(shape))
        if n > len(devices):
            Log.warning(f"mesh_shape {shape} needs {n} devices, have "
                        f"{len(devices)}; falling back to serial")
            return None
        return Mesh(np.asarray(devices[:n]).reshape(shape), axes)
    n = len(devices)
    if n == 1:
        return None
    axis = FEATURE_AXIS if config.tree_learner == "feature" else DATA_AXIS
    return Mesh(np.asarray(devices), (axis,))


class ShardingPolicy:
    """Per-learner sharding decisions consumed by the grower."""

    def __init__(self, config: Config, mesh: Optional[Mesh]):
        self.mesh = mesh
        self.learner = config.tree_learner
        try:
            self.nproc = jax.process_count()
        except Exception:  # pragma: no cover - uninitialized backend
            self.nproc = 1
        # multi-host: arrays must be assembled from process-local
        # shards (device_put of a full array cannot address other
        # hosts' devices)
        self.multihost = mesh is not None and self.nproc > 1
        from ..telemetry import TELEMETRY
        if TELEMETRY.on and mesh is not None:
            # topology gauges: a scraped metrics page should say what
            # fabric the run is on without reading logs
            TELEMETRY.gauge("mesh_devices", int(mesh.size))
            TELEMETRY.gauge("mesh_hosts", int(self.nproc))
            TELEMETRY.gauge("mesh_axes",
                            ",".join(f"{a}={n}" for a, n in
                                     zip(mesh.axis_names,
                                         mesh.devices.shape)))
        if mesh is None:
            self.row_spec = None
            self.hist_spec = None
            return
        axes = mesh.axis_names
        if self.learner in ("data", "voting") or DATA_AXIS in axes:
            data_axis = DATA_AXIS if DATA_AXIS in axes else axes[0]
            self.row_spec = P(data_axis)            # rows sharded
            # per-machine feature ownership after the reduce
            # (data_parallel_tree_learner.cpp:53-115): shard the reduced
            # histogram over groups so the row-contraction lowers to a
            # reduce-scatter instead of a full all-reduce
            self.hist_spec = P(None, data_axis, None, None)
        elif self.learner == "feature":
            f_axis = FEATURE_AXIS if FEATURE_AXIS in axes else axes[0]
            self.row_spec = None                    # rows replicated
            self.hist_spec = P(None, f_axis, None, None)
            # vertical partition (the reference's feature-parallel data
            # layout, feature_parallel_tree_learner.cpp): each device
            # owns its feature-group COLUMNS of the bin matrix, so the
            # histogram contraction is local per shard and only the
            # SplitInfo election + the owner's per-row routing decision
            # cross the network — without this, the SPMD partitioner
            # splits the replicated-bins contraction over rows and
            # all-reduces FULL histograms (caught by the
            # __graft_entry__ collective gate)
            self.bins_spec = P(None, f_axis)
        else:
            self.row_spec = None
            self.hist_spec = None

    # ------------------------------------------------------------------
    def place_bins(self, arr):
        """Place the (N, G) bin matrix: column-sharded for the
        feature-parallel learner (vertical partition) when the group
        count divides the mesh — the shard_map SplitInfo-election path
        needs even shards; uneven group counts fall back to the row
        placement (replicated bins, constraint-sharded histograms)."""
        spec = getattr(self, "bins_spec", None)
        if self.mesh is not None and spec is not None \
                and arr.shape[1] % self.mesh.size == 0:
            return jax.device_put(arr, NamedSharding(self.mesh, spec))
        return self.place_rows(arr)

    def place_rows(self, arr):
        """Place a row-indexed array ((N,) or (N, G)).  Multi-host: the
        array is the ASSEMBLED global view (host h's rows at
        [h*N/nproc, (h+1)*N/nproc)); this host's slice is extracted and
        the global array built from process-local shards."""
        if self.mesh is None or self.row_spec is None:
            return jax.device_put(arr)
        ndim = getattr(arr, "ndim", 1)
        spec = P(self.row_spec[0], *([None] * (ndim - 1)))
        if self.multihost:
            return self.place_local_rows(self._local_slice(arr, axis=0))
        return jax.device_put(arr, NamedSharding(self.mesh, spec))

    def place_local_rows(self, local_arr):
        """Multi-host: build the global row-sharded array from THIS
        host's padded shard (jax.make_array_from_process_local_data —
        the seam reference dataset_loader.cpp's pre-partitioned loading
        feeds)."""
        ndim = getattr(local_arr, "ndim", 1)
        spec = P(self.row_spec[0], *([None] * (ndim - 1)))
        sh = NamedSharding(self.mesh, spec)
        if not self.multihost:
            return jax.device_put(local_arr, sh)
        return jax.make_array_from_process_local_data(sh, local_arr)

    def place_row_shards(self, shard_arrays, n_padded: int):
        """Sharded-construct placement (lightgbm_tpu/sharded/): the
        per-participant row shards of the bin matrix go STRAIGHT onto
        their devices along the row mesh axis — device d receives its
        ``n_padded / mesh.size`` row block sliced from the shard list
        (plus the zero tail pad) and the global array assembles via
        ``jax.make_array_from_single_device_arrays``, so the host
        never materializes the concatenated matrix on the mesh path.
        The logical global layout is IDENTICAL to the single-matrix
        route (rows in construction order, pad at the tail): the
        compiled program, and therefore the trained trees, are
        byte-identical across the two routes.

        Falls back to a host concat (then the normal placement) when
        there is no 1-D row mesh to tile — serial runs, multi-axis
        meshes, the feature learner's vertical partition, multi-host
        (each host passes its own shards through
        ``place_local_rows``), or a row count the mesh can't divide."""
        arrs = [np.asarray(a) for a in shard_arrays]
        rest = tuple(arrs[0].shape[1:])
        n = sum(a.shape[0] for a in arrs)
        if n > n_padded:
            raise ValueError(f"shards hold {n} rows > n_padded "
                             f"{n_padded}")
        mesh = self.mesh
        direct = (mesh is not None and self.row_spec is not None
                  and not self.multihost
                  and len(mesh.axis_names) == 1
                  and getattr(self, "bins_spec", None) is None
                  and n_padded % mesh.size == 0)
        if not direct:
            full = np.zeros((n_padded,) + rest, dtype=arrs[0].dtype)
            full[:n] = np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
            return self.place_bins(full) if full.ndim == 2 \
                else self.place_rows(full)
        spec = P(self.row_spec[0], *([None] * len(rest)))
        sh = NamedSharding(mesh, spec)
        shape = (n_padded,) + rest
        # shard start offsets within the logical global row order
        starts = np.cumsum([0] + [a.shape[0] for a in arrs])
        blocks = []
        for dev, idx in sh.addressable_devices_indices_map(
                shape).items():
            lo = idx[0].start or 0
            hi = idx[0].stop if idx[0].stop is not None else n_padded
            parts = []
            for i, a in enumerate(arrs):
                s, e = max(lo, int(starts[i])), \
                    min(hi, int(starts[i + 1]))
                if s < e:
                    parts.append(a[s - int(starts[i]):
                                   e - int(starts[i])])
            have = sum(p.shape[0] for p in parts)
            if have < hi - lo:          # zero tail pad on this device
                parts.append(np.zeros((hi - lo - have,) + rest,
                                      dtype=arrs[0].dtype))
            block = parts[0] if len(parts) == 1 \
                else np.concatenate(parts)
            blocks.append(jax.device_put(
                np.ascontiguousarray(block), dev))
        return jax.make_array_from_single_device_arrays(shape, sh,
                                                        blocks)

    def place_score_rows(self, arr):
        """Place a (K, N) class-major score matrix (rows on axis 1)."""
        if self.mesh is None or self.row_spec is None:
            return jax.device_put(arr)
        sh = NamedSharding(self.mesh, P(None, self.row_spec[0]))
        if self.multihost:
            return jax.make_array_from_process_local_data(
                sh, self._local_slice(arr, axis=1))
        return jax.device_put(arr, sh)

    def _local_slice(self, arr, axis: int):
        import numpy as _np
        n = arr.shape[axis]
        per = n // self.nproc
        pid = jax.process_index()
        idx = [slice(None)] * arr.ndim
        idx[axis] = slice(pid * per, (pid + 1) * per)
        return _np.ascontiguousarray(_np.asarray(arr)[tuple(idx)])

    def replicate(self, arr):
        if self.mesh is None:
            return jax.device_put(arr)
        if self.multihost:
            return jax.make_array_from_process_local_data(
                NamedSharding(self.mesh, P()), np.asarray(arr))
        return jax.device_put(arr, NamedSharding(self.mesh, P()))

    def constrain_hist(self, hist):
        """Apply the post-reduce histogram sharding constraint."""
        if self.mesh is None or self.hist_spec is None:
            return hist
        return jax.lax.with_sharding_constraint(
            hist, NamedSharding(self.mesh, self.hist_spec))

    @property
    def num_shards(self) -> int:
        return 1 if self.mesh is None else self.mesh.size
