"""Per-host step-wall monitoring + straggler detection.

The analog of the reference's ``GlobalSyncUpByMin/Max/Mean`` scalar
syncs (network.h:165-257): after each training dispatch every host
contributes its step wall to a tiny allgather, and every host derives
the same max/min/mean — the ``straggler_ratio`` gauge
(``max / mean``) is the one number that says whether the fleet is
compute-bound or waiting on one slow host.  Straggler/imbalance is THE
dominant distributed-GBDT failure mode (PAPERS.md: arXiv 1706.08359
§data-parallel scaling, LiteMORT 2001.09419), and before this module
it was only visible by diffing N per-host logs by hand.

Wired into ``GBDT.train_chunk`` / ``train_one_iter`` when telemetry is
on and the run spans multiple processes; the gather is a collective,
so the call sites are the SPMD training loop every host executes in
lockstep.  ``gather`` is injectable so the single-process test suite
can exercise the exact ratio math with simulated hosts (thread-barrier
fakes), the way ``LGBM_NetworkInitWithFunctions`` let the reference
fake its network.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from ..telemetry import TELEMETRY
from ..utils.log import Log

# ratio above which a step is counted as straggled (warned once per
# process, counted always): max/mean = 1.5 means the slowest host
# left the others idle for half a mean step
STRAGGLER_WARN_RATIO = 1.5

_warned = {"straggler": False}


def step_wall_stats(times_s) -> Dict[str, float]:
    """max/min/mean/ratio over per-host step walls (seconds) — the
    pure reduction both the production gather and the tests share."""
    times = [float(t) for t in times_s]
    if not times:
        raise ValueError("step_wall_stats needs at least one sample")
    mx = max(times)
    mn = min(times)
    mean = sum(times) / len(times)
    return {
        "max": mx,
        "min": mn,
        "mean": mean,
        "ratio": (mx / mean) if mean > 0 else 1.0,
    }


def _default_gather(seconds: float) -> Optional[List[float]]:
    """Allgather this host's step wall across processes (None when the
    run is single-process — there is nothing to compare).  An
    installed TCP transport (``collective_transport=tcp``) carries the
    exchange directly — the straggler monitor then works on the
    host-side data plane with no jax distributed runtime, and the
    round is traced/accounted as a ``transport_round`` like every
    other transport collective.  Otherwise the exchange routes through
    ``distributed._allgather`` so it passes the
    ``collectives.allgather`` fault seam and shows up in the
    ``collective_host_allgather_*`` accounting like every other host
    collective."""
    import numpy as np

    from . import transport as _transport
    t = _transport.active()
    if t is not None and t.world_size > 1:
        gathered = t.allgather(np.asarray([seconds], dtype=np.float64))
        return [float(x) for x in np.asarray(gathered).ravel()]
    if TELEMETRY._n_hosts() <= 1:
        return None
    from .distributed import _allgather
    gathered = _allgather(np.asarray([seconds], dtype=np.float64))
    return [float(x) for x in np.asarray(gathered).ravel()]


def record_step_wall(seconds: float,
                     gather: Optional[Callable] = None
                     ) -> Optional[Dict[str, float]]:
    """Record this host's step wall and — when the run spans hosts —
    the fleet-wide max/min/mean and ``straggler_ratio`` gauges
    (docs/OBSERVABILITY.md, distributed observability).

    ``gather(seconds) -> [per-host seconds]`` is the collective; the
    default allgathers over ``jax`` processes and returns None
    single-process.  With no injected gather, the cross-host exchange
    only runs when the device fence is active (``telemetry=spans``, or
    ``counters`` with the bench's explicit fence): unfenced "step
    wall" is just the async enqueue time — straggler_ratio over it
    would measure host Python jitter, not device-step skew — and
    counters mode is documented to add NO blocking work to the
    dispatch pipeline.  Returns the stats dict when a gather
    happened."""
    tm = TELEMETRY
    if not tm.on:
        return None
    tm.gauge("step_wall_ms", round(seconds * 1e3, 3))
    # histogram under its own family name: `step_wall_ms` is already a
    # gauge, and one Prometheus metric name cannot be both
    tm.observe("step_wall_hist_ms", seconds * 1e3)
    if gather is None and not (tm.spans_on or tm.fence_active):
        return None
    times = (gather or _default_gather)(seconds)
    if not times or len(times) < 2:
        return None
    st = step_wall_stats(times)
    tm.gauge("step_wall_ms_max", round(st["max"] * 1e3, 3))
    tm.gauge("step_wall_ms_min", round(st["min"] * 1e3, 3))
    tm.gauge("step_wall_ms_mean", round(st["mean"] * 1e3, 3))
    ratio = round(st["ratio"], 4)
    tm.gauge("straggler_ratio", ratio)
    tm.gauge_max("straggler_ratio_peak", ratio)
    if ratio >= STRAGGLER_WARN_RATIO:
        tm.add("straggler_steps", 1)
        if not _warned["straggler"]:
            _warned["straggler"] = True
            slow = max(range(len(times)), key=lambda i: times[i])
            Log.warning(
                f"straggler detected: slowest host {slow} at "
                f"{st['max'] * 1e3:.1f} ms vs fleet mean "
                f"{st['mean'] * 1e3:.1f} ms (ratio {ratio}; warned "
                "once — straggler_ratio / straggler_steps keep "
                "counting, docs/OBSERVABILITY.md)")
    return st
