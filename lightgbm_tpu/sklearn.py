"""scikit-learn estimator wrappers.

Mirrors the reference sklearn API (reference:
python-package/lightgbm/sklearn.py:127 LGBMModel, :599 LGBMRegressor,
:629 LGBMClassifier, :739 LGBMRanker, plus the custom objective/eval
function adapters at :17-126).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from .basic import Dataset
from .booster import Booster
from .engine import train as _train
from .utils.log import Log

try:  # sklearn integration is optional (reference compat.py gating)
    from sklearn.base import (BaseEstimator as _SKBase,
                              ClassifierMixin as _SKClassifier,
                              RegressorMixin as _SKRegressor)
except ImportError:  # pragma: no cover
    _SKBase = object

    class _SKClassifier:  # type: ignore
        pass

    class _SKRegressor:  # type: ignore
        pass


class _ObjectiveFunctionWrapper:
    """Adapts sklearn-style fobj(y_true, y_pred) -> (grad, hess)
    (reference sklearn.py:17-77)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.metadata.label[:dataset.num_data]
        argc = self.func.__code__.co_argcount
        if argc == 2:
            grad, hess = self.func(labels, preds)
        elif argc == 3:
            grad, hess = self.func(labels, preds,
                                   dataset.metadata.get_field("group"))
        else:
            raise TypeError(f"Self-defined objective takes 2 or 3 "
                            f"arguments, got {argc}")
        return grad, hess


class _EvalFunctionWrapper:
    """Adapts sklearn-style feval (reference sklearn.py:78-126)."""

    def __init__(self, func: Callable):
        self.func = func

    def __call__(self, preds, dataset):
        labels = dataset.metadata.label[:dataset.num_data]
        argc = self.func.__code__.co_argcount
        if argc == 3:
            return [self.func(labels, preds)]
        if argc == 4:
            return [self.func(labels, preds, dataset.metadata.weight)]
        raise TypeError("Self-defined eval function takes 3 or 4 arguments")


class LGBMModel(_SKBase):
    """Base estimator (reference sklearn.py:127-598)."""

    def __init__(self, boosting_type="gbdt", num_leaves=31, max_depth=-1,
                 learning_rate=0.1, n_estimators=100,
                 subsample_for_bin=200000, objective=None, class_weight=None,
                 min_split_gain=0.0, min_child_weight=1e-3,
                 min_child_samples=20, subsample=1.0, subsample_freq=0,
                 colsample_bytree=1.0, reg_alpha=0.0, reg_lambda=0.0,
                 random_state=None, n_jobs=-1, silent=True,
                 importance_type="split", **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.class_weight = class_weight
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self.importance_type = importance_type
        self._other_params: Dict[str, Any] = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._n_features = None
        self._classes = None
        self._n_classes = None
        self._evals_result = None
        self._best_iteration = -1
        self._best_score: Dict[str, Dict[str, float]] = {}
        self._objective = objective

    # -- sklearn protocol -------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        params = {k: getattr(self, k) for k in (
            "boosting_type", "num_leaves", "max_depth", "learning_rate",
            "n_estimators", "subsample_for_bin", "objective", "class_weight",
            "min_split_gain", "min_child_weight", "min_child_samples",
            "subsample", "subsample_freq", "colsample_bytree", "reg_alpha",
            "reg_lambda", "random_state", "n_jobs", "silent",
            "importance_type")}
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for k, v in params.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self._other_params[k] = v
        return self

    # ---------------------------------------------------------------------
    def _default_objective(self) -> str:
        return "regression"

    def _build_params(self) -> Dict[str, Any]:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "verbose": -1 if self.silent else 1,
        }
        if self.random_state is not None:
            params["seed"] = int(self.random_state)
        obj = self.objective
        if obj is None or callable(obj):
            params["objective"] = self._default_objective()
        else:
            params["objective"] = obj
        params.update(self._other_params)
        return params

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_group=None, eval_metric=None,
            early_stopping_rounds=None, verbose=False, callbacks=None):
        params = self._build_params()
        if eval_metric is not None and not callable(eval_metric):
            params["metric"] = eval_metric
        fobj = _ObjectiveFunctionWrapper(self.objective) \
            if callable(self.objective) else None
        feval = _EvalFunctionWrapper(eval_metric) \
            if callable(eval_metric) else None

        y_fit = self._process_label(np.asarray(y))
        train_set = Dataset(X, label=y_fit, weight=sample_weight,
                            group=group, init_score=init_score,
                            categorical_feature=self._other_params.get(
                                "categorical_feature", "auto"))
        valid_sets = []
        valid_names = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                if vx is X and vy is y:
                    valid_sets.append(train_set)
                else:
                    vw = (eval_sample_weight or {}).get(i) \
                        if isinstance(eval_sample_weight, dict) \
                        else (eval_sample_weight[i]
                              if eval_sample_weight else None)
                    vg = (eval_group[i] if eval_group else None)
                    valid_sets.append(train_set.create_valid(
                        vx, label=self._process_label(np.asarray(vy)),
                        weight=vw, group=vg))
                valid_names.append((eval_names or {}).get(i)
                                   if isinstance(eval_names, dict)
                                   else (eval_names[i] if eval_names
                                         else f"valid_{i}"))
        evals_result: Dict = {}
        self._Booster = _train(
            params, train_set,
            num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=valid_names,
            fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=evals_result, verbose_eval=verbose,
            callbacks=callbacks)
        self._evals_result = evals_result
        self._best_iteration = self._Booster.best_iteration
        # best_score_ (reference sklearn.py): per-dataset per-metric
        # value at the best iteration (last iteration when no early
        # stopping fired)
        self._best_score: Dict[str, Dict[str, float]] = {}
        at = (self._best_iteration - 1) if self._best_iteration and \
            self._best_iteration > 0 else -1
        for dname, metrics in evals_result.items():
            self._best_score[dname] = {
                mname: vals[at] for mname, vals in metrics.items()
                if vals}
        self._n_features = train_set.num_feature()
        # sklearn's check_is_fitted detects fitted state from instance
        # attributes with a trailing underscore
        self.n_features_in_ = self._n_features
        return self

    def _process_label(self, y):
        return y

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        """Extra keyword arguments (e.g. ``device=True`` to force the
        bucketed device predictor for serving-shaped micro-batches)
        forward to Booster.predict."""
        if self._Booster is None:
            raise RuntimeError("Estimator not fitted")
        return self._Booster.predict(
            X, num_iteration=num_iteration or -1, raw_score=raw_score,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib, **kwargs)

    # -- attributes -------------------------------------------------------
    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise RuntimeError("No booster found; call fit first")
        return self._Booster

    @property
    def best_iteration_(self):
        return self._best_iteration

    @property
    def evals_result_(self):
        return self._evals_result

    @property
    def feature_importances_(self):
        return self.booster_.feature_importance(self.importance_type)

    @property
    def n_features_(self):
        return self._n_features

    @property
    def best_score_(self):
        """reference sklearn.py: {dataset: {metric: value}} at the
        best iteration."""
        if self._Booster is None:
            raise RuntimeError("No booster found; call fit first")
        return self._best_score

    @property
    def objective_(self):
        """reference sklearn.py: the concrete objective used to fit."""
        if self._Booster is None:
            raise RuntimeError("No booster found; call fit first")
        return self.objective if self.objective is not None \
            else self._default_objective()

    def apply(self, X, num_iteration=None):
        """reference sklearn.py LGBMModel.apply: predicted leaf index
        of every tree for every sample."""
        if self._Booster is None:
            raise RuntimeError("Estimator not fitted")
        return self._Booster.predict(X, num_iteration=num_iteration
                                     or -1, pred_leaf=True)


class LGBMRegressor(_SKRegressor, LGBMModel):
    # mixin first: sklearn's __sklearn_tags__/estimator_type resolution
    # walks the MRO and the mixin must precede the BaseEstimator subclass
    def _default_objective(self):
        return "regression"


class LGBMClassifier(_SKClassifier, LGBMModel):
    def _default_objective(self):
        if self._n_classes is not None and self._n_classes > 2:
            return "multiclass"
        return "binary"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y)
        self._classes = np.unique(y)
        self._n_classes = len(self._classes)
        if self._n_classes > 2 and not callable(self.objective) \
                and (self.objective is None
                     or self.objective in ("multiclass", "multiclassova",
                                           "softmax", "ova", "ovr")):
            self._other_params.setdefault("num_class", self._n_classes)
        return super().fit(X, y, **kwargs)

    def _process_label(self, y):
        lut = {c: i for i, c in enumerate(self._classes)}
        return np.asarray([lut[v] for v in y], dtype=np.float64)

    def predict(self, X, raw_score=False, num_iteration=None,
                pred_leaf=False, pred_contrib=False, **kwargs):
        result = self.predict_proba(X, raw_score, num_iteration,
                                    pred_leaf, pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        idx = np.argmax(result, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score=False, num_iteration=None,
                      pred_leaf=False, pred_contrib=False, **kwargs):
        result = super().predict(X, raw_score, num_iteration, pred_leaf,
                                 pred_contrib, **kwargs)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:   # binary: (n,) prob of positive class
            return np.column_stack([1.0 - result, result])
        return result

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self):
        return self._n_classes


class LGBMRanker(LGBMModel):
    def _default_objective(self):
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        return super().fit(X, y, group=group, **kwargs)
