"""Booster: the user-facing trained-model handle.

The analog of the reference's C-API Booster + python Booster
(reference: src/c_api.cpp:29-311, python-package/lightgbm/basic.py:1264+)
— owns the boosting object during training and the host-side tree list
for prediction/serialization; model text format is interchangeable with
the reference's (gbdt_model_text.cpp:235-315).
"""
from __future__ import annotations

import functools
import re
from typing import Any, Dict, List, Optional, Union

import numpy as np

from .config import Config, canonical_objective
from .dataset import Dataset
from .tree import Tree
from .utils.log import Log

MODEL_VERSION = "v2"

_ACC_FN = None

# serializes the (trace-counter read, dispatch, compare) window that
# classifies a serving dispatch as bucket hit vs miss — without it a
# concurrent thread's compile lands inside another thread's window and
# a cached-program hit is misattributed as a miss.  Only taken when
# telemetry is on; the enqueue itself is sub-ms so serving threads
# contend only on the dispatch call, never on device execution.
_SERVING_CLASSIFY_LOCK = None


def _serving_lock():
    global _SERVING_CLASSIFY_LOCK
    if _SERVING_CLASSIFY_LOCK is None:
        import threading
        _SERVING_CLASSIFY_LOCK = threading.Lock()
    return _SERVING_CLASSIFY_LOCK


def _acc_fn():
    """Module-level jitted tree-stack accumulator for the device
    predict path: one compilation per (shapes, max_steps), shared by
    every Booster and every predict() call (a per-call closure would
    re-trace each time)."""
    global _ACC_FN
    if _ACC_FN is None:
        import jax
        from .ops.predict import predict_binned

        @functools.partial(jax.jit, static_argnames=("max_steps",
                                                     "packed_groups"))
        def acc(total, stack, shrink_arr, vbins, f_group, g2f_lut,
                f_missing, f_default_bin, f_num_bin, *, max_steps,
                packed_groups=0):
            from .telemetry import TELEMETRY
            TELEMETRY.note_trace("predict.binned_scan",
                                 (vbins.shape, max_steps))

            def body(carry, xs):
                tr, sh = xs
                pv = predict_binned(tr, vbins, f_group, g2f_lut,
                                    f_missing, f_default_bin, f_num_bin,
                                    max_steps=max_steps,
                                    packed_groups=packed_groups)
                return carry + sh * pv, None
            out, _ = jax.lax.scan(body, total, (stack, shrink_arr))
            return out
        _ACC_FN = acc
    return _ACC_FN


_PREDICT_CHUNK_BUDGET_BYTES = 256 << 20  # transient per-chunk device
# footprint bound for predict_chunk_rows=auto (two chunks in flight)


def round_up_bucket(m: int, min_bucket: int) -> int:
    """The serving bucket ladder: smallest power-of-two multiple of
    ``min_bucket`` covering ``m`` rows.  ONE definition shared by the
    predictor's dispatch rounding and the serving micro-batcher's
    fill metric (callers clamp to their own caps)."""
    b = max(1, int(min_bucket))
    while b < m:
        b <<= 1
    return b


class _ServingPredictor:
    """Shape-bucketed, chunk-streamed device predictor over one
    ensemble slice — the serving subsystem's compiled-program unit.

    Batch sizes round UP to power-of-two row buckets (masked tails:
    pad rows are scored and discarded), so micro-batch serving traffic
    compiles once per bucket instead of once per batch size; the
    module-level jit in ops/predict.py shares those compilations
    across every Booster in the process and `compile_cache_dir` across
    processes.  Batches above the chunk cap stream through the device
    in fixed full-bucket chunks with at most two chunks' results in
    flight (double buffering: the next chunk's upload/compute overlaps
    the previous one's D2H), so bulk scoring never densifies the whole
    matrix on device."""

    def __init__(self, models: List[Tree], num_class: int, config):
        import jax.numpy as jnp

        from .ops import predict as P
        from .tree import flatten_ensemble

        flat = flatten_ensemble(models, num_class)
        self.depth = int(flat.pop("depth"))
        self.stack = P.LevelEnsemble(
            **{k: jnp.asarray(v) for k, v in flat.items()})
        self.num_class = max(num_class, 1)
        kernel = str(getattr(config, "predict_kernel", "auto")).lower()
        self.kernel = "level" if kernel in ("auto", "") else kernel
        self.interpret = bool(getattr(config, "force_pallas_interpret",
                                      False))
        tile = max(1, int(getattr(config, "predict_pallas_tile", 512)))
        # power-of-two floor: the grid requires tile | rows, and both
        # buckets and chunk caps are powers of two
        self.tile = 1 << (tile.bit_length() - 1)
        self.bucketed = str(getattr(config, "predict_bucket", "auto")
                            ).lower() not in ("off", "false", "0")
        self.min_bucket = max(1, int(getattr(
            config, "predict_min_bucket_rows", 16)))
        self.chunk_rows = int(getattr(config, "predict_chunk_rows", 0))
        # OOM degradation ladder (docs/RELIABILITY.md): on
        # RESOURCE_EXHAUSTED the dispatch bucket halves and the
        # request retries at the smaller shape instead of failing;
        # the learned cap persists so later requests start degraded
        self.oom_downshift = bool(getattr(config, "oom_downshift",
                                          True))
        self._oom_cap: Optional[int] = None
        self._oom_warned = False

    # ------------------------------------------------------------------
    def _chunk_cap(self, two_f: int) -> int:
        if self.chunk_rows > 0:
            cap = self.chunk_rows
        else:
            t = int(self.stack.root.shape[0])
            # per-row transients: the (N, 2F) hi/lo matrix + the (N, T)
            # node state, (N, 2T) gather indices and (N, T) values
            bytes_per_row = 4 * (two_f + 8 * max(t, 1))
            cap = _PREDICT_CHUNK_BUDGET_BYTES // max(bytes_per_row, 1)
            cap = max(4096, min(1 << 20, cap))
        if self.bucketed:
            # power-of-two cap => every full chunk is ONE bucket shape
            cap = 1 << (max(cap, 1).bit_length() - 1)
        return cap

    def _bucket(self, m: int, cap: int) -> int:
        if not self.bucketed:
            return m
        return min(round_up_bucket(m, self.min_bucket), cap)

    # ------------------------------------------------------------------
    def _dispatch(self, x2_dev):
        from .ops import predict as P
        from .reliability.faults import FAULTS
        FAULTS.fault_point("predict.dispatch")
        if self.kernel == "pallas":
            # halve until the tile divides the batch (immediate for
            # power-of-two buckets; odd bucket-off batches degrade to
            # tile 1 rather than crash the grid)
            tile = self.tile
            while x2_dev.shape[0] % tile:
                tile >>= 1
            return P.predict_level_ensemble_pallas(
                self.stack, x2_dev, depth=self.depth, tile=max(tile, 1),
                interpret=self.interpret)
        return P.predict_level_ensemble(self.stack, x2_dev,
                                        depth=self.depth)

    def _recover_oom(self, e: BaseException, bucket_rows: int, pending,
                     tm, s: int) -> int:
        """Classify a failed dispatch OR a failed drain (on async
        backends a device OOM materializes at the result copy, not the
        enqueue): RESOURCE_EXHAUSTED halves the serving ladder (warn
        once, count the event) and returns the row index to restart
        from; anything else — or OOM at a single-row bucket, where
        there is nothing left to halve — re-raises.

        In-flight results are DISCARDED, not drained: draining a
        poisoned buffer would re-raise the same OOM from inside the
        handler, and dropping the references lets the backend free the
        buffers (the other half of the memory pressure).  Their slices
        rewind into the restart index and are re-dispatched at the
        smaller bucket."""
        from .reliability.retry import is_oom
        if not (self.oom_downshift and is_oom(e)) or bucket_rows <= 1:
            raise
        restart = min((slot[1] for slot in pending), default=s)
        pending.clear()
        self._oom_cap = max(1, bucket_rows // 2)
        tm.add("oom_downshifts", 1)
        tm.journal.emit("oom_downshift", seam="predict.dispatch",
                        bucket=bucket_rows, new_cap=self._oom_cap)
        tm.flight.dump("oom_downshift", seam="predict.dispatch",
                       bucket=bucket_rows, new_cap=self._oom_cap)
        if not self._oom_warned:
            self._oom_warned = True
            Log.warning(
                "RESOURCE_EXHAUSTED during serving dispatch at bucket "
                f"{bucket_rows} ({e}); downshifting to bucket "
                f"{self._oom_cap} and retrying the slice")
        return restart

    def __call__(self, data: np.ndarray) -> np.ndarray:
        """(n, F) float64 raw features -> (n, K) float64 raw scores
        (f32 device accumulation, identical routing to the host walk).

        Telemetry (docs/OBSERVABILITY.md): a ``predict`` span per call
        with a ``predict_dispatch``/``predict_drain`` child per chunk;
        counters count requests, scored vs masked-tail pad rows, and
        bucket hit/miss — a MISS is a dispatch that triggered a new jit
        trace (== an XLA compilation, the ``test_predict_cache`` ground
        truth), everything else is a compiled-program hit.  Latency
        lands in the fixed log-bucket histograms any scraper derives
        p50/p95/p99 from: ``predict_latency_ms`` (whole request),
        ``predict_drain_ms`` (per-chunk result wait — the double-buffer
        "bucket wait") and ``predict_queue_depth`` (chunks in flight at
        each dispatch)."""
        import time

        import jax.numpy as jnp

        from .ops import predict as P
        from .telemetry import TELEMETRY as tm

        data = np.asarray(data, dtype=np.float64)
        n = data.shape[0]
        if n == 0:
            return np.zeros((0, self.num_class))
        t0 = time.perf_counter() if tm.on else 0.0
        span = tm.start_span("predict", rows=n)
        try:
            return self._call_impl(data, n, jnp, P, tm)
        finally:
            # the ladder's re-raise paths (non-OOM errors, OOM at
            # bucket 1) must not leave the request span unrecorded
            tm.end_span(span)
            if tm.on:
                tm.observe("predict_latency_ms",
                           (time.perf_counter() - t0) * 1e3)

    def _call_impl(self, data, n, jnp, P, tm) -> np.ndarray:
        if tm.on:
            tm.add("predict_requests", 1)
        hi, lo = P.split_hi_lo(data)
        x2 = np.empty((n, 2 * data.shape[1]), np.float32)
        x2[:, 0::2] = hi
        x2[:, 1::2] = lo
        cap = self._chunk_cap(x2.shape[1])
        if self._oom_cap is not None:
            cap = max(1, min(cap, self._oom_cap))
        out = np.empty((n, self.num_class), np.float32)
        pending: list = []

        def drain(slot):
            import time
            dev, s, m = slot
            t0 = time.perf_counter() if tm.on else 0.0
            with tm.span("predict_drain"):
                out[s:s + m] = np.asarray(dev)[:m]
            if tm.on:
                # the double-buffer wait: on an async backend this is
                # where the request actually waits on the device
                tm.observe("predict_drain_ms",
                           (time.perf_counter() - t0) * 1e3)

        s = 0
        while s < n or pending:
            if pending and (s >= n or len(pending) >= 2):
                # double buffer: at most TWO chunks' results in flight
                # (what _PREDICT_CHUNK_BUDGET_BYTES sizes against).
                # The drain is inside the ladder too: on an async
                # backend a device OOM materializes HERE, at the
                # result copy, not at the enqueue.
                slot = pending[0]
                try:
                    drain(slot)
                except Exception as e:
                    s = self._recover_oom(e, int(slot[0].shape[0]),
                                          pending, tm, s)
                    cap = max(1, min(cap, self._oom_cap))
                    continue
                pending.pop(0)
                continue
            part = x2[s:s + cap]
            m = part.shape[0]
            b = self._bucket(m, cap)
            if m < b:
                part = np.concatenate(
                    [part, np.zeros((b - m, x2.shape[1]), np.float32)])
            try:
                if tm.on:
                    with _serving_lock():
                        traces0 = P.PREDICT_TELEMETRY["traces"]
                        with tm.span("predict_dispatch",
                                     bucket=int(part.shape[0])):
                            dev = self._dispatch(jnp.asarray(part))
                        miss = P.PREDICT_TELEMETRY["traces"] > traces0
                    tm.add("predict_dispatches", 1)
                    tm.add("predict_rows", m)
                    tm.add("predict_pad_rows", int(part.shape[0]) - m)
                    tm.add("predict_bucket_miss" if miss
                           else "predict_bucket_hit", 1)
                else:
                    dev = self._dispatch(jnp.asarray(part))
            except Exception as e:
                # RESOURCE_EXHAUSTED degradation ladder: halve the
                # dispatch bucket and retry from the earliest
                # un-drained slice at the smaller shape instead of
                # failing the request; the learned cap sticks so
                # later requests start degraded
                s = self._recover_oom(e, int(part.shape[0]), pending,
                                      tm, s)
                cap = max(1, min(cap, self._oom_cap))
                continue
            P.PREDICT_TELEMETRY["dispatches"] += 1
            P.PREDICT_TELEMETRY["rows"] += m
            P.PREDICT_TELEMETRY["buckets"].add(int(part.shape[0]))
            pending.append((dev, s, m))
            if tm.on:
                tm.gauge_max("predict_stream_depth", len(pending))
                from .telemetry import DEPTH_BOUNDS
                tm.observe("predict_queue_depth", len(pending),
                           bounds=DEPTH_BOUNDS)
            s += m
        if tm.on:
            tm.sample_memory()
        return out.astype(np.float64)


class Booster:
    def __init__(self, config: Optional[Config] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None,
                 init_model=None, custom_objective: bool = False):
        self.config = config or Config()
        self.gbdt = None
        # set when host-side tree arrays are mutated after training
        # (refit): the device-resident stacks are then stale and the
        # batched device predict must not serve from them
        self._device_stale = False
        self.best_iteration = -1
        self.models: List[Tree] = []
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.max_feature_idx = 0
        self.objective_str = "regression"
        self.average_output = False
        self._train_data_name = "training"
        self._attrs: Dict[str, str] = {}
        self._datasets_freed = False
        # reference QualityProfile attached by engine.train under
        # quality=on; save_model persists it beside the model file
        # (docs/MODEL_MONITORING.md)
        self.quality_profile = None

        if model_file is not None:
            with open(model_file) as f:
                self._load_from_string(f.read())
            return
        if model_str is not None:
            self._load_from_string(model_str)
            return
        if train_set is None:
            return

        # the reference python package accepts a lazy Dataset here
        # (basic.py Booster.__init__ constructs it); engine.train
        # passes an already-constructed core
        if hasattr(train_set, "construct") and \
                callable(train_set.construct):
            train_set = train_set.construct(self.config)

        from .boosting import create_boosting
        self.gbdt = create_boosting(self.config, train_set,
                                    custom_objective=custom_objective)
        self.average_output = getattr(self.gbdt, "average_output", False)
        self.models = self.gbdt.models      # shared list, grows in place
        self.num_class = self.config.num_class
        self.num_tree_per_iteration = self.config.num_tree_per_iteration
        self.feature_names = train_set.feature_names
        self.feature_infos = train_set.feature_infos()
        self.max_feature_idx = train_set.num_total_features - 1
        self.pandas_categorical = getattr(train_set, "pandas_categorical",
                                          None)
        self.objective_str = self._objective_to_string()
        if init_model is not None:
            base = (Booster(model_file=init_model)
                    if isinstance(init_model, str) else init_model)
            self._continue_from(base, train_set)

    # ------------------------------------------------------------------
    def _objective_to_string(self) -> str:
        o = self.config.objective
        if o == "binary":
            return f"binary sigmoid:{self.config.sigmoid:g}"
        if o in ("multiclass", "multiclassova"):
            s = f"{o} num_class:{self.config.num_class}"
            if o == "multiclassova":
                s += f" sigmoid:{self.config.sigmoid:g}"
            return s
        if o == "regression" and self.config.reg_sqrt:
            return "regression sqrt"
        if o == "lambdarank":
            return "lambdarank"
        return o

    # ------------------------------------------------------------------
    def _continue_from(self, base: "Booster", train_set: Dataset) -> None:
        """Continued training: seed scores with the old model's
        predictions (reference boosting.cpp:44-60 + gbdt.h MergeFrom)."""
        import jax.numpy as jnp
        raw = train_set._raw_data
        if raw is None:
            Log.fatal("Continued training (init_model) requires raw "
                      "data on the Dataset — construct it with "
                      "free_raw_data=False (reference semantics; "
                      "two_round streaming datasets never materialize "
                      "the matrix and cannot continue training)")
        base._sync_models()
        pred = base.predict(raw, raw_score=True)
        pred = pred.reshape(self.num_class, train_set.num_data) \
            if pred.ndim > 1 and self.num_class > 1 else \
            pred.reshape(1, -1) if pred.ndim == 1 else pred.T
        pad = self.gbdt.grower.n_padded - train_set.num_data
        pred = np.pad(pred.astype(np.float32), ((0, 0), (0, pad)))
        self.gbdt.scores = self.gbdt.scores + jnp.asarray(pred)
        for t in base.models:
            self.models.append(t)
            # register foreign trees in the lazy-materialization
            # bookkeeping so flush_models() indexes stay aligned
            self.gbdt._tree_scale.append(1.0)
            self.gbdt._applied_scale.append(1.0)
            self.gbdt._scale_offset += 1
        # note: models list order => merged model predicts old + new trees

    # ------------------------------------------------------------------
    def num_feature(self) -> int:
        """reference c_api LGBM_BoosterGetNumFeature."""
        return self.max_feature_idx + 1

    def feature_name(self) -> List[str]:
        """reference c_api LGBM_BoosterGetFeatureNames."""
        return list(self.feature_names)

    # ------------------------------------------------------------------
    def reset_training_data(self, train_set: Dataset) -> None:
        """Swap the training dataset, keeping the trained model
        (reference c_api.cpp ResetTrainingData): the new data must have
        the same feature count; existing trees' predictions seed the
        new training scores exactly like continued training."""
        from .boosting import create_boosting
        self._sync_models()
        old = None
        if self.models:
            old = Booster()
            old.config = self.config
            for k in ("num_class", "num_tree_per_iteration",
                      "objective_str", "average_output", "feature_names",
                      "feature_infos", "max_feature_idx"):
                setattr(old, k, getattr(self, k))
            old.models = list(self.models)
        nf = train_set.num_total_features if hasattr(
            train_set, "num_total_features") else train_set.num_feature()
        if self.models and nf != self.max_feature_idx + 1:
            Log.fatal("reset_training_data: feature count mismatch "
                      f"({nf} vs model's {self.max_feature_idx + 1})")
        old_iter = self.current_iteration
        self.gbdt = create_boosting(self.config, train_set)
        self.models = self.gbdt.models
        self.feature_names = train_set.feature_names
        self.feature_infos = train_set.feature_infos()
        self.max_feature_idx = nf - 1
        if old is not None and old.models:
            self._continue_from(old, train_set)
            # the reference keeps GetCurrentIteration across
            # ResetTrainingData (the model is retained)
            self.gbdt.iter_ = old_iter
        self._device_stale = False

    # ------------------------------------------------------------------
    def update(self, train_set=None, fobj=None) -> bool:
        if self.gbdt is None or self.gbdt.train_set is None:
            # reference contract: no training session (file-loaded
            # model, or free_dataset() ended it)
            Log.fatal("Cannot update: booster has no training session "
                      "(file-loaded model or datasets were freed)")
        if fobj is not None:
            score = self._current_train_scores()
            grad, hess = fobj(score, self.gbdt.train_set)
            return self.gbdt.train_one_iter(grad, hess)
        return self.gbdt.train_one_iter()

    def rollback_one_iter(self):
        if self.gbdt is None:
            Log.fatal("Cannot rollback: booster has no training "
                      "session (file-loaded model or datasets were "
                      "freed)")
        self.gbdt.rollback_one_iter()
        # a later update() can restore the same tree COUNT with a
        # different tree — a length-keyed stack cache would serve the
        # rolled-back ensemble
        self._raw_stack_cache = None
        self._predictor_cache = None

    def _sync_models(self) -> None:
        """Materialize any device-resident trees into self.models
        (one batched transfer; no-op for file-loaded models)."""
        if self.gbdt is not None:
            self.gbdt.flush_models()

    @property
    def current_iteration(self) -> int:
        return self.gbdt.iter_ if self.gbdt else \
            len(self.models) // max(self.num_tree_per_iteration, 1)

    def num_trees(self) -> int:
        self._sync_models()
        return len(self.models)

    def _current_train_scores(self) -> np.ndarray:
        s = np.asarray(self.gbdt.scores[:, :self.gbdt.num_data])
        if self.num_tree_per_iteration == 1:
            return s[0]
        return s.T.reshape(-1, order="F")  # class-major like reference

    # ------------------------------------------------------------------
    def predict(self, data: np.ndarray, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0,
                device: Optional[bool] = None) -> np.ndarray:
        """Prediction on raw features (reference
        gbdt_prediction.cpp:9-100; SHAP via tree.PredictContrib;
        margin-based early stop prediction_early_stop.cpp:13-80).

        ``device``: None (auto) routes predictions through the
        accelerator when one is attached — large in-session batches
        through the binned scan (input binned with the training
        mappers, device-resident trees evaluated in one scanned
        program, the TPU analog of the reference's OMP batch predict,
        c_api.cpp:200), and everything else — any batch size,
        serving-shaped micro-batches included — through the bucketed
        level-descent serving predictor (_ServingPredictor: batch
        sizes round up to power-of-two buckets so small batches reuse
        one compiled program).  The device paths accumulate in float32
        (the host walk uses float64), so raw scores may differ at
        ~1e-6 relative.  True forces the device path, False forces the
        host walk."""
        from .basic import _is_sparse, _to_matrix
        if _is_sparse(data):
            # CSR prediction without whole-matrix densify (reference
            # c_api.h:574 PredictForCSR walks per-row sparse features;
            # the TPU answer keeps the batched vectorized walk but
            # stages dense chunks).  Wide-sparse matrices first drop to
            # the model's USED feature columns — a model over 10^6
            # columns references only the features it ever split on, so
            # staging is bounded by used width, not matrix width, and
            # chunks stay large.  Absent sparse entries are 0.0 either
            # way, so this is exact.
            csr = data.tocsr()
            width = csr.shape[1]
            compact = self._compact_for_sparse(num_iteration, width) \
                if not pred_contrib else None
            if compact is not None:
                bst, used_cols = compact
                csr = csr[:, used_cols]
                width = used_cols.size
                num_iteration = -1  # models already sliced
            else:
                bst = self
            chunk = max(1, (128 << 20) // max(8 * width, 1))
            parts = [bst.predict(
                np.asarray(csr[i:i + chunk].todense(), dtype=np.float64),
                num_iteration=num_iteration, raw_score=raw_score,
                pred_leaf=pred_leaf, pred_contrib=pred_contrib,
                pred_early_stop=pred_early_stop,
                pred_early_stop_freq=pred_early_stop_freq,
                pred_early_stop_margin=pred_early_stop_margin,
                device=device)
                for i in range(0, csr.shape[0], chunk)]
            return np.concatenate(parts, axis=0)
        # pandas categoricals encode against the TRAIN-time category
        # lists so reordered/unseen predict-time categories map right
        data = _to_matrix(data, getattr(self, "pandas_categorical", None))
        if data.ndim == 1:
            data = data[None, :]
        n = data.shape[0]
        k = max(self.num_tree_per_iteration, 1)

        if not pred_leaf and not pred_contrib and not pred_early_stop:
            if self._can_device_predict(n, num_iteration, device):
                # in-session single-class fast path: binned device scan
                raw = self._device_predict_raw(data, num_iteration)[:, None]
                if not raw_score and not self.average_output:
                    raw = self._convert_output(raw)
                return raw[:, 0]
            if self._can_device_predict_loaded(n, num_iteration, device):
                # every OTHER model kind (file-loaded, multiclass, DART
                # -renormalized, init_model-merged, RF): raw-feature
                # stacked walk (reference c_api.cpp:177-211 batch
                # predict covers all models; so does this)
                raw, used = self._device_predict_loaded(data,
                                                        num_iteration)
                return self._finish_device_scores(raw, used,
                                                  raw_score=raw_score)

        models = self._used_models(num_iteration)

        if pred_leaf:
            out = np.zeros((n, len(models)), dtype=np.int32)
            for i, t in enumerate(models):
                out[:, i] = t.predict_leaf(data)
            return out
        if pred_contrib:
            from .shap import predict_contrib
            return predict_contrib(self, data, models)

        raw = np.zeros((n, k), dtype=np.float64)
        if pred_early_stop and not self.average_output:
            # rows whose margin already exceeds the threshold skip the
            # remaining trees, checked every pred_early_stop_freq trees
            # (reference prediction_early_stop.cpp: binary |score|,
            # multiclass top-2 gap)
            active = np.ones(n, dtype=bool)
            for i, t in enumerate(models):
                if not active.any():
                    break
                raw[active, i % k] += t.predict(data[active])
                if (i + 1) % (pred_early_stop_freq * k) == 0:
                    if k == 1:
                        margin = np.abs(raw[:, 0])
                    else:
                        part = np.partition(raw, k - 2, axis=1)
                        margin = part[:, -1] - part[:, -2]
                    active &= margin < pred_early_stop_margin
        else:
            for i, t in enumerate(models):
                raw[:, i % k] += t.predict(data)
        raw = self._add_init_and_average(raw, len(models))
        if not raw_score and not self.average_output:
            # RF leaf outputs are already in converted space
            raw = self._convert_output(raw)
        return raw[:, 0] if k == 1 else raw

    def _compact_for_sparse(self, num_iteration: int, width: int):
        """Used-feature compaction for wide-sparse prediction: a
        shallow booster clone whose trees index a dense matrix of ONLY
        the split-on features.  Returns (clone, used_column_ids) or
        None when compaction wouldn't pay (narrow input, empty model,
        or most columns used)."""
        import copy
        self._sync_models()
        models = self._used_models(num_iteration)
        feats = [t.split_feature for t in models if t.num_leaves > 1]
        if not feats:
            return None
        used = np.unique(np.concatenate(feats)).astype(np.int64)
        if used.size == 0 or used.size * 2 >= width:
            return None
        remap = np.zeros(width, dtype=np.int32)
        remap[used] = np.arange(used.size, dtype=np.int32)
        bst = copy.copy(self)
        bst.gbdt = None          # raw-feature walk only (host / stacked)
        bst.best_iteration = 0   # models below are already sliced
        bst.models = []
        for t in models:
            ct = copy.copy(t)
            if t.num_leaves > 1:
                ct.split_feature = remap[t.split_feature]
            bst.models.append(ct)
        bst.max_feature_idx = int(used.size) - 1
        bst._raw_stack_cache = None
        bst._predictor_cache = None
        bst._device_stale = False
        return bst, used

    def _resolve_tree_count(self, total: int, num_iteration: int) -> int:
        """Shared num_iteration/best_iteration -> tree-count resolution
        (used by both the host and device predict paths so they can
        never slice different counts)."""
        k = max(self.num_tree_per_iteration, 1)
        if num_iteration is None or num_iteration <= 0:
            if self.best_iteration > 0:
                num_iteration = self.best_iteration
            else:
                return total
        return min(total, num_iteration * k)

    def _n_used_trees(self, num_iteration: int) -> int:
        total = (len(self.gbdt.device_trees) if self.gbdt is not None
                 else len(self.models))
        return self._resolve_tree_count(total, num_iteration)

    def _can_device_predict(self, n: int, num_iteration: int,
                            device: Optional[bool]) -> bool:
        """Batch device predict is valid for single-class in-session
        models with uniform tree scaling (no DART renorm, no foreign
        init_model trees, not RF averaging)."""
        if device is False or self.gbdt is None or self._device_stale:
            return False
        g = self.gbdt
        ok = (self.num_tree_per_iteration == 1
              and not self.average_output
              and g._scale_offset == 0
              and len(g.device_trees) > 0
              and all(s == 1.0 for s in g._tree_scale))
        if not ok:
            return False
        if device is True:
            return True
        import jax
        n_trees = self._n_used_trees(num_iteration)
        return (jax.default_backend() in ("tpu", "axon")
                and n * n_trees >= 2_000_000)

    def _device_predict_raw(self, data: np.ndarray,
                            num_iteration: int) -> np.ndarray:
        """Raw scores via the accelerator: bin the input against the
        training mappers, then accumulate a lax.scan of predict_binned
        over the device-resident tree stacks."""
        import jax
        import jax.numpy as jnp

        g = self.gbdt
        gr = g.grower
        cfg = g.config
        vcore = Dataset.from_matrix(np.asarray(data, dtype=np.float64),
                                    config=cfg, reference=g.train_set)
        vbins = jnp.asarray(vcore.group_bins)
        n_trees = self._n_used_trees(num_iteration)
        shrinks = g._tree_shrink[:n_trees]

        acc = _acc_fn()

        def acc_jit(total, part, sh):
            return acc(total, part, sh, vbins, gr.f_group, gr.g2f_lut,
                       gr.f_missing, gr.f_default_bin, gr.f_num_bin,
                       max_steps=cfg.num_leaves,
                       packed_groups=gr.pack_P)
        # iter-0 trained in session => the boost_from_average bias is
        # NOT folded into the device trees (flush folds it host-side)
        total = jnp.full(vbins.shape[0], np.float32(g.init_score))
        i = 0
        entries = g.device_trees[:n_trees]
        while i < len(entries):
            e = entries[i]
            if isinstance(e, tuple) and e and e[0] in ("stackref",
                                                       "recref"):
                stack = e[1]
                j0 = e[2]
                j1 = j0
                while (i + (j1 - j0) + 1 < len(entries)
                       and isinstance(entries[i + (j1 - j0) + 1], tuple)
                       and entries[i + (j1 - j0) + 1][0] == e[0]
                       and entries[i + (j1 - j0) + 1][1] is stack
                       and entries[i + (j1 - j0) + 1][2] == j1 + 1
                       and entries[i + (j1 - j0) + 1][3:] == e[3:]):
                    j1 += 1
                count = j1 - j0 + 1
                if e[0] == "recref":
                    # packed-carry chunk: unpack the record rows on
                    # device (static slices + bitcasts, no gathers)
                    from .ops.predict import unpack_tree_records_device
                    part = unpack_tree_records_device(
                        stack[j0:j0 + count, e[3]], cfg.num_leaves,
                        gr.max_feature_bin)
                else:
                    part = jax.tree_util.tree_map(
                        lambda x: x[j0:j0 + count], stack)
                sh = jnp.asarray(np.asarray(
                    shrinks[i:i + count], np.float32))
                total = acc_jit(total, part, sh)
                i += count
            else:
                part = jax.tree_util.tree_map(lambda x: x[None], e)
                sh = jnp.asarray(np.asarray(shrinks[i:i + 1], np.float32))
                total = acc_jit(total, part, sh)
                i += 1
        return np.asarray(total)

    def _can_device_predict_loaded(self, n: int, num_iteration: int,
                                   device: Optional[bool]) -> bool:
        """Raw-feature stacked device predict: valid for any model with
        host trees (loaded, multiclass, DART, init_model, RF)."""
        if device is False:
            return False
        total = len(self.models) or (
            len(self.gbdt.device_trees) if self.gbdt is not None else 0)
        if total == 0:
            return False
        if device is True:
            return True
        import jax
        if jax.default_backend() not in ("tpu", "axon"):
            return False
        if self._predict_impl() != "scan" \
                and str(getattr(self.config, "predict_bucket", "auto")
                        ).lower() not in ("off", "false", "0"):
            # bucketed serving predictor: small batches reuse the
            # bucket's compiled program, so serving-shaped traffic
            # routes to the accelerator at ANY batch size (the old
            # n*trees floor existed to amortize per-shape compiles)
            return True
        n_trees = self._resolve_tree_count(total, num_iteration)
        return n * n_trees >= 2_000_000

    def _predict_impl(self) -> str:
        k = str(getattr(self.config, "predict_kernel", "auto")).lower()
        return "level" if k in ("auto", "") else k

    @staticmethod
    def _predict_device():
        """The CURRENT default device (thread-local: the serving lane
        pool pins each lane's worker via ``jax.default_device``), or
        None outside any pinning context.  Part of the serving
        predictor cache key so each lane device gets its own resident
        ensemble stack."""
        import jax
        try:
            return jax.config.jax_default_device
        except AttributeError:
            return None

    def _serving_predictor(self, count: int) -> _ServingPredictor:
        """Per-(model revision, tree count, pinned device) serving
        predictor cache — the ensemble stack uploads once per lane
        device; compiled programs are shared process-wide by the
        module-level jit underneath."""
        cache = getattr(self, "_predictor_cache", None)
        if cache is None or cache[0] != len(self.models):
            cache = (len(self.models), {})
            self._predictor_cache = cache
        by_key = cache[1]
        key = (count, self._predict_device())
        if key not in by_key:
            by_key[key] = _ServingPredictor(
                self.models[:count],
                max(self.num_tree_per_iteration, 1), self.config)
        return by_key[key]

    def warm_predictor(self, batch_sizes=(1,),
                       num_iteration: int = -1,
                       log: bool = False,
                       devices=None) -> "Booster":
        """Serving warm-up: compile the bucketed device predictor for
        the given batch sizes at deploy time instead of on the first
        request (with compile_cache_dir wired this is a disk hit in
        later processes).  Drives the serving predictor DIRECTLY —
        predict() routing would send an in-session booster's call
        through the binned scan instead, warming the wrong programs.
        Wired to `predict_warm_buckets` in engine.train(); the CLI
        predict/serve tasks pass ``log=True`` so deploy scripts see
        the per-bucket warm compile wall before taking traffic.

        ``devices`` (an iterable of jax devices, or None entries for
        the unpinned default) warms every listed device's buckets —
        the lane-pool fix: warming only the default device would
        leave lanes 2..N eating a cold compile on their first
        request.  None keeps the single default-device warm."""
        import contextlib
        import time
        self._sync_models()
        if not self.models:
            return self
        count = self._resolve_tree_count(len(self.models), num_iteration)
        if count == 0 or self._predict_impl() == "scan":
            return self
        f = self.max_feature_idx + 1
        devs = tuple(devices) if devices else (None,)
        for dev in devs:
            if dev is not None:
                import jax
                ctx = jax.default_device(dev)
            else:
                ctx = contextlib.nullcontext()
            with ctx:
                # fetched INSIDE the device context: the per-device
                # cache key pins this lane's resident stack
                pred = self._serving_predictor(count)
                for b in batch_sizes:
                    m = max(int(b), 1)
                    t0 = time.perf_counter()
                    pred(np.zeros((m, f)))
                    if log:
                        bucket = pred._bucket(m, pred._chunk_cap(2 * f))
                        Log.info(
                            f"warm_predictor: batch {m} -> bucket "
                            f"{bucket}"
                            + (f" on {dev}" if dev is not None else "")
                            + " warmed in "
                            f"{(time.perf_counter() - t0) * 1e3:.1f} ms")
        return self

    def _device_predict_loaded(self, data: np.ndarray,
                               num_iteration: int):
        """Raw scores via the ensemble-vectorized level descent (or the
        legacy per-tree stacked walk when predict_kernel=scan).
        Returns ((n, k) float64 raw scores, used tree count).
        Accumulation is float32 (documented device-predict precision);
        decisions match the host walk exactly via the two-float
        threshold compare.  num_iteration resolves through the SAME
        _resolve_tree_count as the host path, so both paths always
        slice identical tree counts."""
        self._sync_models()
        count = self._resolve_tree_count(len(self.models), num_iteration)
        k = max(self.num_tree_per_iteration, 1)
        if count == 0:
            return np.zeros((data.shape[0], k)), 0
        if self._predict_impl() == "scan":
            return self._device_predict_scan(data, count, k), count
        return self._serving_predictor(count)(data), count

    def _device_predict_scan(self, data: np.ndarray, count: int,
                             k: int) -> np.ndarray:
        """Legacy per-tree lax.scan walk (predict_kernel=scan A/B)."""
        import jax
        import jax.numpy as jnp

        from .ops.predict import (predict_raw_ensemble, split_hi_lo,
                                  stack_host_trees)

        cache = getattr(self, "_raw_stack_cache", None)
        if cache is None or cache[0] != len(self.models):
            cache = (len(self.models), stack_host_trees(self.models))
            self._raw_stack_cache = cache
        stack = cache[1]
        if count < len(self.models):
            stack = jax.tree_util.tree_map(lambda x: x[:count], stack)
        cls = jnp.arange(count, dtype=jnp.int32) % k
        Xhi, Xlo = split_hi_lo(data)
        out = predict_raw_ensemble(
            stack, jnp.asarray(Xhi), jnp.asarray(Xlo), cls,
            jnp.zeros((k, data.shape[0]), jnp.float32))
        return np.asarray(out).T.astype(np.float64)

    def _used_models(self, num_iteration: int) -> List[Tree]:
        self._sync_models()
        return self.models[:self._resolve_tree_count(len(self.models),
                                                     num_iteration)]

    def _finish_device_scores(self, raw: np.ndarray, used: int,
                              raw_score: bool = False) -> np.ndarray:
        """Host-side finish of a device raw-score block: RF
        averaging, objective conversion, single-class squeeze — the
        ONE post-dispatch pipeline shared by ``predict()``'s
        level-descent route and the serving co-batcher's per-model
        segment finish, so a fused dispatch's slice goes through
        byte-identical postprocessing to a direct predict."""
        k = max(self.num_tree_per_iteration, 1)
        raw = self._add_init_and_average(raw, used)
        if not raw_score and not self.average_output:
            raw = self._convert_output(raw)
        return raw[:, 0] if k == 1 else raw

    def _add_init_and_average(self, raw, num_models):
        if self.average_output and num_models:
            raw = raw / (num_models // max(self.num_tree_per_iteration, 1))
        return raw

    def _convert_output(self, raw: np.ndarray) -> np.ndarray:
        obj = self.objective_str.split()[0] if self.objective_str else ""
        obj = canonical_objective(obj)
        if obj == "binary":
            m = re.search(r"sigmoid:([0-9.eE+-]+)", self.objective_str)
            sig = float(m.group(1)) if m else 1.0
            return 1.0 / (1.0 + np.exp(-sig * raw))
        if obj == "multiclass":
            e = np.exp(raw - raw.max(axis=1, keepdims=True))
            return e / e.sum(axis=1, keepdims=True)
        if obj == "multiclassova":
            m = re.search(r"sigmoid:([0-9.eE+-]+)", self.objective_str)
            sig = float(m.group(1)) if m else 1.0
            return 1.0 / (1.0 + np.exp(-sig * raw))
        if obj in ("poisson", "gamma", "tweedie"):
            return np.exp(raw)
        if obj == "regression" and "sqrt" in self.objective_str:
            return np.sign(raw) * raw * raw
        if obj == "cross_entropy":
            return 1.0 / (1.0 + np.exp(-raw))
        if obj == "cross_entropy_lambda":
            return np.log1p(np.exp(raw))
        return raw

    # ------------------------------------------------------------------
    def _n_train_eval_rows(self) -> int:
        """gbdt emits training metric rows FIRST; datasets are told
        apart by position, never by name (a valid set may be literally
        named 'training')."""
        if self.gbdt is None:
            return 0
        return sum(len(m.names()) for m in self.gbdt.train_metrics)

    def eval(self) -> List:
        out = self.gbdt.eval_metrics() if self.gbdt else []
        if self._train_data_name != "training":
            k = self._n_train_eval_rows()
            out = [(self._train_data_name, m, v, b) if i < k
                   else (d, m, v, b)
                   for i, (d, m, v, b) in enumerate(out)]
        return out

    def eval_train(self) -> List:
        """reference basic.py Booster.eval_train: training-set metric
        rows only (valid-set metrics are not computed)."""
        if self.gbdt is None:
            if self._datasets_freed:
                Log.fatal("Booster datasets were freed (free_dataset) "
                          "— cannot evaluate training metrics")
            return []
        if not self.gbdt.train_metrics:
            self.gbdt.add_train_metrics()
        out = self.gbdt.eval_metrics("train")
        return [(self._train_data_name, m, v, b)
                for (_d, m, v, b) in out]

    def eval_valid(self) -> List:
        """reference basic.py Booster.eval_valid: validation rows only
        (training metrics are not computed)."""
        return self.gbdt.eval_metrics("valid") if self.gbdt else []

    def add_valid(self, data, name: str) -> "Booster":
        """reference basic.py Booster.add_valid.  Unconstructed lazy
        datasets are bin-aligned to the training mappers automatically
        (the reference package calls set_reference in train(); a valid
        set binned with its OWN mappers would evaluate trees whose
        thresholds live in train bin space — silently wrong)."""
        if self.gbdt is None:
            Log.fatal("Cannot add validation data to a booster without "
                      "a training session (file-loaded model)")
        if hasattr(data, "construct_aligned"):
            core = data.construct_aligned(self.gbdt.train_set,
                                          self.config)
        elif hasattr(data, "construct"):
            core = data.construct(self.config)
        else:
            core = data
        self.gbdt.add_valid(core, name)
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        """reference basic.py Booster.set_train_data_name: the label
        eval() reports for the training rows."""
        self._train_data_name = name
        return self

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """reference basic.py Booster.reset_parameter — learning_rate
        plus plain config scalars (the surface
        LGBM_BoosterResetParameter forwards here)."""
        if "learning_rate" in params and self.gbdt is not None:
            self.gbdt.shrinkage_rate = float(params["learning_rate"])
        for k, v in params.items():
            if k != "learning_rate" and hasattr(self.config, k):
                cur = getattr(self.config, k)
                try:
                    if isinstance(cur, bool):
                        # bool('false') is True — parse string forms
                        setattr(self.config, k, str(v).lower()
                                in ("1", "true", "yes", "on"))
                    else:
                        setattr(self.config, k, type(cur)(v))
                except (TypeError, ValueError):
                    pass
        return self

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """reference basic.py Booster.get_leaf_output."""
        self._sync_models()
        return float(self.models[int(tree_id)].leaf_value[int(leaf_id)])

    def attr(self, key: str) -> Optional[str]:
        """reference basic.py Booster.attr: free-form string
        attributes (python-side, like the reference)."""
        return self._attrs.get(key)

    def set_attr(self, **kwargs) -> "Booster":
        """reference basic.py Booster.set_attr: value None deletes."""
        for k, v in kwargs.items():
            if v is None:
                self._attrs.pop(k, None)
            else:
                self._attrs[k] = str(v)
        return self

    def free_dataset(self) -> "Booster":
        """reference basic.py Booster.free_dataset: ACTUALLY release
        the training/validation state — the grower holds the binned
        device matrix and padded score arrays (GBs at HIGGS scale), so
        dropping only the dataset handle would free almost nothing.
        Models are flushed to host first; prediction still works
        (host walk / raw-feature stacked device path); further
        update() calls error."""
        if self.gbdt is not None:
            self._sync_models()
            self.best_iteration = max(self.best_iteration,
                                      self.gbdt.best_iteration)
            self.gbdt = None
            self._device_stale = True
            self._datasets_freed = True
        return self

    def free_network(self) -> "Booster":
        """reference basic.py Booster.free_network (socket rendezvous
        has no TPU analog — see LGBM_NetworkFree)."""
        return self

    def set_network(self, machines, local_listen_port: int = 12400,
                    listen_time_out: int = 120,
                    num_machines: int = 1) -> "Booster":
        """reference basic.py Booster.set_network: accepted for call
        compatibility; multi-host setup goes through
        jax.distributed.initialize + mesh_shape (warns like
        LGBM_NetworkInit)."""
        from .capi import LGBM_NetworkInit
        LGBM_NetworkInit(machines if isinstance(machines, str)
                         else ",".join(machines), local_listen_port,
                         listen_time_out, num_machines)
        return self

    # ------------------------------------------------------------------
    def save_model(self, filename: str, num_iteration: int = -1) -> None:
        text = self.model_to_string(num_iteration)
        with open(filename, "w") as f:
            f.write(text)
        prof = getattr(self, "quality_profile", None)
        if prof is not None:
            from .quality import model_fingerprint, profile_path
            if model_fingerprint(text) == prof.fingerprint:
                # the profile is bound to the FULL model it was built
                # from — persist it beside the file so a later
                # task=serve can arm drift monitors from disk
                path = prof.save(profile_path(filename))
                Log.info(f"quality profile saved to {path}")
            else:
                # e.g. a num_iteration-sliced save: the written text
                # is not the profiled model — writing the sidecar
                # would trip the fingerprint refusal at serve time
                Log.debug("quality profile not saved beside "
                          f"{filename}: the written model text does "
                          "not match the profiled model (sliced "
                          "save?)")

    def model_to_string(self, num_iteration: int = -1) -> str:
        """reference gbdt_model_text.cpp:235-315 SaveModelToString."""
        models = self._used_models(num_iteration)
        out = ["tree", f"version={MODEL_VERSION}",
               f"num_class={self.num_class}",
               f"num_tree_per_iteration={self.num_tree_per_iteration}",
               "label_index=0",
               f"max_feature_idx={self.max_feature_idx}",
               f"objective={self.objective_str}"]
        if self.average_output:
            out.append("average_output")
        out.append("feature_names=" + " ".join(self.feature_names))
        out.append("feature_infos=" + " ".join(self.feature_infos))
        tree_strs = []
        for i, t in enumerate(models):
            tree_strs.append(f"Tree={i}\n{t.to_string()}\n")
        out.append("tree_sizes=" + " ".join(str(len(s)) for s in tree_strs))
        out.append("")
        text = "\n".join(out) + "\n" + "".join(tree_strs)
        # feature importances footer
        imp = self.feature_importance("split", num_iteration)
        pairs = [(int(v), self.feature_names[i]) for i, v in enumerate(imp)
                 if v > 0]
        pairs.sort(key=lambda p: -p[0])
        text += "\nfeature importances:\n"
        for v, name in pairs:
            text += f"{name}={v}\n"
        if getattr(self, "pandas_categorical", None):
            # trailing mapping line, like the reference python package
            import json as _json
            text += "\npandas_categorical:%s\n" % _json.dumps(
                self.pandas_categorical, default=str)
        return text

    # ------------------------------------------------------------------
    def _load_from_string(self, text: str) -> None:
        """reference gbdt_model_text.cpp:317+ LoadModelFromString."""
        self.pandas_categorical = None
        for line in reversed(text.rstrip().splitlines()[-3:]):
            if line.startswith("pandas_categorical:"):
                import json as _json
                try:
                    self.pandas_categorical = _json.loads(
                        line[len("pandas_categorical:"):])
                except ValueError:
                    pass
                text = text[:text.rfind("pandas_categorical:")]
                break
        header, _, rest = text.partition("Tree=0")
        kv = {}
        for line in header.splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        self.num_class = int(kv.get("num_class", "1"))
        self.num_tree_per_iteration = int(
            kv.get("num_tree_per_iteration", "1"))
        self.max_feature_idx = int(kv.get("max_feature_idx", "0"))
        self.objective_str = kv.get("objective", "regression")
        self.feature_names = kv.get("feature_names", "").split()
        self.feature_infos = kv.get("feature_infos", "").split()
        self.average_output = "average_output" in header.splitlines()
        self.models = []
        if not rest:
            return
        blocks = re.split(r"Tree=\d+\n", "Tree=0" + rest)
        for block in blocks:
            block = block.strip()
            if not block or block.startswith("feature importances"):
                continue
            block = block.split("\nfeature importances")[0]
            if "num_leaves" not in block:
                continue
            self.models.append(Tree.from_string(block))

    # ------------------------------------------------------------------
    def dump_model(self, num_iteration: int = -1) -> Dict[str, Any]:
        """JSON model dump (reference gbdt_model_text.cpp:20-180
        DumpModel / Tree::ToJSON)."""
        models = self._used_models(num_iteration)

        def node_json(tree: Tree, node: int):
            if node < 0:
                leaf = -node - 1
                return {"leaf_index": leaf,
                        "leaf_value": float(tree.leaf_value[leaf]),
                        "leaf_count": int(tree.leaf_count[leaf])}
            dt = int(tree.decision_type[node])
            is_cat = bool(dt & 1)
            mtype = {0: "None", 1: "Zero", 2: "NaN"}[(dt >> 2) & 3]
            out = {
                "split_index": int(node),
                "split_feature": int(tree.split_feature[node]),
                "split_gain": float(tree.split_gain[node]),
                "threshold": float(tree.threshold[node]),
                "decision_type": "==" if is_cat else "<=",
                "default_left": bool(dt & 2),
                "missing_type": mtype,
                "internal_value": float(tree.internal_value[node]),
                "internal_count": int(tree.internal_count[node]),
                "left_child": node_json(tree, int(tree.left_child[node])),
                "right_child": node_json(tree, int(tree.right_child[node])),
            }
            if is_cat:
                ci = int(tree.threshold[node])
                lo, hi = tree.cat_boundaries[ci], tree.cat_boundaries[ci + 1]
                out["cat_threshold"] = list(tree.cat_threshold[lo:hi])
            return out

        return {
            "name": "tree",
            "version": MODEL_VERSION,
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": 0,
            "max_feature_idx": self.max_feature_idx,
            "objective": self.objective_str,
            "average_output": self.average_output,
            "feature_names": list(self.feature_names),
            "tree_info": [
                {"tree_index": i, "num_leaves": t.num_leaves,
                 "num_cat": t.num_cat, "shrinkage": t.shrinkage,
                 "tree_structure": node_json(
                     t, 0 if t.num_leaves > 1 else -1)}
                for i, t in enumerate(models)],
        }

    # ------------------------------------------------------------------
    def refit(self, data: np.ndarray, label: np.ndarray,
              params: Optional[Dict[str, Any]] = None) -> "Booster":
        """Refit leaf values on new data keeping the tree structures
        (reference gbdt.cpp:338-360 RefitTree + c_api refit task).
        Telemetry: wrapped in a ``refit`` span, with every leaf whose
        value was recomputed counted in ``refit_leaves_updated`` —
        the continuous lane's refit cycles are sized by it."""
        from .telemetry import TELEMETRY
        span = TELEMETRY.start_span("refit",
                                    rows=int(np.shape(data)[0]))
        try:
            return self._refit_impl(data, label, params)
        finally:
            TELEMETRY.end_span(span)

    def _refit_impl(self, data, label, params) -> "Booster":
        from .config import Config
        from .dataset import Metadata
        from .objectives import create_objective
        from .ops.split import calculate_leaf_output
        from .telemetry import TELEMETRY

        import jax.numpy as jnp  # noqa: F401  (objectives use jnp)

        params = dict(params or {})
        params.setdefault("objective", self.objective_str.split()[0])
        if self.num_tree_per_iteration > 1:
            params.setdefault("num_class", self.num_tree_per_iteration)
        config = Config.from_params(params)
        from .basic import _is_sparse
        if not _is_sparse(data):
            # sparse stays sparse — refit only reads the data through
            # predict(pred_leaf=True), which densifies in bounded chunks
            data = np.ascontiguousarray(np.asarray(data, dtype=np.float64))
        n = data.shape[0]
        objective = create_objective(config)
        meta = Metadata(n)
        meta.set_label(label)
        objective.init(meta, n)

        self._sync_models()
        k = max(self.num_tree_per_iteration, 1)
        leaf_preds = self.predict(data, pred_leaf=True)  # (n, ntrees)
        scores = np.zeros((n, k), dtype=np.float64)
        leaves_updated = 0
        for i, tree in enumerate(self.models):
            cls = i % k
            s = scores if k > 1 else scores[:, 0]
            g, h = objective.get_gradients(np.asarray(s, dtype=np.float32))
            g = np.asarray(g)
            h = np.asarray(h)
            if k > 1:
                g, h = g[:, cls], h[:, cls]
            lp = leaf_preds[:, i]
            shrink = tree.shrinkage if tree.shrinkage != 0 else 1.0
            for leaf in range(tree.num_leaves):
                mask = lp == leaf
                if not mask.any():
                    continue
                sg, sh = float(g[mask].sum()), float(h[mask].sum())
                out = float(calculate_leaf_output(
                    np.float64(sg), np.float64(sh), config.lambda_l1,
                    config.lambda_l2, config.max_delta_step))
                tree.leaf_value[leaf] = out * shrink
                tree.leaf_count[leaf] = int(mask.sum())
                leaves_updated += 1
            scores[:, cls] += tree.leaf_value[lp]
        if TELEMETRY.on:
            TELEMETRY.add("refit_leaves_updated", leaves_updated)
        # host trees diverged from the in-session device stacks;
        # invalidate every device path's cache (the serving/raw-stack
        # predictors rebuild from the refitted host trees on next use
        # — refit mutates leaf values IN PLACE, so the length-keyed
        # caches would otherwise serve stale ensembles)
        self._device_stale = True
        self._raw_stack_cache = None
        self._predictor_cache = None
        return self

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           num_iteration: int = -1) -> np.ndarray:
        """reference gbdt.h FeatureImportance."""
        models = self._used_models(num_iteration)
        n = self.max_feature_idx + 1
        imp = np.zeros(n, dtype=np.float64)
        for t in models:
            m = t.num_leaves - 1
            for i in range(m):
                f = t.split_feature[i]
                if importance_type == "split":
                    imp[f] += 1
                else:
                    imp[f] += max(t.split_gain[i], 0.0)
        return imp

    # ------------------------------------------------------------------
    def __getstate__(self):
        state = {"model_str": self.model_to_string(),
                 "best_iteration": self.best_iteration}
        return state

    def __setstate__(self, state):
        self.__init__(model_str=state["model_str"])
        self.best_iteration = state.get("best_iteration", -1)
